#!/usr/bin/env python
"""Capacity & SLO smoke for tools/t1.sh (docs/OBSERVABILITY.md
"Capacity & SLO"): the canary/error-budget loop must survive REAL
process boundaries, not just in-process tests.  One leg, real
subprocesses, one JSON line:

- a REMOTE single-engine replica is started with an injected
  always-500 fault (``DSOD_FAULTS=serve_500@1x100000`` — a crashed
  worker behind a live listener);
- a ROUTER process fronts it with the synthetic prober armed and an
  availability SLO on the model — and receives ZERO live traffic;
- the prober's canaries ride the full router→engine path, every one
  terminates bad in the router book, the SLO burn rate crosses its
  threshold, and the ``slo_avail_burn`` alert must FIRE at /alerts and
  DEGRADE the router /healthz — the "outage detected with no users"
  contract;
- /slo must stay CONSISTENT with the router's own terminal book
  (good + bad == the fleet identity's terminal count — probes are
  counted traffic under the reserved tenant, and nothing else ran);
- the capacity ledger rides the same smoke on the replica
  (serve.capacity_ledger=true): its /metrics must export
  ``dsod_capacity_mfu`` with per-program cost from the warmed
  executables.

Budget contract: every internal deadline sums under t1.sh's 600 s
wrapper, so a stall reports its OWN diagnostic instead of dying to the
outer timeout.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _get_json(url: str, timeout: float = 5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        # /healthz answers 503 with a JSON body when the whole fleet
        # is unroutable — that body IS the verdict under test.
        return json.loads(e.read().decode())


def _get_text(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _wait_port(port_file: str, proc, deadline_s: float):
    deadline = time.monotonic() + deadline_s
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            return None, f"process died before binding (rc={proc.returncode})"
        if time.monotonic() > deadline:
            return None, "never bound a port"
        time.sleep(0.25)
    with open(port_file) as f:
        return int(f.read().strip()), None


def _poll(fn, deadline_s: float, poll_s: float = 0.5):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            v = fn()
            if v:
                return v
        except Exception:  # noqa: BLE001 — endpoint mid-bind
            pass
        time.sleep(poll_s)
    return None


def smoke(out: dict) -> bool:
    replica_port_file = tempfile.mktemp(prefix="dsod_slo_rport_")
    router_port_file = tempfile.mktemp(prefix="dsod_slo_fport_")
    common = ["--device", "cpu",
              "--set", "data.image_size=32,32",
              "--set", "serve.resolution_buckets=32",
              "--set", "serve.batch_buckets=1,2",
              "--set", "serve.precision_arms=f32"]
    # Leg A: the sick replica — live listener, every /predict answers
    # an injected 500 before the engine sees it.  The capacity ledger
    # rides here so the smoke also proves the live-MFU surface on a
    # real process.
    replica = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "serve.py"),
         "--config", "minet_vgg16_ref", "--init-random",
         "--port", "0", "--port-file", replica_port_file,
         "--set", "serve.capacity_ledger=true"] + common,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 DSOD_FAULTS="serve_500@1x100000"))
    router = None
    fleet_file = tempfile.mktemp(prefix="dsod_slo_fleet_", suffix=".json")
    try:
        rport, err = _wait_port(replica_port_file, replica, 240)
        if err:
            out["replica_error"] = err
            return False
        rbase = f"http://127.0.0.1:{rport}"
        if not _poll(lambda: "ok" in _get_text(rbase + "/healthz"), 60):
            out["replica_error"] = "replica never became healthy"
            return False
        metrics = _get_text(rbase + "/metrics")
        out["replica_capacity_ok"] = (
            "dsod_capacity_mfu" in metrics
            and "dsod_capacity_program_flops" in metrics)
        # Leg B: the router — prober on, availability SLO on the model,
        # tight windows so the smoke converges in seconds (production
        # keeps hour-scale windows).
        with open(fleet_file, "w") as f:
            json.dump({
                "models": [{"name": "minet", "url": rbase}],
                "slo_objectives": ["avail:model=minet:availability"
                                   ":0.9:60"],
                "slo_burn_threshold": 2.0,
                "slo_alert_for_s": 1.0,
                "slo_alert_clear_s": 5.0,
                "prober_interval_s": 0.25,
                "prober_px": 32,
                "prober_timeout_s": 10.0,
                "retry_max_attempts": 1,
            }, f)
        router = subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "serve.py"),
             "--fleet-config", fleet_file, "--device", "cpu",
             "--port", "0", "--port-file", router_port_file],
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        fport, err = _wait_port(router_port_file, router, 120)
        if err:
            out["router_error"] = err
            return False
        fbase = f"http://127.0.0.1:{fport}"

        # ZERO live traffic from here: only canaries move.  The burn
        # alert must fire off probe failures alone.
        def burn_fired():
            snap = _get_json(fbase + "/alerts")
            return ("slo_avail_burn" in snap.get("active", [])
                    and snap) or None

        fired = _poll(burn_fired, 90)
        if not fired:
            out["router_error"] = "slo_avail_burn never fired (zero-" \
                "traffic canary detection failed)"
            return False
        slo = _get_json(fbase + "/slo")
        obj = slo["objectives"][0]
        out["slo"] = {k: obj[k] for k in
                      ("good", "bad", "budget_remaining", "burn_rate")}
        health = _get_json(fbase + "/healthz")
        out["router_healthz"] = health.get("status")
        stats = _get_json(fbase + "/stats")
        out["fleet_consistent"] = stats["fleet"]["consistent"]
        out["probe"] = stats.get("probes", {}).get("models", {}).get(
            "minet", {})
        # /slo vs the router book: probes are the ONLY traffic, none of
        # it client-fault, so SLO events must equal the router's
        # terminal count exactly.
        terminal = stats["fleet"]["terminal"]
        out["slo_matches_book"] = (obj["good"] + obj["bad"]) == terminal
        mtext = _get_text(fbase + "/metrics")
        families_ok = all(f in mtext for f in (
            "dsod_slo_burn_rate", "dsod_slo_budget_remaining",
            "dsod_probe_failed_total", "dsod_probe_dropped_total"))
        out["router_families_ok"] = families_ok
        # The verdict may read "degraded" (breaker mid-half-open cycle:
        # something still routable, the SLO alert degrades it) or
        # "unhealthy" (breaker open on the only replica: nothing
        # routable) — both are correct non-ok answers; either way the
        # body must name the burning SLO.
        ok = (out["replica_capacity_ok"] and out["fleet_consistent"]
              and out["slo_matches_book"] and families_ok
              and obj["bad"] > 0 and obj["budget_remaining"] < 0
              and health.get("status") in ("degraded", "unhealthy")
              and any("slo_avail" in a
                      for a in health.get("slo_alerts", [])))
        router.send_signal(signal.SIGTERM)
        out["router_rc"] = router.wait(timeout=90)
        replica.send_signal(signal.SIGTERM)
        out["replica_rc"] = replica.wait(timeout=90)
        return ok and out["router_rc"] == 0 and out["replica_rc"] == 0
    finally:
        for proc in (router, replica):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        for f in (replica_port_file, router_port_file, fleet_file):
            if os.path.exists(f):
                os.unlink(f)


def main(argv=None) -> int:
    argparse.ArgumentParser(description=__doc__).parse_args(argv)
    out: dict = {"metric": "slo_smoke"}
    out["ok"] = smoke(out)
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
