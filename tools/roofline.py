#!/usr/bin/env python
"""Analytic roofline for the flagship (MINet-R50 @320px) train step.

VERDICT r3 item 3: make the MFU push falsifiable BEFORE hardware.
This derives, from closed forms (no device needed):

  - per-op forward/backward FLOPs and ideal-fusion HBM bytes for every
    conv/BN/pool/resize/loss/optimizer op in the MINet-R50 train step,
  - a per-resolution-bucket roofline time  t >= max(F/peak, B/bw)
    on v5e (197 TFLOP/s dense bf16, 819 GB/s HBM),
  - predicted step time / throughput / MFU at b32/b64/b128,
    remat on/off, plain vs s2d stem, fast vs xla resize,
  - and (with ``--trace DIR``) the measured per-bucket table from a
    captured profile, aggregated by the spatial resolution parsed out
    of each HLO op's result shapes — so prediction and measurement
    meet on the same axis without any fusion-name mapping.

Cross-checks:
  - ``--xla-check`` jits the REAL train step on CPU at b4 and compares
    XLA's cost-model FLOPs against this ledger (catches hand-math rot;
    agreement within ~10% expected — XLA counts a handful of fusions
    this ledger rolls into "elementwise").

Usage:
    python tools/roofline.py                       # predictions
    python tools/roofline.py --trace tpu_results/trace --batch 64 --remat
    python tools/roofline.py --xla-check

Modeling assumptions (documented so disagreement is informative):
  - bf16 activations (2 B), f32 params/BN stats (4 B).
  - Ideal fusion: each ConvBNAct costs one read of its input and one
    write of its output; BN statistics reduce in the conv's epilogue
    (the trace's ``convert_reduce_fusion`` ops are exactly this) and
    the normalize+relu rides the consumer's read.  Real fusion is
    never better, often worse — predictions are LOWER bounds.
  - Backward per conv: dx-conv + dw-conv, each the fwd FLOP count;
    bytes: read upstream grad + saved input + weights, write grad-in
    + weight-grad.
  - ``--remat`` (the ``model.remat=true, policy=none`` config): the
    backward additionally re-runs the forward (its FLOPs and bytes are
    added to bwd) — remat trades HBM *capacity* for bandwidth+FLOPs,
    which is why b128 no-remat beat b64+remat on v5e.
  - SGD+momentum update: read param/momentum/grad, write param/
    momentum (f32) — 20 B/param, ~3 FLOPs/param.

Reference capability being modeled: the SURVEY §2.2 "Pallas where
profitable" contract — this table ranks which stages can repay a
custom kernel (HBM-bound, far from roofline) before any is written.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from dataclasses import dataclass, field

# v5e per-chip numbers (same sources as bench.py's MFU self-report).
PEAK_FLOPS = 197e12  # dense bf16 MACs*2
HBM_BW = 819e9       # bytes/s
ICI_BW = 2e11        # bytes/s — v5e 1,600 Gbps aggregate ICI per chip
#                      (same constant as utils/capacity.py's live side)
DCN_BW = 12.5e9      # bytes/s — ~100 Gbps per-host DCN NIC, the
#                      inter-host leg of a multi-pod mesh (same
#                      constant as utils/capacity.py's live side);
#                      16x slower than ICI, which is WHY the
#                      hierarchical reduction moves only 1/chips of
#                      the bytes across it

A = 2  # activation bytes (bf16)
P = 4  # param / stat / f32 bytes


@dataclass
class Op:
    name: str
    res: int          # output spatial bucket (H of the square output)
    flops: float      # forward FLOPs
    bytes: float      # forward ideal-fusion HBM bytes
    bwd_flops: float = 0.0
    bwd_bytes: float = 0.0
    params: int = 0

    def scaled(self, k: float) -> "Op":
        return Op(self.name, self.res, self.flops * k, self.bytes * k,
                  self.bwd_flops * k, self.bwd_bytes * k, self.params)


def conv(name, b, h_in, cin, cout, k=3, stride=1, res_out=None,
         bn=True) -> Op:
    """ConvBNAct closed form (NHWC, square spatial)."""
    h_out = res_out if res_out is not None else h_in // stride
    f = 2.0 * b * h_out * h_out * cout * cin * k * k
    n_in = b * h_in * h_in * cin
    n_out = b * h_out * h_out * cout
    params = cin * cout * k * k + (4 * cout if bn else cout)
    fwd_bytes = A * (n_in + n_out) + P * params
    # dx + dw convs; read g_out twice (dx, dw) + saved input, write
    # g_in + dw; BN bwd rides the same fusions (stat grads are f32
    # scalars per channel — negligible traffic).
    bwd_f = 2.0 * f
    bwd_b = A * (2 * n_out + n_in + n_in) + P * 2 * params
    return Op(name, h_out, f, fwd_bytes, bwd_f, bwd_b, params)


def eltwise(name, b, h, c, reads=1, writes=1, res=None) -> Op:
    """Pure-VPU op: residual add, pool, fast resize, activation copy."""
    n = b * h * h * c
    return Op(name, res or h, 0.0, A * n * (reads + writes),
              0.0, A * n * (reads + writes))


# Decoder upsample/merge sites of the flagship (the fused-resample
# kernel's targets).  Populated by minet_r50_ledger as a side list so
# the per-arm ledger (fmt_fused_ledger) and the predictions price the
# SAME sites.  Each fused site replaces "read the fine map, write the
# fine map" with "read the COARSE map (a quarter of the bytes), write
# the fine map" — the merge operand reads are unchanged — so every
# site saves 0.75 * n_fine * A bytes of HBM traffic, fwd and bwd (the
# transposed-resample backward reads fine / writes coarse the same
# way).


def _up_site(ops, sites, name, b, res, c, reads=1, fused=False):
    """An upsample(+merge) decoder site: ``reads`` counts the fine-res
    operand reads on the XLA path (1 = bare upsample, 2 = upsample +
    add/concat merge).  ``fused=True`` prices the Pallas fused arm."""
    n = b * res * res * c
    plain = eltwise(name, b, res, c, reads=reads)
    if not fused:
        op = plain
    else:
        bytes_ = plain.bytes - 0.75 * A * n  # coarse read, fine write
        op = Op(name, res, 0.0, bytes_, 0.0, bytes_)
    ops.append(op)
    sites.append((name, res, plain.bytes - op.bytes))
    return op


def _conv_site(ops, sites, op: Op, b: int, cout: int,
               fused: bool = False, cat_elems: float = 0.0) -> Op:
    """A DECODER ConvBNAct site (the fused-conv kernel's targets).

    ``fused=True`` prices the ``model.conv_impl=fused`` arm: the
    BN-normalize+ReLU epilogue runs on the conv's VMEM tile instead of
    a second HBM round trip over the output map (the r4 reconciliation
    shows the fine buckets do NOT get this fusion for free — 160/80 at
    3.3x/2.1x off the ideal-fusion prediction), and a conv over a
    materialized channel concat (``cat_elems`` = elements of that
    concat) reads its parts directly, saving the concat's write+read.
    FLOPs are untouched by construction (asserted by
    ``fmt_fused_conv_ledger``); the saving is counted fwd and bwd (the
    backward's mask+scale epilogue fuses into the dx kernel's read the
    same way).  Conservative: backbone convs are NOT repriced even
    though the seam routes them too — only the decoder sites the
    roofline names are claimed.
    """
    n_out = float(b) * op.res * op.res * cout
    saved = (2.0 * A * n_out + 2.0 * A * cat_elems) if fused else 0.0
    if fused:
        op = Op(op.name, op.res, op.flops, op.bytes - saved,
                op.bwd_flops, op.bwd_bytes - saved, op.params)
    ops.append(op)
    sites.append((op.name, op.res, saved))
    return op


def minet_r50_ledger(b: int, hw: int = 320, s2d: bool = False,
                     resize: str = "fast",
                     fused_sites: list | None = None,
                     conv_arm: str = "xla",
                     conv_sites: list | None = None) -> list:
    """Every op in one MINet-R50 train step (fwd reference: the module
    graph in models/minet.py + models/backbones/resnet.py).

    ``resize``: 'fast'/'xla' as before; 'fused' prices the decoder
    upsample+merge sites as the Pallas fused-resample kernel
    (model.resample_impl=fused) — ``fused_sites`` (when passed a list)
    collects (site, res, bytes saved/step) for the per-arm ledger.
    ``conv_arm``: 'xla'/'fused' — 'fused' prices the decoder ConvBNAct
    sites as the Pallas fused conv-stage kernel
    (model.conv_impl=fused; see ``_conv_site``), ``conv_sites``
    collecting (site, res, bytes saved per direction).
    """
    ops: list[Op] = []
    sites = fused_sites if fused_sites is not None else []
    csites = conv_sites if conv_sites is not None else []
    fused = resize == "fused"
    cfused = conv_arm == "fused"
    r = hw // 2  # 160 for 320

    # ---- backbone stem ----------------------------------------------
    if s2d:
        # Same bytes (reads the same image, writes the same map); the
        # contraction runs 4x4x12=192 taps vs 7x7x3=147 — nominally
        # +31% FLOPs, but the MXU packs Cin=12 4x denser than Cin=3,
        # so wall-clock compute drops ~4x where the op is MXU-limited.
        st = conv("stem_s2d", b, hw // 2, 12, 64, k=4, stride=1)
        st.bytes = A * (b * hw * hw * 3 + b * r * r * 64) + P * st.params
        ops.append(st)
    else:
        ops.append(conv("stem7x7", b, hw, 3, 64, k=7, stride=2))
    ops.append(eltwise("maxpool", b, r, 64))  # 160 -> 80

    # ---- residual stages (torchvision bottleneck counts) ------------
    # (stage, blocks, width, out, res): R50 = 3/4/6/3.
    stages = [("res2", 3, 64, 256, hw // 4), ("res3", 4, 128, 512, hw // 8),
              ("res4", 6, 256, 1024, hw // 16), ("res5", 3, 512, 2048, hw // 32)]
    cin = 64
    for name, blocks, w, cout, res_ in stages:
        for i in range(blocks):
            stride = 2 if (i == 0 and name != "res2") else 1
            h_in = res_ * stride if stride == 2 else res_
            ops.append(conv(f"{name}.b{i}.c1", b, h_in, cin if i == 0 else cout,
                            w, k=1, res_out=h_in))
            ops.append(conv(f"{name}.b{i}.c2", b, h_in, w, w, k=3,
                            stride=stride))
            ops.append(conv(f"{name}.b{i}.c3", b, res_, w, cout, k=1))
            if i == 0:
                ops.append(conv(f"{name}.proj", b, h_in, cin, cout, k=1,
                                stride=stride, bn=True))
            ops.append(eltwise(f"{name}.b{i}.add", b, res_, cout, reads=2))
        cin = cout

    # ---- AIM (one per level; width 64) ------------------------------
    feats = [(hw // 2, 64), (hw // 4, 256), (hw // 8, 512),
             (hw // 16, 1024), (hw // 32, 2048)]
    for i, (res_, c) in enumerate(feats):
        n_parts = 1 + (i > 0) + (i < 4)
        _conv_site(ops, csites, conv(f"aim{i}.cur", b, res_, c, 64),
                   b, 64, fused=cfused)
        if i > 0:
            rb, cb = feats[i - 1]
            _conv_site(ops, csites, conv(f"aim{i}.below", b, rb, cb, 64),
                       b, 64, fused=cfused)
            ops.append(eltwise(f"aim{i}.down", b, rb, 64, res=res_))
        if i < 4:
            ra, ca = feats[i + 1]
            _conv_site(ops, csites, conv(f"aim{i}.above", b, ra, ca, 64),
                       b, 64, fused=cfused)
            _up_site(ops, sites, f"aim{i}.up", b, res_, 64, fused=fused)
        # The merge conv's input IS a materialized concat on the XLA
        # arm — the fused conv+concat kernel reads the parts directly.
        _conv_site(ops, csites,
                   conv(f"aim{i}.merge", b, res_, 64 * n_parts, 64),
                   b, 64, fused=cfused,
                   cat_elems=float(b) * res_ * res_ * 64 * n_parts)

    # ---- SIM decoder (one per level, coarsest first) ----------------
    for i, (res_, _) in enumerate(reversed(feats)):
        p = f"sim{4 - i}"
        _conv_site(ops, csites, conv(f"{p}.h", b, res_, 64, 64),
                   b, 64, fused=cfused)
        _conv_site(ops, csites, conv(f"{p}.l0", b, res_, 64, 32),
                   b, 32, fused=cfused)
        ops.append(eltwise(f"{p}.lpool", b, res_ // 2, 32))
        _conv_site(ops, csites, conv(f"{p}.l2h", b, res_ // 2, 32, 64),
                   b, 64, fused=cfused)
        _up_site(ops, sites, f"{p}.hup", b, res_, 64, fused=fused)
        _conv_site(ops, csites, conv(f"{p}.h2", b, res_, 64, 64),
                   b, 64, fused=cfused)
        _conv_site(ops, csites, conv(f"{p}.h2l", b, res_, 64, 32),
                   b, 32, fused=cfused)
        _conv_site(ops, csites, conv(f"{p}.l2", b, res_ // 2, 32, 32),
                   b, 32, fused=cfused)
        # SIM's merge input concat is the fused-RESAMPLE kernel's site
        # (resample_merge mode='concat') — claimed there, NOT here.
        _conv_site(ops, csites, conv(f"{p}.merge", b, res_, 96, 64),
                   b, 64, fused=cfused)
        if i < 4:  # decoder hop up to the next (finer) level
            _up_site(ops, sites, f"{p}.declift", b, res_ * 2, 64,
                     reads=2, fused=fused)

    # ---- head + full-res logit --------------------------------------
    _conv_site(ops, csites, conv("head.c1", b, hw // 2, 64, 32),
               b, 32, fused=cfused)
    ops.append(conv("head.logit", b, hw // 2, 32, 1, bn=False))
    if fused:  # the head's 2x logit upsample rides the kernel too
        _up_site(ops, sites, "head.resize", b, hw, 1, fused=True)
    else:
        k_resize = 3.0 if resize == "xla" else 1.0  # dot_general + 2 relayouts
        ops.append(eltwise("head.resize", b, hw, 1,
                           reads=k_resize, writes=k_resize))

    # ---- loss @ full res (BCE+IoU+SSIM+CEL, f32) --------------------
    n = b * hw * hw
    ops.append(Op("loss", hw, 40.0 * n, P * 8 * n, 40.0 * n, P * 8 * n))

    # ---- optimizer (SGD+momentum, f32) ------------------------------
    n_params = sum(o.params for o in ops)
    ops.append(Op("sgd", 0, 0.0, 0.0, 3.0 * n_params, 20.0 * n_params))
    return ops


def act_capacity_gb(b, hw=320, policy: str = "none") -> float:
    """Rough live-activation footprint for the backward pass (upper
    bound — XLA frees what it can reorder around).  ``policy``:
    'none' = no remat, every op output resident; 'dots' = the
    ``remat_policy=dots`` checkpoint policy, only conv/matmul outputs
    resident (elementwise recomputed).  Against v5e's 16 GB HBM this
    predicts where the batch curve hits the capacity wall."""
    ops = minet_r50_ledger(b, hw=hw)
    n_out = 0.0
    for o in ops:
        if policy == "dots" and not o.params:
            continue
        # bytes = A*(n_in+n_out)+P*params for convs; A*n*(r+w) for
        # eltwise — recover n_out as the write half.
        writes = (o.bytes - P * o.params) / 2 if o.params else o.bytes / 2
        n_out += max(writes, 0.0)
    return n_out / 1e9


def predict(b, remat=False, s2d=False, resize="fast", hw=320,
            remat_policy="none", conv="xla"):
    ops = minet_r50_ledger(b, hw=hw, s2d=s2d, resize=resize,
                           conv_arm=conv)
    rows = {}
    tot_f = tot_b = tot_t = 0.0
    for o in ops:
        f = o.flops + o.bwd_flops
        by = o.bytes + o.bwd_bytes
        if remat:
            if remat_policy == "dots":
                # conv outputs saved; only elementwise recomputed
                if not o.params:
                    f += o.flops
                    by += o.bytes
            else:  # policy=none: bwd re-runs the whole forward
                f += o.flops
                by += o.bytes
        t = max(f / PEAK_FLOPS, by / HBM_BW)
        r = rows.setdefault(o.res, [0.0, 0.0, 0.0])
        r[0] += f
        r[1] += by
        r[2] += t
        tot_f += f
        tot_b += by
        tot_t += t
    return rows, tot_f, tot_b, tot_t


def fmt_pred(b, remat=False, s2d=False, resize="fast",
             remat_policy="none", conv="xla"):
    rows, tf, tb, tt = predict(b, remat=remat, s2d=s2d, resize=resize,
                               remat_policy=remat_policy, conv=conv)
    tag = f"on[{remat_policy}]" if remat else "off"
    out = [f"## predicted  b{b}  remat={tag}  "
           f"stem={'s2d' if s2d else 'plain'}  resize={resize}  "
           f"conv={conv}",
           "| res | GFLOPs | HBM GB | roofline ms | bound |",
           "|---|---|---|---|---|"]
    for res in sorted(rows, reverse=True):
        f, by, t = rows[res]
        bound = "HBM" if by / HBM_BW > f / PEAK_FLOPS else "MXU"
        out.append(f"| {res} | {f / 1e9:.1f} | {by / 1e9:.2f} | "
                   f"{t * 1e3:.2f} | {bound} |")
    out.append(f"| **total** | **{tf / 1e9:.1f}** | **{tb / 1e9:.2f}** | "
               f"**{tt * 1e3:.2f}** | |")
    ideal = b / tt
    mfu = tf / tt / PEAK_FLOPS
    out.append(f"roofline-ideal: {ideal:.1f} img/s/chip, MFU {mfu:.0%} "
               f"(intensity {tf / tb:.0f} FLOPs/B vs ridge "
               f"{PEAK_FLOPS / HBM_BW:.0f})")
    policy = remat_policy if remat else "none"
    if not remat or remat_policy == "dots":
        cap = act_capacity_gb(b, policy=policy if remat else "none")
        label = "dots-saved" if remat else "no-remat live"
        out.append(f"{label} activations (upper bound): "
                   f"~{cap:.1f} GB vs 16 GB v5e HBM")
    return "\n".join(out)


def fmt_fused_ledger(b: int, hw: int = 320) -> str:
    """Per-site HBM ledger for the fused-resample arm
    (``model.resample_impl=fused``): what each decoder upsample/merge
    stage saves per step vs the fast XLA path, and the falsifiable
    total the tools/tpu_agenda_r5.sh A/B legs are queued against.

    Conservative by construction: only sites the base ledger already
    prices are counted (SIM's concat-merge upsample is idealized away
    there and so claims no savings here), and the relayout copies the
    layout-stable interleave removes (tools/hlo_guard.py) are NOT
    priced — both make the prediction a lower bound.
    """
    sites: list = []
    minet_r50_ledger(b, hw=hw, resize="fused", fused_sites=sites)
    out = [f"## fused-resample ledger  b{b}@{hw}px  "
           f"(model.resample_impl=fused vs fast)",
           "| site | res | HBM bytes saved/step | ms saved (fwd+bwd) |",
           "|---|---|---|---|"]
    tot = 0.0
    for name, res, saved in sites:
        tot += saved
        out.append(f"| {name} | {res} | {saved / 1e6:.2f} MB | "
                   f"{2 * saved / HBM_BW * 1e3:.3f} |")
    out.append(f"| **total** | | **{tot / 1e6:.2f} MB** | "
               f"**{2 * tot / HBM_BW * 1e3:.3f}** |")
    _, _, _, t_fast = predict(b, hw=hw, resize="fast")
    _, _, _, t_fused = predict(b, hw=hw, resize="fused")
    out.append(f"prediction: step roofline {t_fast * 1e3:.2f} -> "
               f"{t_fused * 1e3:.2f} ms "
               f"({(1 - t_fused / t_fast):.1%} of the ideal step) — "
               f"the A/B leg must beat noise on THIS number to flip "
               f"any default")
    return "\n".join(out)


def fmt_fused_conv_ledger(b: int, hw: int = 320) -> str:
    """Per-site HBM ledger for the fused conv-stage arm
    (``model.conv_impl=fused``): what each decoder ConvBNAct saves per
    step vs the XLA arm, and the falsifiable total the
    tools/tpu_agenda_r14.sh A/B legs are queued against.

    Assumptions on record (the ledger's honesty contract): the XLA arm
    is charged one extra read+write of each decoder conv's OUTPUT map
    (the BN-normalize+ReLU epilogue the r4 trace reconciliation shows
    is NOT riding the conv fusion at the fine buckets), and one extra
    write+read of each materialized pre-conv CONCAT (AIM merges; SIM's
    merge concat belongs to the fused-resample ledger and is NOT
    double-counted).  Backbone convs route the same seam but claim
    nothing here — decoder sites only, so the total is a floor the
    prof_conv trace leg can only raise.  FLOPs invariance between the
    arms is asserted, not assumed.
    """
    csites: list = []
    ops_f = minet_r50_ledger(b, hw=hw, conv_arm="fused",
                             conv_sites=csites)
    ops_x = minet_r50_ledger(b, hw=hw)
    fx = sum(o.flops + o.bwd_flops for o in ops_x)
    ff = sum(o.flops + o.bwd_flops for o in ops_f)
    if fx != ff:
        raise AssertionError(
            f"fused-conv arm changed ledger FLOPs: {fx} != {ff} — the "
            "kernel computes the SAME convolution; a bytes-only arm "
            "must not touch the FLOP column")
    out = [f"## fused-conv ledger  b{b}@{hw}px  "
           f"(model.conv_impl=fused vs xla)",
           f"FLOPs invariant across arms: {fx / 1e9:.1f} GFLOPs both",
           "| site | res | HBM bytes saved/step | ms saved (fwd+bwd) |",
           "|---|---|---|---|"]
    tot = 0.0
    for name, res, saved in csites:
        if saved <= 0:
            continue
        tot += saved
        out.append(f"| {name} | {res} | {2 * saved / 1e6:.2f} MB | "
                   f"{2 * saved / HBM_BW * 1e3:.3f} |")
    out.append(f"| **total** | | **{2 * tot / 1e6:.2f} MB** | "
               f"**{2 * tot / HBM_BW * 1e3:.3f}** |")
    _, _, _, t_x = predict(b, hw=hw)
    _, _, _, t_f = predict(b, hw=hw, conv="fused")
    out.append(f"prediction: step roofline {t_x * 1e3:.2f} -> "
               f"{t_f * 1e3:.2f} ms "
               f"({(1 - t_f / t_x):.1%} of the ideal step) — the "
               f"ledger floor; the real target is the fine buckets' "
               f"3.3x/2.1x conv-fusion overhead, which only the "
               f"prof_conv trace leg can price.  The r14 A/B must "
               f"beat noise on THIS number to flip any default")
    return "\n".join(out)


def fmt_comm_ledger(b: int, n_dp: int = 8, bucket_mb: float = 25.0,
                    compression: str = "none", hosts: int = 1) -> str:
    """Per-step gradient-communication ledger for the flagship
    (ROADMAP item 4, round 18): the REAL param tree's leaves (abstract
    init — no arrays allocated) partitioned into the rules engine's
    backward-ordered buckets (parallel/rules.py::grad_buckets), each
    priced as a ring allreduce over ``n_dp`` replicas — wire bytes
    ``2(n-1)/n × payload`` at ``ICI_BW`` — plus the structural overlap
    estimate (every bucket except the last overlaps remaining backward
    compute) and the ZeRO per-device HBM saving.

    ``hosts > 1`` prices the hierarchical two-level schedule
    (parallel/rules.py::_hier_psum) instead: per bucket, intra-host
    reduce-scatter ((c−1)/c × payload at ICI, c = chips/host) →
    inter-host all-reduce (2(h−1)/h × payload/c at DCN — each chip
    owns 1/c of the bucket, so only that slice crosses the slow leg)
    → intra-host all-gather ((c−1)/c × payload at ICI).

    ``compression`` scales the wire bytes: bf16 halves them; int8_ef
    prices the ACHIEVABLE 1 B/elem (wire_scale 0.25) even though the
    current XLA transport psums int32 — the ledger documents the wire
    format's information content, the transport honesty note below
    keeps the gap visible.  The live twin of this table is the
    ``dsod_capacity_comm_*`` surface (utils/capacity.py::record_comm);
    the measured numbers stay tools/tpu_agenda_r18.sh predictions
    until a TPU window lands them.
    """
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from distributed_sod_project_tpu.configs import get_config
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel.rules import grad_buckets

    if hosts > 1 and n_dp % hosts:
        raise SystemExit(f"--hosts {hosts} must divide --n-dp {n_dp}")
    cfg = get_config("minet_r50_dp")
    model = build_model(cfg.model)
    # Param shapes are input-size independent for the conv zoo; a 64px
    # abstract init keeps this instant and allocation-free.
    variables = jax.eval_shape(
        lambda k, img: model.init(k, img, None, train=False),
        jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32))
    leaves = jax.tree_util.tree_leaves(variables["params"])
    shapes = [(x.shape, x.dtype) for x in leaves]
    sizes = [int(math.prod(s or (1,))) * 4 for s, _ in shapes]  # f32
    wire_scale = {"none": 1.0, "bf16": 0.5, "int8_ef": 0.25}[compression]
    buckets = grad_buckets(shapes, int(bucket_mb * 2 ** 20))
    chips = n_dp // hosts if hosts > 1 else n_dp
    out = [f"## comm ledger  b{b}  n_dp={n_dp}  hosts={hosts}  "
           f"bucket={bucket_mb}MB  compression={compression}",
           f"param leaves: {len(leaves)}  grad bytes/replica: "
           f"{sum(sizes) / 1e6:.1f} MB f32"]
    tot_ici = tot_dcn = 0.0
    if hosts > 1:
        out += ["| bucket | leaves | payload MB | ICI wire MB "
                "(rs+ag) | ICI ms | DCN wire MB (ar) | DCN ms |",
                "|---|---|---|---|---|---|---|"]
        ici_frac = (chips - 1) / chips           # rs and ag, each
        dcn_ring = 2.0 * (hosts - 1) / hosts
        for i, bucket in enumerate(buckets):
            payload = sum(sizes[j] for j in bucket) * wire_scale
            ici = 2.0 * ici_frac * payload       # rs + ag
            dcn = dcn_ring * payload / chips     # 1/chips of the bytes
            tot_ici += ici
            tot_dcn += dcn
            out.append(
                f"| {i} | {len(bucket)} | {payload / 1e6:.2f} | "
                f"{ici / 1e6:.2f} | {ici / ICI_BW * 1e3:.3f} | "
                f"{dcn / 1e6:.2f} | {dcn / DCN_BW * 1e3:.3f} |")
        out.append(
            f"| **total** | **{len(leaves)}** | "
            f"**{sum(sizes) * wire_scale / 1e6:.2f}** | "
            f"**{tot_ici / 1e6:.2f}** | "
            f"**{tot_ici / ICI_BW * 1e3:.3f}** | "
            f"**{tot_dcn / 1e6:.2f}** | "
            f"**{tot_dcn / DCN_BW * 1e3:.3f}** |")
        flat_dcn = 2.0 * (n_dp - 1) / n_dp * sum(sizes) * wire_scale
        out.append(
            f"flat ring at DCN for comparison: "
            f"{flat_dcn / 1e6:.2f} MB ~{flat_dcn / DCN_BW * 1e3:.3f} "
            f"ms — the hierarchy moves {1.0 / chips:.0%} of the bytes "
            f"over the slow leg")
    else:
        out += ["| bucket | leaves | payload MB | wire MB (ring) | "
                "ICI ms |",
                "|---|---|---|---|---|"]
        ring = 2.0 * (n_dp - 1) / n_dp
        for i, bucket in enumerate(buckets):
            payload = sum(sizes[j] for j in bucket) * wire_scale
            wire = ring * payload
            tot_ici += wire
            out.append(f"| {i} | {len(bucket)} | {payload / 1e6:.2f} | "
                       f"{wire / 1e6:.2f} | "
                       f"{wire / ICI_BW * 1e3:.3f} |")
        out.append(f"| **total** | **{len(leaves)}** | "
                   f"**{sum(sizes) * wire_scale / 1e6:.2f}** | "
                   f"**{tot_ici / 1e6:.2f}** | "
                   f"**{tot_ici / ICI_BW * 1e3:.3f}** |")
    last = sum(sizes[j] for j in buckets[-1]) if buckets else 0
    overlap = (1.0 - last / max(sum(sizes), 1)
               if len(buckets) > 1 else 0.0)
    _, _, _, t_step = predict(b)
    wire_time = tot_ici / ICI_BW + tot_dcn / DCN_BW
    exposed = wire_time * (1.0 - overlap)
    out.append(
        f"overlap estimate (structural): {overlap:.0%} of wire time "
        f"hides under backward compute; exposed comm "
        f"~{exposed * 1e3:.3f} ms vs roofline step "
        f"{t_step * 1e3:.2f} ms")
    if compression == "int8_ef":
        out.append(
            "int8_ef transport honesty: XLA's collective carries the "
            "quantized values as int32 today (4 B/elem on the wire); "
            "the 0.25 wire scale above prices the 1 B/elem the int8 "
            "payload CONTAINS — the gap is transport packing, not "
            "information, and closes with a packed-collective lowering")
    # ZeRO: moments (momentum = 1x params f32) + EMA when on shard
    # over n_dp — each replica keeps 1/n of the buffer bytes.
    opt_bytes = sum(sizes)  # SGD momentum: one f32 slot per param
    saved = opt_bytes * (1.0 - 1.0 / n_dp)
    out.append(
        f"ZeRO-1 (parallel.zero=1): optimizer moments "
        f"{opt_bytes / 1e6:.1f} MB/replica -> "
        f"{opt_bytes / n_dp / 1e6:.1f} MB sharded; "
        f"{saved / 1e6:.1f} MB HBM freed per device "
        f"(+ the same again per EMA tree when ema_decay>0)")
    return "\n".join(out)


# ---------------------------------------------------------------------
# measured side: bucket a captured trace by result-shape resolution
# ---------------------------------------------------------------------

_SHAPE = re.compile(r"\[(\d+(?:,\d+)*)\]")


def _scan_square(text: str, known: set) -> int:
    best = 0
    for m in _SHAPE.finditer(text):
        dims = [int(d) for d in m.group(1).split(",")]
        if len(dims) >= 3:
            for a, c in zip(dims[1:-1], dims[2:]):
                if a == c and a in known and a > best:
                    best = a
    return best


def _bucket_of(expr: str, known: set) -> int:
    """Spatial bucket of an HLO op: the largest known square spatial
    dim among its RESULT shapes — falling back to the whole expression
    (operands included) for ops whose results carry no spatial square,
    e.g. weight-grad fusions producing f32[3,3,Cin,Cout]."""
    rhs = expr.split("=", 1)[1].strip() if "=" in expr else expr
    if rhs.startswith("("):  # tuple result: take the balanced parens
        depth = 0
        head = rhs
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    head = rhs[:i + 1]
                    break
    else:
        head = rhs.split("(", 1)[0]
    return _scan_square(head, known) or _scan_square(expr, known)


def measured_table(trace_dir: str, top_unmatched: int = 5):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from analyze_trace import convert, find_xspaces

    xs = find_xspaces(trace_dir)
    if not xs:
        raise SystemExit(f"no xplane.pb under {trace_dir}")
    data = convert(xs, "hlo_stats")
    table = json.loads(data[data.index("{"):]) if isinstance(data, str) else data
    cols = [c.get("id") for c in table["cols"]]
    i_expr = cols.index("hlo_op_expression")
    i_self = cols.index("total_self_time")
    i_occ = cols.index("occurrences")
    i_bound = cols.index("bound_by")
    i_cat = cols.index("category")
    known = {320, 160, 80, 40, 20, 10}
    buckets: dict = {}
    cats: dict = {}
    unmatched: list = []
    total_us = 0.0
    for r in table["rows"]:
        vals = [c.get("v") if isinstance(c, dict) else c for c in r["c"]]
        occ = float(vals[i_occ] or 1)
        us = float(vals[i_self] or 0.0) / max(occ, 1)  # per-step us
        total_us += us
        cat = str(vals[i_cat] or "?")
        cats[cat] = cats.get(cat, 0.0) + us
        res = _bucket_of(str(vals[i_expr]), known)
        b = buckets.setdefault(res, [0.0, {}])
        b[0] += us
        bound = str(vals[i_bound] or "?")
        b[1][bound] = b[1].get(bound, 0.0) + us
        if res == 0 and us > 0:
            unmatched.append((us, str(vals[i_expr])[:90]))
    out = ["| res | measured ms/step | share | top bound-by |",
           "|---|---|---|---|"]
    for res in sorted(buckets, reverse=True):
        us, bounds = buckets[res]
        top = max(bounds.items(), key=lambda kv: kv[1])[0] if bounds else "?"
        out.append(f"| {res or 'other'} | {us / 1e3:.2f} | "
                   f"{us / total_us:.0%} | {top} |")
    out.append(f"| **total (self-time)** | **{total_us / 1e3:.2f}** | | |")
    out.append("")
    out.append("| category | ms/step | share |")
    out.append("|---|---|---|")
    for cat, us in sorted(cats.items(), key=lambda kv: -kv[1]):
        out.append(f"| {cat} | {us / 1e3:.2f} | {us / total_us:.0%} |")
    unmatched.sort(reverse=True)
    for us, e in unmatched[:top_unmatched]:
        out.append(f"  unbucketed {us / 1e3:.3f} ms: {e}")
    return "\n".join(out)


def xla_check(b: int = 4, hw: int = 64):
    """Compare the ledger against XLA's cost model on the REAL step —
    and cross-check the LIVE capacity ledger (utils/capacity.py) on the
    SAME compiled executable: the dsod_capacity_* surface must report
    exactly what cost_analysis reports here (within 1%), or live MFU
    and this offline roofline have diverged."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_sod_project_tpu.configs import (apply_overrides,
                                                     get_config)
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel.engine import (
        prepare_train_step)
    from distributed_sod_project_tpu.parallel.mesh import (
        batch_sharding, make_mesh)
    from distributed_sod_project_tpu.train import (build_optimizer,
                                                   create_train_state)

    cfg = get_config("minet_r50_dp")
    cfg = apply_overrides(cfg, [f"data.image_size={hw},{hw}",
                                "model.compute_dtype=float32",
                                f"global_batch_size={b}"])
    mesh = make_mesh(cfg.mesh)
    model = build_model(cfg.model)
    tx, sched = build_optimizer(cfg.optim, 100)
    rng = np.random.RandomState(0)
    batch = {"image": rng.randn(b, hw, hw, 3).astype(np.float32),
             "mask": (rng.rand(b, hw, hw, 1) > 0.5).astype(np.float32)}
    state = create_train_state(jax.random.key(0), model, tx, batch)
    state, step, _plan = prepare_train_step(
        cfg, model, tx, mesh, sched, state)
    dev_batch = jax.device_put(batch, batch_sharding(mesh))
    compiled = step.lower(state, dev_batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    xla_flops = float(cost.get("flops", 0.0))
    ops = minet_r50_ledger(b, hw=hw)
    ours = sum(o.flops + o.bwd_flops for o in ops)
    print(f"XLA cost model (b{b}@{hw}px, full train step): "
          f"{xla_flops / 1e9:.2f} GFLOPs")
    print(f"ledger                                      : "
          f"{ours / 1e9:.2f} GFLOPs  "
          f"(ratio {ours / xla_flops:.3f})")
    # Live-ledger cross-check on the SAME executable: what the
    # capacity_ledger knob would export for this program.
    from distributed_sod_project_tpu.utils.capacity import CapacityLedger

    cap = CapacityLedger(device_memory=False)
    rec = cap.record(f"train/{hw}x{hw}/k1", compiled)
    live_ratio = rec["flops"] / xla_flops if xla_flops else 0.0
    print(f"capacity ledger (live dsod_capacity_* source): "
          f"{rec['flops'] / 1e9:.2f} GFLOPs  "
          f"(ratio {live_ratio:.4f} — must be within 1%)")
    if not 0.99 <= live_ratio <= 1.01:
        print("capacity ledger DISAGREES with cost_analysis on the "
              "same executable")
        return 0.0  # outside every acceptance band below
    return ours / xla_flops


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=None,
                   help="single batch size (default: the b32/64/128 sweep)")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", choices=["none", "dots"],
                   default="none",
                   help="with --remat: the model.remat_policy knob — "
                        "'none' re-runs the whole forward in bwd, "
                        "'dots' keeps conv outputs (capacity cost) "
                        "and recomputes only elementwise")
    p.add_argument("--s2d", action="store_true")
    p.add_argument("--resize", choices=["fast", "xla", "fused"],
                   default="fast",
                   help="price the resample arm: fast (slice/lerp), "
                        "xla (generic jax.image.resize), fused (the "
                        "Pallas resample-merge kernel; also prints the "
                        "per-site bytes-saved ledger)")
    p.add_argument("--conv", choices=["xla", "fused"], default="xla",
                   help="price the conv-block arm: xla (nn.Conv + "
                        "BatchNorm), fused (the Pallas conv-stage "
                        "kernel, model.conv_impl=fused; also prints "
                        "the per-decoder-site bytes-saved ledger and "
                        "asserts FLOPs invariance vs the xla arm)")
    p.add_argument("--trace", help="profile dir to reconcile against")
    p.add_argument("--xla-check", action="store_true")
    p.add_argument("--comm", action="store_true",
                   help="print the gradient-communication ledger "
                        "(round 18): real param-tree buckets priced as "
                        "ring allreduces at ICI bandwidth, overlap "
                        "estimate, ZeRO HBM saving")
    p.add_argument("--n-dp", type=int, default=8,
                   help="with --comm: data-parallel degree the ring "
                        "is priced for")
    p.add_argument("--bucket-mb", type=float, default=25.0,
                   help="with --comm: parallel.comm_bucket_mb arm")
    p.add_argument("--compression",
                   choices=["none", "bf16", "int8_ef"],
                   default="none",
                   help="with --comm: parallel.grad_compression arm "
                        "(int8_ef prices the achievable 1 B/elem wire)")
    p.add_argument("--hosts", type=int, default=1,
                   help="with --comm: mesh.data_hosts — price the "
                        "hierarchical intra-host rs / inter-host ar / "
                        "intra-host ag schedule with the ICI and DCN "
                        "legs separated")
    args = p.parse_args(argv)

    if args.xla_check:
        ratio = xla_check()
        return 0 if 0.8 < ratio < 1.25 else 1

    batches = [args.batch] if args.batch else [32, 64, 128]
    if args.comm:
        for b in batches:
            print(fmt_comm_ledger(b, n_dp=args.n_dp,
                                  bucket_mb=args.bucket_mb,
                                  compression=args.compression,
                                  hosts=args.hosts))
            print()
        return 0
    for b in batches:
        print(fmt_pred(b, remat=args.remat, s2d=args.s2d,
                       resize=args.resize,
                       remat_policy=args.remat_policy, conv=args.conv))
        print()
        if args.resize == "fused":
            print(fmt_fused_ledger(b))
            print()
        if args.conv == "fused":
            print(fmt_fused_conv_ledger(b))
            print()
    if args.trace:
        print(f"## measured ({args.trace})")
        print(measured_table(args.trace))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
