#!/usr/bin/env python
"""Offline saliency-map evaluator — PySODEvalToolkit parity.

The reference author's ecosystem evaluates *saved* prediction maps
against ground-truth folders, decoupled from any framework
(SURVEY.md §2 C10: the PySODMetrics/PySODEvalToolkit pair).  This tool
is that capability for the TPU framework: point it at one or more
(pred_dir, gt_dir) pairs and get the full SOD metric table — MAE,
max/mean/adaptive Fβ, weighted Fβ, S-measure, E-measure — plus an
optional per-dataset precision/recall curve dump for plotting.

Usage:
    python tools/eval_preds.py duts_te=preds/duts_te:/data/DUTS-TE/Mask \
        [more name=pred_dir:gt_dir ...] [--curves curves.json] [--csv out.csv]

Predictions and GT are matched by file stem; predictions are resized to
GT resolution (the saved-map convention) before scoring.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pin_cpu() -> None:
    from distributed_sod_project_tpu.utils.platform import pin_cpu

    pin_cpu()


IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp")


def _index_dir(d):
    out = {}
    for f in sorted(os.listdir(d)):
        stem, ext = os.path.splitext(f)
        if ext.lower() in IMG_EXTS:
            out[stem] = os.path.join(d, f)
    return out


def _load_gray(path, size=None):
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("L")
        if size is not None and im.size != size:
            im = im.resize(size, Image.BILINEAR)
        return np.asarray(im, np.float32) / 255.0


def evaluate_pair(pred_dir: str, gt_dir: str, curves: bool = False):
    """Score every stem-matched (pred, gt) pair; returns (metrics,
    curve_dict|None, n_missing)."""
    from distributed_sod_project_tpu.metrics import SODMetrics

    preds = _index_dir(pred_dir)
    gts = _index_dir(gt_dir)
    matched = sorted(set(preds) & set(gts))
    missing = len(gts) - len(matched)
    if not matched:
        raise SystemExit(
            f"no stem matches between {pred_dir} ({len(preds)} maps) and "
            f"{gt_dir} ({len(gts)} masks)")

    agg = SODMetrics(compute_structure=True)
    for stem in matched:
        gt = (_load_gray(gts[stem]) > 0.5).astype(np.float32)
        pred = _load_gray(preds[stem], size=(gt.shape[1], gt.shape[0]))
        agg.add(pred, gt)
    results = agg.results()

    curve = None
    if curves:
        curve = {k: v.tolist() for k, v in agg.curves().items()}
    return results, curve, missing


def main(argv=None):
    _pin_cpu()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("pairs", nargs="+",
                   help="name=pred_dir:gt_dir (repeatable)")
    p.add_argument("--curves", default=None,
                   help="write per-dataset PR/Fβ curves to this JSON")
    p.add_argument("--csv", default=None, help="write the table as CSV")
    p.add_argument("--markdown", default=None,
                   help="write the table as a GitHub-style markdown file")
    p.add_argument("--latex", default=None,
                   help="write the table as a LaTeX tabular (the "
                        "PySODEvalToolkit paper-table export)")
    args = p.parse_args(argv)

    all_results = {}
    all_curves = {}
    for spec in args.pairs:
        if "=" not in spec or ":" not in spec.split("=", 1)[1]:
            raise SystemExit(f"bad pair {spec!r}; want name=pred_dir:gt_dir")
        name, rest = spec.split("=", 1)
        pred_dir, gt_dir = rest.rsplit(":", 1)
        res, curve, missing = evaluate_pair(pred_dir, gt_dir,
                                            curves=bool(args.curves))
        if missing:
            print(f"[warn] {name}: {missing} GT masks had no prediction",
                  file=sys.stderr)
        all_results[name] = res
        if curve:
            all_curves[name] = curve

    def _fmt(v):
        """Shared value formatting for console/markdown/LaTeX (CSV
        stays full-precision — it is the machine-readable output)."""
        return ("" if v is None else
                f"{v:.4f}" if isinstance(v, float) else str(v))

    cols = ["mae", "max_fbeta", "mean_fbeta", "adp_fbeta",
            "weighted_fmeasure", "s_measure", "e_measure", "max_emeasure",
            "mean_emeasure", "num_images"]
    present = [c for c in cols if any(c in r for r in all_results.values())]
    widths = {c: max(len(c), 7) for c in present}
    header = "dataset".ljust(12) + "  ".join(c.rjust(widths[c])
                                             for c in present)
    print(header)
    print("-" * len(header))
    for name, res in all_results.items():
        row = name.ljust(12)
        for c in present:
            row += _fmt(res.get(c)).rjust(widths[c]) + "  "
        print(row.rstrip())

    if args.csv:
        with open(args.csv, "w") as f:
            f.write("dataset," + ",".join(present) + "\n")
            for name, res in all_results.items():
                f.write(name + "," + ",".join(
                    str(res.get(c, "")) for c in present) + "\n")
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write("| dataset | " + " | ".join(present) + " |\n")
            f.write("|---" * (len(present) + 1) + "|\n")
            for name, res in all_results.items():
                f.write("| " + name + " | " + " | ".join(
                    _fmt(res.get(c)) for c in present) + " |\n")
    if args.latex:
        with open(args.latex, "w") as f:
            f.write("\\begin{tabular}{l" + "r" * len(present) + "}\n")
            f.write("\\toprule\ndataset & "
                    + " & ".join(c.replace("_", "\\_") for c in present)
                    + " \\\\\n\\midrule\n")
            for name, res in all_results.items():
                f.write(name.replace("_", "\\_") + " & " + " & ".join(
                    _fmt(res.get(c)) for c in present) + " \\\\\n")
            f.write("\\bottomrule\n\\end{tabular}\n")
    if args.curves:
        with open(args.curves, "w") as f:
            json.dump(all_curves, f)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
