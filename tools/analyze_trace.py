#!/usr/bin/env python
"""Summarise a ``jax.profiler`` trace into the tables the MFU push needs.

``bench.py --profile-dir DIR`` writes an XSpace (``*.xplane.pb``) under
``DIR/plugins/profile/<run>/``.  TensorBoard can render it, but the
sandbox has no browser — this tool extracts the numbers that matter
straight from xprof's converters (installed with jax's profiler deps):

    python tools/analyze_trace.py tpu_results/trace
    python tools/analyze_trace.py tpu_results/trace --tool hlo_stats --top 25
    python tools/analyze_trace.py tpu_results/trace --list-tools
    python tools/analyze_trace.py tpu_results/trace --dump-json out/

Default output: the overview page's step-time / FLOPS utilisation
summary plus the top-N HLO ops by self time (the "attack list" for
VERDICT round-1 weakness #1: profile-driven optimisation, not guesses).

The xprof tool JSON shapes are not a stable API; every extractor here
degrades to dumping the raw JSON (``--dump-json``) rather than failing,
so a converter change can never lose a captured trace's information.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def find_xspaces(trace_dir: str) -> list[str]:
    """All xplane.pb files under a profile dir (any nesting)."""
    return sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                            recursive=True))


def convert(xspace_paths: list[str], tool: str):
    """Run one xprof converter; returns (data, mime) or raises."""
    from xprof.convert import raw_to_tool_data

    # xprof's converter names tools with the tab suffix ("^") trimmed;
    # params dict is tool-specific, empty works for the summary tools.
    data, _mime = raw_to_tool_data.xspace_to_tool_data(
        xspace_paths, tool, params={})
    if isinstance(data, bytes):
        data = data.decode("utf-8", errors="replace")
    return data


def _gviz_rows(table: dict) -> tuple[list[str], list[list]]:
    """Flatten a gviz DataTable dict -> (column labels, rows)."""
    cols = [c.get("label") or c.get("id") or f"c{i}"
            for i, c in enumerate(table.get("cols", []))]
    rows = []
    for r in table.get("rows", []):
        rows.append([c.get("v") if isinstance(c, dict) else c
                     for c in r.get("c", [])])
    return cols, rows


def _fmt_table(cols: list[str], rows: list[list]) -> str:
    if not rows:
        return "(no rows)"
    widths = [min(max(len(str(c)), *(len(str(r[i])) if i < len(r) else 0
                                     for r in rows)), 48)
              for i, c in enumerate(cols)]
    def fmt_row(vals):
        cells = []
        for i, v in enumerate(vals):
            s = str(v)
            if len(s) > widths[i]:
                s = s[: widths[i] - 1] + "…"
            cells.append(s.ljust(widths[i]))
        return "  ".join(cells)
    out = [fmt_row(cols), fmt_row(["-" * w for w in widths])]
    out.extend(fmt_row(r) for r in rows)
    return "\n".join(out)


def show_overview(xspaces: list[str]) -> None:
    """Step time + utilisation headline from the overview_page tool."""
    try:
        raw = convert(xspaces, "overview_page")
        page = json.loads(raw)
    except Exception as e:  # noqa: BLE001 — degrade, never lose the trace
        print(f"[overview_page unavailable: {type(e).__name__}: {e}]")
        return
    # overview_page ships a list of gviz-ish tables; the properties
    # blocks ("p" keys) carry the scalar headline stats.
    props: dict = {}
    stack = [page]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            p = node.get("p")
            if isinstance(p, dict):
                props.update(p)
            stack.extend(node.values())
        elif isinstance(node, list):
            stack.extend(node)
    wanted = [
        ("average_step_time_ms", "avg step time (ms)"),
        ("steptime_ms_average", "avg step time (ms)"),
        ("flop_rate_utilization_relative_to_roofline", "FLOPS vs roofline"),
        ("mxu_utilization_percent", "MXU utilisation"),
        ("device_duty_cycle_percent", "device duty cycle"),
        ("memory_bw_utilization_relative_to_hw_limit", "HBM BW vs limit"),
        ("host_idle_time_percent", "host idle"),
        ("device_idle_time_percent", "device idle"),
    ]
    shown = False
    for key, label in wanted:
        if key in props:
            print(f"  {label:28s} {props[key]}")
            shown = True
    if not shown:
        print("  [overview_page parsed but no recognised scalar keys; "
              "use --dump-json to inspect]")


def show_hlo_stats(xspaces: list[str], top: int, sort_hint: str) -> None:
    """Top-N HLO ops by self time — the optimisation attack list."""
    try:
        raw = convert(xspaces, "hlo_stats")
        table = json.loads(raw)
    except Exception as e:  # noqa: BLE001
        print(f"[hlo_stats unavailable: {type(e).__name__}: {e}]")
        return
    if isinstance(table, list):  # some versions wrap in a list
        table = table[0] if table else {}
    cols, rows = _gviz_rows(table)
    if not rows:
        print("  (hlo_stats empty — use --dump-json)")
        return
    # Keep the informative columns; sort by self-time if identifiable.
    lowered = [c.lower() for c in cols]
    def col_idx(*cands):
        for cand in cands:
            for i, c in enumerate(lowered):
                if cand in c:
                    return i
        return None
    i_sort = col_idx(sort_hint, "total self time (us)", "self time")
    if i_sort is not None:
        def keyf(r):
            try:
                return -float(r[i_sort])
            except (TypeError, ValueError, IndexError):
                return 0.0
        rows = sorted(rows, key=keyf)
    keep = [i for i in (
        col_idx("hlo op name", "hlo_op_name", "op name"),
        col_idx("category"),
        col_idx("occurrences", "#"),
        i_sort,
        col_idx("self time (%", "self_time_percent", "%"),
        col_idx("flop rate", "gflops"),
        col_idx("bandwidth", "gibytes"),
    ) if i is not None]
    if not keep:
        keep = list(range(min(len(cols), 7)))
    sel_cols = [cols[i] for i in keep]
    sel_rows = [[r[i] if i < len(r) else "" for i in keep]
                for r in rows[:top]]
    print(_fmt_table(sel_cols, sel_rows))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("trace_dir", help="dir passed to bench.py --profile-dir")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--tool", default=None,
                   help="run ONE named xprof tool and print its raw JSON "
                        "(see --list-tools)")
    p.add_argument("--sort", default="total self time",
                   help="hlo_stats column substring to sort descending by")
    p.add_argument("--list-tools", action="store_true")
    p.add_argument("--dump-json", default=None, metavar="DIR",
                   help="write every available tool's raw JSON to DIR")
    args = p.parse_args(argv)

    xspaces = find_xspaces(args.trace_dir)
    if not xspaces:
        print(f"no *.xplane.pb under {args.trace_dir} — was the bench run "
              "with --profile-dir?", file=sys.stderr)
        return 1
    print(f"xspace files: {[os.path.basename(x) for x in xspaces]}")

    # Degrade, don't traceback (the module docstring's promise): xprof
    # ships with the jax profiler deps and its layout has moved between
    # releases — a missing/changed package must not crash --list-tools.
    # Tool enumeration failing is fatal only for the flags that need
    # it; the default overview path still runs (its extractors degrade
    # one by one).
    try:
        from xprof.convert import raw_to_tool_data

        names = [n.rstrip("^@")
                 for n in raw_to_tool_data.xspace_to_tool_names(xspaces)]
    except Exception as e:  # noqa: BLE001 — import/layout drift
        print(f"[xprof tool conversion unavailable "
              f"({type(e).__name__}: {e}); install the jax profiler "
              f"deps (xprof / tensorboard-plugin-profile)]",
              file=sys.stderr)
        if args.list_tools or args.tool or args.dump_json:
            return 1
        names = []
    if args.list_tools:
        print("\n".join(names))
        return 0

    if args.tool:
        print(convert(xspaces, args.tool))
        return 0

    if args.dump_json:
        os.makedirs(args.dump_json, exist_ok=True)
        for name in names:
            try:
                data = convert(xspaces, name)
            except Exception as e:  # noqa: BLE001 — tool-by-tool isolation
                print(f"  {name}: FAILED {type(e).__name__}: {e}")
                continue
            path = os.path.join(args.dump_json, f"{name}.json")
            with open(path, "w") as f:
                f.write(data if isinstance(data, str) else str(data))
            print(f"  {name}: {path}")
        return 0

    print("\n== overview ==")
    show_overview(xspaces)
    print(f"\n== top {args.top} HLO ops by self time ==")
    show_hlo_stats(xspaces, args.top, args.sort)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
