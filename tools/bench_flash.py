#!/usr/bin/env python
"""Micro-benchmark the Pallas flash-attention kernel vs the XLA core.

First real-v5e capture (round 2) showed the 128/128-block default
2.2x SLOWER than XLA's materialised attention on vit_sod shapes
(N=1024, D=64) — at short N the online-softmax VPU work dominates the
tiny per-tile dots.  This sweeps block shapes on the hardware so the
defaults can be set from measurement, not folklore:

    python tools/bench_flash.py --shape 12,1024,64
    python tools/bench_flash.py --shape 12,4096,64 --no-xla   # long N
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def time_fn(f, *args, iters=20):
    out = f(*args)  # compile + warm
    jax.block_until_ready(out)
    # Host fetch of a value depending on the result — reliable over the
    # remote-device transport (see bench.py sync note).
    def sync(o):
        leaf = jax.tree_util.tree_leaves(o)[0]
        return float(leaf.sum())
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--shape", default="12,1024,64",
                   help="bh,n,d (batch*heads, seq, head_dim)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--blocks", default="128/128,128/512,256/512,256/1024,"
                                       "512/512,512/1024",
                   help="comma list of block_q/block_kv pairs")
    p.add_argument("--no-xla", action="store_true",
                   help="skip the XLA oracle (OOMs at long N)")
    p.add_argument("--fwd-only", action="store_true")
    args = p.parse_args(argv)

    from distributed_sod_project_tpu.pallas.flash_attention import (
        flash_attention)
    from distributed_sod_project_tpu.parallel.ring_attention import (
        resolve_attn_fn)

    bh, n, d = (int(x) for x in args.shape.split(","))
    rng = np.random.RandomState(0)
    # Both cores take [B, H, N, D]; batch*heads folded into H is
    # equivalent for attention (no cross-head mixing).
    q, k, v = (jnp.asarray(rng.randn(1, bh, n, d), jnp.bfloat16)
               for _ in range(3))

    def run(fn):
        if args.fwd_only:
            return jax.jit(fn)
        return jax.jit(jax.grad(
            lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))

    rows = []
    if not args.no_xla:
        xla = resolve_attn_fn("xla")
        dt = time_fn(run(xla), q, k, v, iters=args.iters)
        rows.append(("xla", dt))
    for pair in args.blocks.split(","):
        bq, bkv = (int(x) for x in pair.split("/"))
        if bq > n or bkv > n:
            continue
        fn = lambda q, k, v, bq=bq, bkv=bkv: flash_attention(
            q, k, v, block_q=bq, block_kv=bkv)
        try:
            dt = time_fn(run(fn), q, k, v, iters=args.iters)
        except Exception as e:  # noqa: BLE001 — sweep must survive OOMs
            print(f"flash {pair}: FAILED {type(e).__name__}: "
                  f"{str(e)[:120]}")
            continue
        rows.append((f"flash {pair}", dt))

    mode = "fwd" if args.fwd_only else "fwd+bwd"
    print(f"\nshape bh={bh} n={n} d={d}  ({mode}, {args.iters} iters)")
    base = rows[0][1] if rows else 1.0
    for name, dt in rows:
        print(f"  {name:16s} {dt * 1e3:8.3f} ms   x{base / dt:.2f}")


if __name__ == "__main__":
    raise SystemExit(main())
