#!/usr/bin/env python
"""HLO relayout guard — catch data-formatting regressions at t1 time.

The round-2 v5e trace put ~10% of the flagship step in data-formatting
relayout copies, and the round-4 roofline named the upsample
interleave's ``stack+reshape`` form as the biggest single source
(~1.25 ms dim-shuffled ``bf16[64,160,64,160]`` copies per call).  The
layout-stable interleave (models/layers.py::_upsample_axis, round 5)
removes the size-1-axis insertions that force those copies — but
nothing stops a future change from quietly re-introducing them, and a
TPU window is needed to SEE them in a trace.

This tool makes the regression visible on CPU, per PR: it lowers the
flagship train step (reusing tools/dump_hlo.py, lowering only — no
compile) and counts the data-formatting ops in the pre-optimization
StableHLO — ``reshape``, ``transpose`` and ``broadcast_in_dim`` — for
two arms of the interleave:

- ``fast``        — the layout-stable concat-in-next-axis form
                    (the default path);
- ``fast_stack``  — the historical stack+reshape form
                    (``DSOD_RESIZE_INTERLEAVE=stack``).

Round 14 adds the conv-block arms on a smaller carrier (the fused arm
lowers every Pallas kernel in interpret mode — minutes of tracing at
flagship size):

- ``conv_xla``    — model.conv_impl=xla (the default; its counts
                    drifting is a byte-identity regression canary);
- ``conv_fused``  — model.conv_impl=fused (the Pallas conv-stage
                    kernels; counts pin the fused seam's lowered
                    structure).

Pre-optimization StableHLO is stable across machines (the same reason
dump_hlo.py diffs it), so the counts are checked into
``tools/hlo_copy_baseline.json`` and every run prints a ONE-LINE JSON
delta against that baseline — recorded, non-gating in tools/t1.sh
(pass ``--fail-on-increase`` to gate locally).  Invariants the tool
itself asserts (exit 1):

- the layout-stable arm counts strictly FEWER formatting ops than the
  stack arm (the guard's reason to exist);

Counting in pre-opt StableHLO is deliberate: the TPU relayout copies
appear only after XLA:TPU's layout assignment, which CPU cannot run —
but every one of them is *caused by* a reshape/transpose pattern that
is already visible (and countable) before optimization.  Fewer
formatting ops in ≈ fewer relayout copies out; the exact ms stays a
TPU-window measurement (tools/tpu_agenda_r5.sh leg ``ilv_stack``).

Usage:
    python tools/hlo_guard.py                      # print delta line
    python tools/hlo_guard.py --update-baseline    # re-seed the file
    python tools/hlo_guard.py --fail-on-increase   # gate (local use)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "hlo_copy_baseline.json")

# What counts as a data-formatting op in pre-opt StableHLO.  reshape +
# transpose are the relayout-copy feeders; broadcast_in_dim is counted
# too because jnp.stack may lower its size-1-axis insertion either way.
_FORMATTING = ("reshape", "transpose", "broadcast_in_dim")

# The two interleave arms of the SAME default resample path.  Each arm
# pins EVERY resample-affecting env var (None = must be unset): the
# agenda scripts export DSOD_RESIZE_INTERLEAVE / DSOD_RESIZE_IMPL for
# their own A/B legs, and an inherited value would silently lower the
# same arm twice and trip the fast<stack invariant with a false alarm.
ARMS = {
    "fast": {"DSOD_RESIZE_INTERLEAVE": None, "DSOD_RESIZE_IMPL": None},
    "fast_stack": {"DSOD_RESIZE_INTERLEAVE": "stack",
                   "DSOD_RESIZE_IMPL": None},
}

# Conv-block arms (round 14): the SAME formatting-op counts per
# model.conv_impl arm, lowered on a smaller carrier than the flagship —
# the fused arm lowers the Pallas kernels in interpret mode on CPU
# (grid loops and im2col slicing all visible as countable ops), which
# on the flagship costs ~2 min of pure tracing; the carrier keeps the
# guard inside the t1 smoke budget while covering every seam idiom
# (plain/concat/dilated/no-BN conv blocks).  conv_xla is lowered too —
# its counts must track the seam's default arm, and a drift here is a
# byte-identity regression before tests/test_pallas_conv.py says so.
CONV_ARMS = {
    "conv_xla": (),
    "conv_fused": ("model.conv_impl=fused",),
}
# Resample env vars pinned (unset) around the conv dumps for the same
# reason as ARMS: an inherited A/B export must not contaminate counts.
_PINNED_ENV = ("DSOD_RESIZE_INTERLEAVE", "DSOD_RESIZE_IMPL")

# Gradient-collective arms (round 18, ISSUE 18 acceptance): the rules
# engine's bucketed allreduce fuses each backward-ordered bucket into
# ONE flat 1-D psum (parallel/rules.py::bucketed_pmean), so the
# ``stablehlo.all_reduce`` count is the countable structure signal —
# on the FLAGSHIP config (same carrier as ARMS):
#
# - ``comm_mono``     — comm_bucket_mb=0: the monolithic ``lax.pmean``
#                       spelling, one all_reduce PER GRADIENT LEAF in
#                       pre-opt StableHLO;
# - ``comm_flat``     — one giant bucket: every grad fused into a
#                       single flat all_reduce (the bucket-count floor);
# - ``comm_bucketed`` — the default parallel.comm_bucket_mb: B buckets.
#
# Invariants asserted (exit 1): bucketed − flat == B − 1 ≥ 1 (the
# "≥2 psum buckets at default bucket size" acceptance check — the only
# all_reduce delta between the two arms IS the extra buckets), and
# mono > bucketed (bucket fusion actually collapsed the per-leaf
# reduces).  Counts are recorded in the same baseline with the same
# never-persist-on-failed-invariant discipline.
#
# Round 18 adds the pod-scale arms:
#
# - ``comm_hier``  — mesh.data_hosts=2 on a 4-device virtual mesh:
#   each bucket's flat psum becomes intra-host reduce-scatter →
#   inter-host all-reduce → intra-host all-gather
#   (parallel/rules.py::_hier_psum), so per bucket the pre-opt
#   StableHLO gains exactly one reduce_scatter and one all_gather
#   while the all_reduce count stays EQUAL to the bucketed arm's
#   (the bucket psum is replaced 1:1 by the inter-host psum).
#   Invariants: rs_hier − rs_bucketed == n_buckets, ag_hier −
#   ag_bucketed == n_buckets, ar_hier == ar_bucketed.
# - ``comm_fsdp``  — parallel.preset=fsdp (model.sync_bn=false: GSPMD
#   has no named BN axis): counted in POST-opt HLO because the SPMD
#   partitioner inserts the collectives during compilation — the
#   pre-opt StableHLO of a GSPMD step contains ZERO collectives.
#   Invariants: ≥1 all-gather (the JIT param gathering that IS FSDP)
#   and ≥1 reduce-scatter-or-all-reduce (the grad reduction; XLA:CPU
#   lowers reduce-scatter to all-reduce+slice, so the rs count alone
#   cannot gate on this backend).
COMM_ARMS = {
    "comm_mono": ("parallel.comm_bucket_mb=0",),
    "comm_flat": ("parallel.comm_bucket_mb=100000",),
    "comm_bucketed": (),
}
# All three collective kinds are counted per arm (flat arms lower with
# zero rs/ag today; the hier invariants difference against them).
_COLLECTIVES = ("all_reduce", "reduce_scatter", "all_gather")
COMM_HIER_ARMS = {
    "comm_hier": ("mesh.data_hosts=2",),
}
# data_hosts=2 needs ≥2 chips per host on the virtual mesh.
_HIER_DEVICES = 4
COMM_FSDP_ARMS = {
    "comm_fsdp": ("parallel.preset=fsdp", "model.sync_bn=false"),
}
# Post-opt HLO spells collectives with dashes (all-gather, ...).
_POST_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather")


def count_formatting_ops(stablehlo_text: str) -> dict:
    """Count stablehlo data-formatting ops by kind (+ 'total')."""
    counts = {}
    for kind in _FORMATTING:
        counts[kind] = len(
            re.findall(rf"stablehlo\.{kind}\b", stablehlo_text))
    counts["total"] = sum(counts.values())
    return counts


def dump_arm_counts(config: str, out_dir: str, n_devices: int,
                    image_size: int) -> dict:
    """Lower the config's train step once per arm; return
    {arm: counts}."""
    from dump_hlo import dump  # tools/ sibling (path set above)

    results = {}
    for arm, env in ARMS.items():
        saved = {k: os.environ.get(k) for k in env}
        for k, v in env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            # NOTE: the env pinning above is the ONLY effective guard
            # for the 'fast' arm — 'fast' is the env-subsumed default,
            # so a config override `model.resample_impl=fast` cannot
            # out-pin an exported DSOD_RESIZE_IMPL (by design:
            # layers._resolve_resample_impl).  Do not trim ARMS on the
            # strength of a config override.
            paths = dump(config, os.path.join(out_dir, arm),
                         n_devices=n_devices, image_size=image_size,
                         compile_cost=False)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        with open(paths["stablehlo"]) as f:
            results[arm] = count_formatting_ops(f.read())
    return results


def dump_conv_arm_counts(config: str, out_dir: str, n_devices: int,
                         image_size: int) -> dict:
    """Lower the conv-arm carrier once per model.conv_impl arm (config
    overrides, not env) with the resample env pinned unset; return
    {arm: counts}."""
    from dump_hlo import dump  # tools/ sibling (path set above)

    results = {}
    saved = {k: os.environ.get(k) for k in _PINNED_ENV}
    for k in _PINNED_ENV:
        os.environ.pop(k, None)
    try:
        for arm, overrides in CONV_ARMS.items():
            paths = dump(config, os.path.join(out_dir, arm),
                         n_devices=n_devices, image_size=image_size,
                         compile_cost=False, overrides=overrides)
            with open(paths["stablehlo"]) as f:
                results[arm] = count_formatting_ops(f.read())
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
    return results


def _count_collectives(stablehlo_text: str) -> dict:
    """Per-kind collective counts in pre-opt StableHLO; 'total' stays
    the all_reduce count for baseline continuity with the round-17
    rows (the bucketing invariants are all_reduce deltas)."""
    counts = {kind: len(re.findall(rf"stablehlo\.{kind}\b",
                                   stablehlo_text))
              for kind in _COLLECTIVES}
    counts["total"] = counts["all_reduce"]
    return counts


def dump_comm_arm_counts(config: str, out_dir: str, n_devices: int,
                         image_size: int) -> dict:
    """Lower the flagship step once per gradient-collective arm (config
    overrides on the rules engine) with the resample env pinned unset;
    return {arm: {'all_reduce': n, ..., 'total': n}}.  The hierarchical
    arm lowers on a 4-device virtual mesh (data_hosts=2 needs ≥2 chips
    per host — main() sizes the device pool up front so this works
    in-process); op COUNTS in the traced program are device-count
    independent, so its deltas difference cleanly against the 2-device
    bucketed arm."""
    from dump_hlo import dump  # tools/ sibling (path set above)

    results = {}
    saved = {k: os.environ.get(k) for k in _PINNED_ENV}
    for k in _PINNED_ENV:
        os.environ.pop(k, None)
    try:
        for arm, overrides in COMM_ARMS.items():
            paths = dump(config, os.path.join(out_dir, arm),
                         n_devices=n_devices, image_size=image_size,
                         compile_cost=False, overrides=overrides)
            with open(paths["stablehlo"]) as f:
                results[arm] = _count_collectives(f.read())
        for arm, overrides in COMM_HIER_ARMS.items():
            paths = dump(config, os.path.join(out_dir, arm),
                         n_devices=max(n_devices, _HIER_DEVICES),
                         image_size=image_size,
                         compile_cost=False, overrides=overrides)
            with open(paths["stablehlo"]) as f:
                results[arm] = _count_collectives(f.read())
        for arm, overrides in COMM_FSDP_ARMS.items():
            paths = dump(config, os.path.join(out_dir, arm),
                         n_devices=n_devices, image_size=image_size,
                         compile_cost=False, overrides=overrides,
                         post_opt=True)
            with open(paths["hlo_post"]) as f:
                txt = f.read()
            counts = {kind.replace("-", "_"): txt.count(f"{kind}(")
                      for kind in _POST_COLLECTIVES}
            counts["total"] = counts["all_gather"]
            results[arm] = counts
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="minet_r50_dp",
                   help="flagship by default — the config the roofline "
                        "levers were derived on")
    p.add_argument("--image-size", type=int, default=64,
                   help="small-but-even lowering size: every decoder "
                        "resample stays an exact factor-2, so the "
                        "interleave op pattern matches 320px")
    p.add_argument("--devices", type=int, default=2,
                   help="virtual CPU mesh size (lowering only; 2 keeps "
                        "the guard fast while exercising the sharded "
                        "step)")
    p.add_argument("--out", default=None,
                   help="dump dir (default: a temp dir)")
    p.add_argument("--conv-config", default="minet_vgg16_ref",
                   help="carrier for the model.conv_impl arms — "
                        "smaller than the flagship because the fused "
                        "arm lowers every Pallas kernel in interpret "
                        "mode (~2 min of tracing at flagship size)")
    p.add_argument("--conv-image-size", type=int, default=32,
                   help="conv-arm lowering size (even, so decoder "
                        "shapes stay exact factor-2)")
    p.add_argument("--no-conv-arms", action="store_true",
                   help="skip the conv_impl arm dumps (resample arms "
                        "only — the pre-r14 behavior)")
    p.add_argument("--no-comm-arms", action="store_true",
                   help="skip the gradient-collective arm dumps "
                        "(round 18: rules-engine bucketed allreduce)")
    p.add_argument("--baseline", default=_BASELINE)
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--fail-on-increase", action="store_true",
                   help="exit 2 when any arm's total exceeds the "
                        "baseline (off in shared CI: recorded, not "
                        "gating — the t1.sh posture)")
    args = p.parse_args(argv)

    # The virtual device pool must be sized BEFORE the first dump
    # initializes jax (dump()'s own setdefault cannot grow an already-
    # initialized backend): the comm_hier arm needs _HIER_DEVICES even
    # when every other arm lowers on --devices.  Each dump still
    # slices jax.devices()[:n], so the smaller-mesh traces are
    # unchanged by the larger pool.
    os.environ.setdefault(
        "XLA_FLAGS",
        "--xla_force_host_platform_device_count="
        f"{max(args.devices, _HIER_DEVICES)}")

    tmp = None
    out_dir = args.out
    if out_dir is None:
        import tempfile

        # Cleaned up on exit: each arm's flagship StableHLO dump is
        # multi-MB and t1.sh runs this on every pass.
        tmp = tempfile.TemporaryDirectory(prefix="hlo_guard_")
        out_dir = tmp.name
    try:
        arm_counts = dump_arm_counts(args.config, out_dir, args.devices,
                                     args.image_size)
    finally:
        if tmp is not None:
            tmp.cleanup()

    rc = 0
    fast, stack = arm_counts["fast"], arm_counts["fast_stack"]
    if fast["total"] >= stack["total"]:
        # The guard's core invariant: the layout-stable interleave must
        # emit strictly fewer formatting ops than the stack form.
        print(f"hlo_guard: layout-stable arm NOT fewer formatting ops "
              f"({fast['total']} vs {stack['total']})", file=sys.stderr)
        rc = 1

    baseline = None
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baseline = json.load(f)
    key = f"{args.config}@{args.image_size}px"
    if rc != 0:
        # Never persist counts from a run whose own invariant failed —
        # a corrupt seed would make every later comparison report
        # delta 0 against garbage, permanently masking the regression.
        print(f"hlo_guard: invariant failed — NOT seeding/updating "
              f"baseline for {key}", file=sys.stderr)
        print(json.dumps({
            "metric": f"hlo_formatting_ops[{key}]",
            "arms": {arm: c["total"] for arm, c in arm_counts.items()},
            "invariant_failed": True,
        }), flush=True)
        return rc
    if args.update_baseline or baseline is None or key not in baseline:
        baseline = baseline or {}
        baseline[key] = arm_counts
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        recorded = True
        delta = {arm: 0 for arm in arm_counts}
    else:
        recorded = False
        delta = {arm: arm_counts[arm]["total"]
                 - baseline[key].get(arm, {}).get("total", 0)
                 for arm in arm_counts}
        if args.fail_on_increase and any(d > 0 for d in delta.values()):
            rc = rc or 2

    # The one-line JSON delta window reports track per PR.
    print(json.dumps({
        "metric": f"hlo_formatting_ops[{key}]",
        "arms": {arm: c["total"] for arm, c in arm_counts.items()},
        "detail": arm_counts,
        "delta_vs_baseline": delta,
        "stack_minus_fast": stack["total"] - fast["total"],
        **({"recorded": True} if recorded else {}),
    }), flush=True)

    if args.no_conv_arms:
        return rc

    # -- conv_impl arms (round 14): same recorded-delta discipline on
    #    the conv-arm carrier; conv_xla drifting is a byte-identity
    #    regression canary, conv_fused drifting means the fused seam's
    #    lowered structure changed.
    tmp2 = None
    out_dir2 = args.out
    if out_dir2 is None:
        import tempfile

        tmp2 = tempfile.TemporaryDirectory(prefix="hlo_guard_conv_")
        out_dir2 = tmp2.name
    try:
        conv_counts = dump_conv_arm_counts(
            args.conv_config, out_dir2, args.devices,
            args.conv_image_size)
    finally:
        if tmp2 is not None:
            tmp2.cleanup()
    ckey = f"{args.conv_config}@{args.conv_image_size}px-conv"
    if args.update_baseline or ckey not in baseline:
        baseline[ckey] = conv_counts
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        crecorded = True
        cdelta = {arm: 0 for arm in conv_counts}
    else:
        crecorded = False
        cdelta = {arm: conv_counts[arm]["total"]
                  - baseline[ckey].get(arm, {}).get("total", 0)
                  for arm in conv_counts}
        if args.fail_on_increase and any(d > 0 for d in cdelta.values()):
            rc = rc or 2
    print(json.dumps({
        "metric": f"hlo_formatting_ops[{ckey}]",
        "arms": {arm: c["total"] for arm, c in conv_counts.items()},
        "detail": conv_counts,
        "delta_vs_baseline": cdelta,
        **({"recorded": True} if crecorded else {}),
    }), flush=True)

    if args.no_comm_arms:
        return rc

    # -- gradient-collective arms (round 18): all_reduce counts per
    #    bucketing arm of the rules engine on the FLAGSHIP config.
    tmp3 = None
    out_dir3 = args.out
    if out_dir3 is None:
        import tempfile

        tmp3 = tempfile.TemporaryDirectory(prefix="hlo_guard_comm_")
        out_dir3 = tmp3.name
    try:
        comm_counts = dump_comm_arm_counts(
            args.config, out_dir3, args.devices, args.image_size)
    finally:
        if tmp3 is not None:
            tmp3.cleanup()
    mkey = f"{args.config}@{args.image_size}px-comm"
    n_buckets = (comm_counts["comm_bucketed"]["total"]
                 - comm_counts["comm_flat"]["total"] + 1)
    comm_invariant_failed = False
    if n_buckets < 2:
        print(f"hlo_guard: bucketed arm emits {n_buckets} psum "
              "bucket(s) — the default bucket size must split the "
              "flagship gradient into >= 2 (ISSUE 18 acceptance)",
              file=sys.stderr)
        comm_invariant_failed = True
    if comm_counts["comm_mono"]["total"] <= \
            comm_counts["comm_bucketed"]["total"]:
        print("hlo_guard: bucket fusion did NOT reduce the all_reduce "
              f"count ({comm_counts['comm_mono']['total']} mono vs "
              f"{comm_counts['comm_bucketed']['total']} bucketed)",
              file=sys.stderr)
        comm_invariant_failed = True
    # Hierarchical arm (round 18): per bucket, one intra-host
    # reduce_scatter and all_gather appear and the flat bucket psum is
    # replaced 1:1 by the inter-host psum — per-level counts asserted.
    hier = comm_counts["comm_hier"]
    bktd = comm_counts["comm_bucketed"]
    for kind, expect in (("reduce_scatter", n_buckets),
                         ("all_gather", n_buckets)):
        got = hier.get(kind, 0) - bktd.get(kind, 0)
        if got != expect:
            print(f"hlo_guard: hierarchical arm {kind} delta vs "
                  f"bucketed is {got}, expected n_buckets={expect}",
                  file=sys.stderr)
            comm_invariant_failed = True
    if hier.get("all_reduce", 0) != bktd.get("all_reduce", 0):
        print("hlo_guard: hierarchical arm all_reduce count "
              f"({hier.get('all_reduce', 0)}) != bucketed arm's "
              f"({bktd.get('all_reduce', 0)}) — the inter-host psum "
              "must replace the flat bucket psum 1:1",
              file=sys.stderr)
        comm_invariant_failed = True
    # FSDP arm (round 18, post-opt counts): the JIT param all-gather
    # is FSDP's signature; grads must reduce (rs, or XLA:CPU's
    # all-reduce lowering of it).
    fsdp = comm_counts["comm_fsdp"]
    if fsdp.get("all_gather", 0) < 1:
        print("hlo_guard: fsdp arm lowered ZERO all-gathers — params "
              "are not being gathered just-in-time", file=sys.stderr)
        comm_invariant_failed = True
    if fsdp.get("reduce_scatter", 0) + fsdp.get("all_reduce", 0) < 1:
        print("hlo_guard: fsdp arm lowered no gradient reduction "
              "(reduce-scatter or all-reduce)", file=sys.stderr)
        comm_invariant_failed = True
    if comm_invariant_failed:
        rc = rc or 1
        print(f"hlo_guard: invariant failed — NOT seeding/updating "
              f"baseline for {mkey}", file=sys.stderr)
        print(json.dumps({
            "metric": f"hlo_grad_collectives[{mkey}]",
            "arms": {arm: c["total"] for arm, c in comm_counts.items()},
            "n_buckets": n_buckets,
            "invariant_failed": True,
        }), flush=True)
        return rc
    if args.update_baseline or mkey not in baseline:
        baseline[mkey] = comm_counts
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        mrecorded = True
        mdelta = {arm: 0 for arm in comm_counts}
    else:
        mrecorded = False
        mdelta = {arm: comm_counts[arm]["total"]
                  - baseline[mkey].get(arm, {}).get("total", 0)
                  for arm in comm_counts}
        if args.fail_on_increase and any(d > 0 for d in mdelta.values()):
            rc = rc or 2
    print(json.dumps({
        "metric": f"hlo_grad_collectives[{mkey}]",
        "arms": {arm: c["total"] for arm, c in comm_counts.items()},
        "n_buckets": n_buckets,
        "delta_vs_baseline": mdelta,
        **({"recorded": True} if mrecorded else {}),
    }), flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
