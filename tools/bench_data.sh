#!/bin/bash
# Host data-plane benchmark + regression record — CPU only, no TPU
# window needed (docs/PERFORMANCE.md "Host data plane").
#
# Runs bench.py --mode data (host backend, rotate+jitter on — the
# full augmentation pipeline) against the CHECKED-IN baseline
# tools/data_baseline.json: the first run on a fresh key seeds it,
# later runs add vs_recorded to the JSON result line.  No hard perf
# gate on shared CI (the sandbox CPUs are noisy-neighbor machines) —
# the number is printed and recorded; pass --fail-below 0.5 (or any
# ratio) to turn it into a local gate.
#
# Knobs via env: STEPS/WARMUP/BATCH/SIZE; extra bench.py flags pass
# through, e.g.:  tools/bench_data.sh --set data.backend=grain
cd "$(dirname "$0")/.." || exit 1
STEPS=${STEPS:-8}
WARMUP=${WARMUP:-2}
BATCH=${BATCH:-8}
SIZE=${SIZE:-128}
exec env JAX_PLATFORMS=cpu python bench.py --device cpu --mode data \
  --steps "$STEPS" --warmup "$WARMUP" --batch-per-chip "$BATCH" \
  --image-size "$SIZE" \
  --set data.backend=host --set data.rotate_degrees=10 \
  --set data.color_jitter=0.4 \
  --baseline-file tools/data_baseline.json "$@"
