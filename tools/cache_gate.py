#!/usr/bin/env python
"""Near-dup cache-serving quality gate — CPU-runnable, per-PR
(docs/SERVING.md "Router cache").

The router cache's near-dup arm (`serve/cache.py`) answers a request
with ANOTHER image's cached mask when the two payloads' perceptual
hashes agree within a Hamming budget — a deliberate quality trade, and
like the precision arms (`tools/precision_gate.py`) the trade is
measurable on CPU at t1 time: serve image A's mask (resize-normalized
exactly the way the router does) for a resize-perturbed variant of A,
and score it against the exact forward on that variant.  This tool
does that over a fixed synthetic set and maintains a checked-in delta
ledger, `tools/cache_baseline.json`, in the hlo_guard/precision_gate
discipline:

- every run prints ONE JSON line with the near-arm deltas and the
  delta against the recorded ledger;
- `--fail-on-increase` exits 2 when the near arm's quality delta
  exceeds its recorded budget by more than `--tolerance` (off in
  shared CI: the t1.sh posture is recorded, non-gating);
- `--update-baseline` re-seeds after an intentional change;
- a run whose own invariants failed (non-finite metrics, short set, a
  perturbed variant that would NOT actually near-hit within the
  Hamming budget) NEVER seeds or updates the ledger.

The ledger's reference row is named ``f32`` by the shared helper —
here that is literally accurate: the reference IS the exact f32
forward on the perturbed payload.  Deltas are signed so "worse" is
positive (``delta_max_fbeta = exact − near``, ``delta_mae = near −
exact``); the reference for the Fβ/MAE sweep is the exact forward
binarized at 0.5, so the exact row scores max_fbeta 1.0 by
construction and the near row's drop is pure near-dup serving error.

Usage:
    python tools/cache_gate.py                      # print deltas
    python tools/cache_gate.py --update-baseline    # re-seed
    python tools/cache_gate.py --fail-on-increase   # gate locally
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import precision_gate  # noqa: E402 — shared ledger discipline

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "cache_baseline.json")

# Resize factors for the perturbed variants, alternated per image —
# the same scales the loadgen's --perturb knob offers, one below and
# one above the catalog resolution so both resize directions are in
# the budget.
_SCALES = (0.875, 1.125)


def _npy(arr) -> bytes:
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def run_gate(model, variables, cfg, *, image_size: int, num_images: int,
             seed: int, hamming_budget: int) -> dict:
    """Score near-dup serving vs the exact forward on a synthetic set →
    ``(report, extras)`` where report is the shared-ledger shape and
    extras carries the gate's own observables (max Hamming distance
    seen, direct served-vs-exact pixel dMAE)."""
    import numpy as np

    from distributed_sod_project_tpu.eval.inference import (_resize_pred,
                                                            make_forward)
    from distributed_sod_project_tpu.metrics import SODMetrics
    from distributed_sod_project_tpu.serve.cache import (hamming,
                                                         payload_fingerprint,
                                                         resize_mask_body)
    from distributed_sod_project_tpu.serve.engine import preprocess_image
    from distributed_sod_project_tpu.serve.loadgen import structured_image
    from PIL import Image

    rng = np.random.RandomState(seed)
    mean = np.asarray(cfg.data.normalize_mean, np.float32)
    std = np.asarray(cfg.data.normalize_std, np.float32)
    hw = image_size
    imgs, perts, pert_hw = [], [], []
    for i in range(num_images):
        img = structured_image(rng, hw, hw)
        f = _SCALES[i % len(_SCALES)]
        side = max(int(hw * f), 8)
        imgs.append(img)
        perts.append(np.asarray(
            Image.fromarray(img).resize((side, side), Image.BILINEAR)))
        pert_hw.append((side, side))

    # Both request streams forward at the catalog resolution — the
    # engine's resolution-bucket behavior: a 56px request runs at the
    # 64px bucket and its mask resizes back to 56px on the way out.
    fwd = make_forward(model)
    batch_o = np.stack([preprocess_image(a, hw, mean, std) for a in imgs])
    batch_p = np.stack([preprocess_image(a, hw, mean, std) for a in perts])
    masks_o = np.asarray(fwd(variables, {"image": batch_o}))
    masks_p = np.asarray(fwd(variables, {"image": batch_p}))

    agg_exact = SODMetrics(compute_structure=False)
    agg_near = SODMetrics(compute_structure=False)
    reasons, max_ham, dmaes = [], 0, []
    for i in range(num_images):
        fp_o = payload_fingerprint(_npy(imgs[i]))
        fp_p = payload_fingerprint(_npy(perts[i]))
        ham = (hamming(fp_o[0], fp_p[0])
               if fp_o is not None and fp_p is not None else 257)
        max_ham = max(max_ham, ham)
        if ham > hamming_budget:
            # The gate must measure what the cache would actually DO:
            # a variant outside the budget would miss, so its score
            # would dilute the ledger with a path the router never
            # takes.
            reasons.append(f"image {i}: Hamming {ham} > budget "
                           f"{hamming_budget} — would not near-hit")
            continue
        exact = _resize_pred(masks_p[i], pert_hw[i])
        served_body = resize_mask_body(
            _npy(masks_o[i].astype(np.float32)), pert_hw[i])
        served = np.load(io.BytesIO(served_body))
        ref = (exact > 0.5).astype(np.float32)
        agg_exact.add(exact, ref)
        agg_near.add(served, ref)
        dmaes.append(float(np.mean(np.abs(served - exact))))

    report = precision_gate.build_report(
        {"f32": agg_exact.results(), "near": agg_near.results()},
        expected_images=num_images)
    if reasons:
        report["invariant_failed"] = True
        report["reasons"] = report["reasons"] + reasons
    extras = {
        "hamming_budget": hamming_budget,
        "max_hamming": max_ham,
        "dmae_mean": round(float(np.mean(dmaes)), 6) if dmaes else None,
        "dmae_max": round(float(np.max(dmaes)), 6) if dmaes else None,
    }
    return report, extras


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="minet_vgg16_ref",
                   help="registered config (weights are random-init — "
                        "the near-dup error is a serving-path effect "
                        "measurable on any weights)")
    p.add_argument("--image-size", type=int, default=64,
                   help="catalog resolution (small keeps the CPU gate "
                        "fast; perturbed variants resize ±12.5%%)")
    p.add_argument("--num-images", type=int, default=12,
                   help="fixed synthetic set size (deterministic per "
                        "seed)")
    p.add_argument("--hamming", type=int, default=16,
                   help="near-dup Hamming budget under test (mirror of "
                        "serve.cache_near_dup_hamming; part of the "
                        "ledger key)")
    p.add_argument("--seed", type=int, default=0,
                   help="catalog + weight seed (part of the ledger key)")
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"],
                   help="cpu by default — the gate must run at t1 time "
                        "with no TPU window")
    p.add_argument("--baseline", default=_BASELINE)
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--fail-on-increase", action="store_true",
                   help="exit 2 when the near arm exceeds its recorded "
                        "quality budget by more than --tolerance (off "
                        "in shared CI: recorded, not gating — the "
                        "t1.sh posture)")
    p.add_argument("--tolerance", type=float, default=0.003,
                   help="slack on the recorded delta before a breach "
                        "(metric units; covers CPU ulp noise)")
    args = p.parse_args(argv)

    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    import jax
    import numpy as np

    from distributed_sod_project_tpu.configs import (apply_overrides,
                                                     get_config)
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.train import (build_optimizer,
                                                   create_train_state)

    hw = args.image_size
    cfg = apply_overrides(get_config(args.config),
                          [f"data.image_size={hw},{hw}",
                           f"seed={args.seed}"])
    model = build_model(cfg.model)
    tx, _ = build_optimizer(cfg.optim, 1)
    probe = {"image": np.zeros((1, hw, hw, 3), np.float32)}
    if cfg.data.use_depth:
        probe["depth"] = np.zeros((1, hw, hw, 1), np.float32)
    state = create_train_state(jax.random.key(cfg.seed), model, tx,
                               probe, ema=cfg.optim.ema_decay > 0)

    report, extras = run_gate(model, state.eval_variables(), cfg,
                              image_size=hw, num_images=args.num_images,
                              seed=args.seed, hamming_budget=args.hamming)

    baseline = {}
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baseline = json.load(f)
    key = f"{cfg.name}@{hw}px-n{args.num_images}-s{args.seed}-h{args.hamming}"
    rc, new_baseline, summary = precision_gate.apply_baseline(
        report, baseline, key, update=args.update_baseline,
        fail_on_increase=args.fail_on_increase,
        tolerance=args.tolerance)
    summary["metric"] = f"cache_gate[{key}]"
    summary["near_dup"] = extras
    if rc == 1:
        print(f"cache_gate: invariant failed — NOT seeding/updating "
              f"baseline for {key}: {report['reasons']}", file=sys.stderr)
    elif new_baseline is not baseline:
        with open(args.baseline, "w") as f:
            json.dump(new_baseline, f, indent=2, sort_keys=True)
            f.write("\n")
    print(json.dumps(summary), flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
