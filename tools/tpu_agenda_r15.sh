#!/bin/bash
# Round-15 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 15).  Round 15 landed the black-box flight recorder
# (utils/flightrecorder.py): durable on-disk telemetry history (an
# append-only JSONL segment ring sampled from the same prom_families
# registry /metrics renders), typed events, debounced crash-safe
# incident bundles, and the tools/incident.py offline analyzer —
# threaded through the serve engine, the fleet router, and the train
# loop.  Crash-safety and the SIGKILL replay are proven on CPU
# (tests/test_flightrecorder.py, tools/fleet_chaos.py); what only
# hardware can answer is the recorder's TAX on real throughput:
#
#   1. canonical b128 headline refresh (comparison anchor)
#   2. RECORDER serve A/B: closed-loop serve bench, recorder off vs on
#      at the default 1 Hz sampling.  Prediction on record: <2% p50 /
#      throughput delta — the sampler is one families render + one
#      buffered write per second on a side thread, nothing on the
#      request path.
#   3. RECORDER train A/B: the flagship train step with the trainer
#      ring armed (registry build + 1 Hz sampling; the sidecar port
#      stays off).  Prediction on record: <2% step-time delta — the
#      loop's own behavior is untouched, the sampler thread reads the
#      same objects the sidecar would.
#   4. incident drill: serve under load, SIGTERM mid-load → the
#      recorder's sigterm bundle exists and tools/incident.py renders
#      its timeline (rc 0) — the post-mortem path proven against a
#      TPU-backed server, not just the CPU harness.
#
# Per the pre-committed rule the recorder default stays OFF regardless
# of the numbers here (it is an operator knob, not a perf arm); the
# <2% predictions gate whether "arm it always in production" is free.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results15}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"
RECDIR="$R/flightrec"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r14 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. recorder serve A/B (prediction: <2% p50/throughput tax at the
#    default 1 Hz sampling).  Same shapes, same arms — the only delta
#    is the recorder knobs, which tag the vs_baseline key via --set.
run serve_rec_off 1500 $BENCH --config minet_r50_dp --mode serve \
    --steps 300 --set "serve.batch_buckets=1,4,8,16"
run serve_rec_on 1500 $BENCH --config minet_r50_dp --mode serve \
    --steps 300 --set "serve.batch_buckets=1,4,8,16" \
    --set serve.flight_recorder=true \
    --set serve.recorder_dir="$RECDIR/serve"

# -- 3. recorder train A/B (prediction: <2% step-time tax; the
#    trainer builds its registry + samples at 1 Hz, sidecar off).
run train_rec_off 900 $BENCH --config minet_r50_dp --batch-per-chip 64
run train_rec_on 1200 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set flight_recorder=true --set recorder_dir="$RECDIR/train"

# -- 4. incident drill: serve under load, SIGTERM → sigterm bundle +
#    offline timeline render against the TPU-backed server's ring.
incident_drill() {
  local dir="$RECDIR/drill"
  rm -rf "$dir"; mkdir -p "$dir"
  local pfile="$R/drill_port"
  rm -f "$pfile"
  timeout 600 python tools/serve.py --config minet_r50_dp --init-random \
      --device tpu --port 0 --port-file "$pfile" \
      --set serve.flight_recorder=true --set "serve.recorder_dir=$dir" \
      --set serve.recorder_sample_s=0.5 > "$R"/drill_serve.out 2>&1 &
  local spid=$!
  for _i in $(seq 1 240); do [ -f "$pfile" ] && break; sleep 1; done
  if [ ! -f "$pfile" ]; then
    echo '{"step": "incident_drill", "rc": 1, "result": {"error": "server never bound"}}' >> "$R"/results.jsonl
    kill -9 $spid 2>/dev/null; return
  fi
  local port; port=$(cat "$pfile")
  timeout 120 python tools/loadgen.py --url "http://127.0.0.1:$port" \
      --mode open --rps 20 --duration 10 --wait-ready 60 \
      > "$R"/drill_load.out 2>&1
  kill -TERM $spid; wait $spid
  local rc_drain=$?
  timeout 60 python tools/incident.py \
      --bundle "$(ls -t "$dir"/incidents/*.json.gz 2>/dev/null | head -1)" \
      --human > "$R"/drill_timeline.out 2>&1
  local rc_an=$?
  echo "{\"step\": \"incident_drill\", \"rc\": $((rc_drain || rc_an)), \"result\": {\"drain_rc\": $rc_drain, \"analyzer_rc\": $rc_an}}" >> "$R"/results.jsonl
}
if ! done_ok incident_drill; then incident_drill; fi

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
