#!/usr/bin/env python
"""Open/closed-loop load generator against a running serve instance —
prints ONE JSON summary line (docs/SERVING.md "Measuring throughput vs
p99").  No jax import: runs anywhere, including next to a TPU-bound
server.

The summary counts transport failures (connection refused/reset,
timeout, short body — a killed replica) SEPARATELY from HTTP-status
errors (a sick replica answering 5xx), both overall and in the
per-model breakdown, so failover/chaos experiments read cleanly.

    # capacity probe: 8 closed-loop workers, 200 requests
    python tools/loadgen.py --url http://127.0.0.1:8080 \
        --mode closed --concurrency 8 --requests 200

    # SLO probe: offer 50 rps for 30 s with a 200 ms deadline
    python tools/loadgen.py --url http://127.0.0.1:8080 \
        --mode open --rps 50 --duration 30 --slo-ms 200
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_sod_project_tpu.serve.loadgen import (  # noqa: E402
    fetch_stats, run_loadgen, run_stream_loadgen, wait_ready)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--url", required=True,
                   help="base URL, e.g. http://127.0.0.1:8080")
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed loop: parallel workers")
    p.add_argument("--requests", type=int, default=50,
                   help="closed loop: total requests")
    p.add_argument("--rps", type=float, default=10.0,
                   help="open loop: offered requests/sec")
    p.add_argument("--duration", type=float, default=5.0,
                   help="open loop: seconds of offered load")
    p.add_argument("--ramp", default=None, metavar="START:END:SECONDS",
                   help="open loop: sweep the offered rate linearly "
                        "from START to END rps over SECONDS (overrides "
                        "--rps; the summary appends a per-time-bucket "
                        "response curve — offered/done/ok/p99 — next "
                        "to the latency summary)")
    p.add_argument("--burst", action="append", default=[],
                   metavar="RPS:START:DUR",
                   help="open loop: add RPS extra offered rate for DUR "
                        "seconds starting at START (repeatable; stacks "
                        "on --rps or --ramp; shaped runs report the "
                        "response curve)")
    p.add_argument("--size", type=int, action="append", default=[],
                   help="square request image side (repeatable; "
                        "default 320)")
    p.add_argument("--zipf", default=None, metavar="S:CATALOG",
                   help="duplicate-traffic mix: draw each payload from "
                        "a catalog of CATALOG distinct structured "
                        "images with Zipf popularity p(k) ∝ 1/k^S "
                        "(e.g. --zipf 1.1:64) — the skewed repeat "
                        "distribution the router cache serves; the "
                        "summary gains hit-rate and the per-terminal-"
                        "class breakdown from X-Cache "
                        "(docs/SERVING.md \"Router cache\")")
    p.add_argument("--perturb", type=float, default=0.0,
                   metavar="FRAC",
                   help="with --zipf: send this fraction of draws as "
                        "resize-perturbed re-encodes of their catalog "
                        "image (same content, nearby resolution — "
                        "misses the exact cache arm, exercises the "
                        "near-dup arm); with --streams: the per-frame "
                        "SCENE-CUT probability (a cut forces a full "
                        "forward past the reuse gate)")
    p.add_argument("--streams", type=int, default=0, metavar="N",
                   help="streaming-video mode (docs/SERVING.md "
                        "\"Streaming\"): N concurrent clients, each "
                        "pushing a temporally-coherent frame train at "
                        "--fps under its own X-Stream-ID.  The summary "
                        "reports per-stream p99, inter-frame jitter, "
                        "and the reuse rate/latency split from "
                        "X-Stream-Reuse.  Overrides --mode; uses "
                        "--duration for the train length")
    p.add_argument("--fps", type=float, default=10.0,
                   help="streaming mode: frames/sec per stream")
    p.add_argument("--slo-ms", type=float, default=0.0,
                   help="per-request deadline sent as X-SLO-MS (0=none)")
    p.add_argument("--precision", default=None,
                   help="precision arm sent as X-Precision on every "
                        "request (must be enabled server-side; the "
                        "summary's per-arm breakdown reports what was "
                        "actually SERVED — the degraded ladder may "
                        "step it down)")
    p.add_argument("--model", default=None,
                   help="model routing key sent as X-Model on every "
                        "request (fleet router; single-model fleets "
                        "route header-less requests automatically)")
    p.add_argument("--tenant", default=None,
                   help="tenant sent as X-Tenant on every request "
                        "(fleet tenancy; default tenant when omitted)")
    p.add_argument("--mix", action="append", default=[],
                   metavar="MODEL[:TENANT]=WEIGHT",
                   help="mixed traffic: weighted per-model(/tenant) "
                        "request mix, repeatable (e.g. --mix minet=3 "
                        "--mix u2net:free=1).  Each request draws its "
                        "(model, tenant) from the mix; the summary "
                        "breaks p50/p95/p99 down per SERVED model, so "
                        "the fleet's mixed-model curve is one command")
    p.add_argument("--slowest", type=int, default=0,
                   help="report the N slowest OK responses with their "
                        "request/trace ids and the server-side stage "
                        "breakdown from X-Timing (queue/device/resize/"
                        "e2e ms) — a sampled row's trace id keys into "
                        "the server's /debug/traces "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=60.0,
                   help="per-request client timeout seconds")
    p.add_argument("--wait-ready", type=float, default=0.0,
                   help="poll /healthz up to this many seconds before "
                        "generating load (0 = don't wait)")
    p.add_argument("--server-stats", action="store_true",
                   help="append the server's /stats snapshot to the "
                        "summary line")
    p.add_argument("--slo", action="store_true",
                   help="scrape the server's /slo at the end of the "
                        "run and report per-objective (per-model/"
                        "per-tenant) budget-remaining and fast/slow "
                        "burn rates under \"slo\" next to the latency "
                        "summary (docs/OBSERVABILITY.md \"Capacity & "
                        "SLO\"; needs slo_objectives on the server)")
    p.add_argument("--quality", action="store_true",
                   help="scrape the per-model shadow-disagreement and "
                        "drift gauges from /metrics at the end of the "
                        "run and report them under \"quality\" — a "
                        "chaos/agenda leg records model quality "
                        "alongside latency (docs/OBSERVABILITY.md "
                        "\"Model health\"; needs serve.quality_monitor "
                        "on the server)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    url = args.url.rstrip("/")
    if args.wait_ready and not wait_ready(url, timeout_s=args.wait_ready):
        print(json.dumps({"error": f"server at {url} not ready after "
                                   f"{args.wait_ready}s"}), flush=True)
        return 1
    sizes = tuple((s, s) for s in (args.size or [320]))
    mix = None
    if args.mix:
        mix = []
        for spec in args.mix:
            if "=" not in spec:
                raise SystemExit(
                    f"--mix {spec!r} is not MODEL[:TENANT]=WEIGHT")
            key, weight = spec.rsplit("=", 1)
            model, _, tenant = key.partition(":")
            mix.append({"model": model, "tenant": tenant or None,
                        "weight": float(weight)})
    ramp = None
    if args.ramp:
        parts = args.ramp.split(":")
        if len(parts) != 3:
            raise SystemExit(f"--ramp {args.ramp!r} is not "
                             "START:END:SECONDS")
        ramp = (float(parts[0]), float(parts[1]), float(parts[2]))
    bursts = []
    for spec in args.burst:
        parts = spec.split(":")
        if len(parts) != 3:
            raise SystemExit(f"--burst {spec!r} is not RPS:START:DUR")
        bursts.append((float(parts[0]), float(parts[1]),
                       float(parts[2])))
    zipf = None
    if args.zipf:
        s, sep, cat = args.zipf.partition(":")
        if not sep:
            raise SystemExit(f"--zipf {args.zipf!r} is not S:CATALOG")
        zipf = (float(s), int(cat))
    if args.streams > 0:
        summary = run_stream_loadgen(
            url, streams=args.streams, fps=args.fps,
            duration_s=args.duration, sizes=sizes, seed=args.seed,
            perturb=args.perturb, slo_ms=args.slo_ms,
            timeout_s=args.timeout, precision=args.precision,
            model=args.model, tenant=args.tenant)
    else:
        summary = run_loadgen(
            url, mode=args.mode, concurrency=args.concurrency,
            requests=args.requests, rps=args.rps,
            duration_s=args.duration,
            sizes=sizes, seed=args.seed, slo_ms=args.slo_ms,
            timeout_s=args.timeout, precision=args.precision,
            model=args.model, tenant=args.tenant, mix=mix,
            slowest=args.slowest, quality=args.quality, slo=args.slo,
            ramp=ramp, bursts=bursts or None, zipf=zipf,
            perturb=args.perturb)
    if args.server_stats:
        try:
            summary["server"] = fetch_stats(url)
        except Exception as e:  # noqa: BLE001 — summary still prints
            summary["server_error"] = str(e)
    print(json.dumps(summary), flush=True)
    return 0 if summary.get("ok", 0) > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
