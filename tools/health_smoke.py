#!/usr/bin/env python
"""Model-health smoke for tools/t1.sh (docs/OBSERVABILITY.md "Model
health"): the new telemetry must survive REAL process boundaries, not
just in-process tests.  Two legs, real subprocesses, one JSON line:

- **trainer** — ``train.py`` with ``health_numerics=true`` + the
  telemetry sidecar, under an injected mid-run NaN
  (``DSOD_FAULTS=nan_grad@3``): the ``dsod_health_*`` families must
  appear on the sidecar /metrics, the ``numerics_nonfinite`` alert
  must FIRE with the non-finite parameter group attributed in its
  detail (visible at /alerts AND named in the degraded /healthz), and
  — the run being healthy again after the one poisoned step — must
  CLEAR after its hysteresis dwell.  SIGTERM then drains cleanly
  (exit 0).
- **serve** — ``tools/serve.py`` with ``serve.quality_monitor=true``
  and full shadow sampling on the bf16 arm: the ``dsod_quality_*``
  families must appear, shadow disagreement must be recorded (and
  stay inside the offline precision-gate budget), and an injected
  input drift (a burst of near-black frames against the checked-in
  reference histogram) must fire ``quality_drift_psi`` at /alerts and
  degrade /healthz.  (The drift alert's CLEAR transition is proven
  fake-clock deterministically in tests/test_quality_monitor.py —
  diluting a PSI histogram in real time would cost minutes of
  requests for no extra coverage.)

Budget contract: every internal deadline sums under t1.sh's 900 s
wrapper, so a stall reports its OWN diagnostic instead of dying to the
outer timeout.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _get_json(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def _get_text(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _wait_port(port_file: str, proc, deadline_s: float):
    deadline = time.monotonic() + deadline_s
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            return None, f"process died before binding (rc={proc.returncode})"
        if time.monotonic() > deadline:
            return None, "never bound a port"
        time.sleep(0.25)
    with open(port_file) as f:
        return int(f.read().strip()), None


def _poll(fn, deadline_s: float, poll_s: float = 0.5):
    """Poll ``fn()`` (truthy = done) until the deadline; returns the
    last truthy value or None."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            v = fn()
            if v:
                return v
        except Exception:  # noqa: BLE001 — endpoint mid-bind
            pass
        time.sleep(poll_s)
    return None


def trainer_leg(out: dict) -> bool:
    """Injected-NaN trainer run: families + provenance-attributed
    alert fire→clear on the live sidecar."""
    port_file = tempfile.mktemp(prefix="dsod_health_tport_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", DSOD_FAULTS="nan_grad@3")
    cmd = [sys.executable, os.path.join(REPO, "train.py"),
           "--config", "minet_vgg16_ref", "--device", "cpu",
           "--max-steps", "200",
           "--telemetry-port", "0", "--telemetry-port-file", port_file,
           "--workdir", tempfile.mkdtemp(prefix="dsod_health_ck_"),
           "--set", "model.name=vit_sod", "--set", "model.backbone=tiny",
           "--set", "model.sync_bn=false",
           "--set", "model.compute_dtype=float32",
           "--set", "data.image_size=32,32",
           "--set", "data.dataset=synthetic",
           "--set", "data.synthetic_size=32",
           "--set", "data.num_workers=0",
           "--set", "global_batch_size=8",
           "--set", "log_every_steps=1",
           "--set", "checkpoint_every_steps=100",
           "--set", "optim.skip_nonfinite=8",
           "--set", "health_numerics=true",
           "--set", "health_alert_clear_s=2"]
    proc = subprocess.Popen(cmd, env=env)
    try:
        port, err = _wait_port(port_file, proc, 240)
        if err:
            out["trainer_error"] = err
            return False
        base = f"http://127.0.0.1:{port}"

        def fired():
            snap = _get_json(base + "/alerts")
            for r in snap.get("rules", []):
                if r["rule"] == "numerics_nonfinite" and r["active"]:
                    return r
            return None

        rule = _poll(fired, 180)
        if not rule:
            out["trainer_error"] = "numerics_nonfinite never fired"
            return False
        out["trainer_alert_detail"] = rule.get("detail", "")
        health = _get_json(base + "/healthz")
        out["trainer_healthz"] = health.get("status")
        metrics = _get_text(base + "/metrics")
        out["trainer_families"] = sorted(
            {line.split()[2] for line in metrics.splitlines()
             if line.startswith("# TYPE dsod_health_")})
        ok = (health.get("status") == "degraded"
              and any("numerics_nonfinite" in a
                      for a in health.get("alerts", []))
              and "group=" in out["trainer_alert_detail"]
              and "dsod_health_nonfinite_group_total" in metrics
              and "dsod_health_grad_group_norm" in metrics)
        # The poisoned step is behind us: the alert must CLEAR after
        # its 2 s dwell of healthy steps.
        cleared = _poll(
            lambda: not _get_json(base + "/alerts")["active"], 120)
        out["trainer_alert_cleared"] = bool(cleared)
        ok = ok and bool(cleared)
        proc.send_signal(signal.SIGTERM)
        out["trainer_rc"] = proc.wait(timeout=150)
        return ok and out["trainer_rc"] == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if os.path.exists(port_file):
            os.unlink(port_file)


def _synthetic_request_images(n: int, hw: int = 64):
    """The first n synthetic-set images, denormalized to the uint8
    request shape — IN-distribution traffic vs the checked-in
    reference (tools/quality_reference.py uses the same set)."""
    import dataclasses

    import numpy as np

    from distributed_sod_project_tpu.configs import get_config
    from distributed_sod_project_tpu.data.folder import resolve_dataset

    cfg = get_config("minet_vgg16_ref")
    data_cfg = dataclasses.replace(cfg.data, dataset="synthetic",
                                   root=None, synthetic_size=max(n, 1),
                                   image_size=(hw, hw))
    ds = resolve_dataset(data_cfg)
    mean = np.asarray(cfg.data.normalize_mean, np.float32)
    std = np.asarray(cfg.data.normalize_std, np.float32)
    out = []
    for i in range(n):
        raw = np.clip(ds[i]["image"] * std + mean, 0.0, 1.0)
        out.append((raw * 255.0).round().astype(np.uint8))
    return out


def _post_npy(base: str, img, precision=None, timeout=60.0) -> int:
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, img)
    headers = {"Content-Type": "application/x-npy"}
    if precision:
        headers["X-Precision"] = precision
    req = urllib.request.Request(base + "/predict", data=buf.getvalue(),
                                 headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return r.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code


def serve_leg(out: dict) -> bool:
    """Quality monitors on a real server: families + live shadow
    disagreement, then an injected input drift fires the PSI alert."""
    import numpy as np

    port_file = tempfile.mktemp(prefix="dsod_health_sport_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.join(TOOLS, "serve.py"),
           "--config", "minet_vgg16_ref", "--init-random",
           "--device", "cpu", "--port", "0", "--port-file", port_file,
           "--set", "data.image_size=64,64",
           "--set", "serve.resolution_buckets=64",
           "--set", "serve.batch_buckets=1,2",
           "--set", "serve.precision_arms=f32,bf16",
           "--set", "serve.quality_monitor=true",
           "--set", "serve.quality_shadow_sample=1.0",
           # 8 in-distribution requests must be enough for a PSI
           # verdict here; production keeps the higher default floor.
           "--set", "serve.quality_psi_min_count=8",
           "--set", "serve.quality_alert_for_s=0.5",
           "--set", "serve.quality_alert_clear_s=2"]
    proc = subprocess.Popen(cmd, env=env)
    try:
        port, err = _wait_port(port_file, proc, 180)
        if err:
            out["serve_error"] = err
            return False
        base = f"http://127.0.0.1:{port}"
        from distributed_sod_project_tpu.serve.loadgen import (
            scrape_quality, wait_ready)

        if not wait_ready(base, timeout_s=60):
            out["serve_error"] = "server never became healthy"
            return False
        # Phase 1 — in-distribution bf16 traffic, every response
        # shadow-scored on f32.
        for img in _synthetic_request_images(8):
            if _post_npy(base, img, precision="bf16") != 200:
                out["serve_error"] = "in-distribution request failed"
                return False
        # >= 6 of 8, not 8 of 8: the bounded shadow lane may DROP under
        # contention on a 1-core box — that is its contract, and the
        # drop counter records it.
        quality = _poll(
            lambda: (lambda q: q if q.get("", {}).get(
                "shadow", {}).get("bf16", {}).get("n", 0) >= 6 else None)(
                scrape_quality(base)), 60)
        if not quality:
            out["serve_error"] = "shadow scores never appeared in /metrics"
            return False
        shadow = quality[""]["shadow"]["bf16"]
        out["serve_shadow"] = shadow
        # Live disagreement must sit inside the offline gate's budget
        # band (bf16 vs f32 is a rounding effect; the recorded offline
        # delta is ~1e-6 — anything past the alert budget is a bug).
        ok = shadow["mae_avg"] < 0.02 and quality[""].get("psi") is not None
        # Phase 2 — injected drift: near-black frames push the
        # input_mean histogram off the reference.
        dark = np.full((64, 64, 3), 4, np.uint8)
        for _ in range(10):
            if _post_npy(base, dark, precision="bf16") != 200:
                out["serve_error"] = "drift request failed"
                return False

        def drift_fired():
            snap = _get_json(base + "/alerts")
            return ("quality_drift_psi" in snap.get("active", [])
                    and snap) or None

        fired = _poll(drift_fired, 60)
        if not fired:
            out["serve_error"] = "quality_drift_psi never fired"
            return False
        health = _get_json(base + "/healthz")
        out["serve_healthz"] = health.get("status")
        out["serve_psi"] = scrape_quality(base).get("", {}).get("psi")
        ok = (ok and health.get("status") == "degraded"
              and any("quality_drift_psi" in a
                      for a in health.get("alerts", [])))
        proc.send_signal(signal.SIGTERM)
        out["serve_rc"] = proc.wait(timeout=60)
        return ok and out["serve_rc"] == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if os.path.exists(port_file):
            os.unlink(port_file)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--leg", default="both",
                   choices=["both", "trainer", "serve"])
    args = p.parse_args(argv)
    out: dict = {"metric": "health_smoke"}
    ok = True
    if args.leg in ("both", "trainer"):
        out["trainer_ok"] = trainer_leg(out)
        ok = ok and out["trainer_ok"]
    if args.leg in ("both", "serve"):
        out["serve_ok"] = serve_leg(out)
        ok = ok and out["serve_ok"]
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
