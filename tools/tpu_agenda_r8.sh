#!/bin/bash
# Round-8 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 8).  Round 8 landed the low-precision serving fast path
# (serve/precision.py: bf16/int8 cast-on-load weight views, per-arm AOT
# programs in the serve cache, a precision-first degraded ladder —
# docs/SERVING.md "Precision arms").  Quality is already gated on CPU
# (tools/precision_gate.py ledger); what only hardware can answer:
#
#   1. canonical b128 headline refresh (comparison anchor; untouched by
#      the serving work, so any drift is environmental)
#   2. per-arm serve bench: bench --mode serve once per precision arm —
#      the per-chip img/s lever ROADMAP item #3 priced.  Each leg's
#      --set serve.precision tag keys its own baseline, so arms never
#      contaminate each other's vs_baseline
#   3. the per-arm throughput-vs-p99 curve: ONE long-lived server with
#      all arms warmed, swept closed-loop per arm at rising concurrency
#      (loadgen --precision splits the curve), to read where the bf16/
#      int8 knee sits vs f32 — the measured answer to "what does a
#      precision rung buy before the ladder trades resolution"
#   4. SLO behavior under pressure: OPEN-loop legs at fixed offered
#      rates with a 500 ms deadline, per arm — shed/expired counts + the
#      served-arm breakdown tell whether the ladder actually converts
#      overload into precision downshifts before resolution downshifts
#
# Predictions on record (docs/PERFORMANCE.md "Precision arms"): bf16
# serve throughput +10-25% over f32 at the b8-bucket operating point
# (weight HBM halves but activations dominate a 320px conv net); int8
# within ±10% of bf16 on v5e (no native int8 conv path — the win is
# weight residency, the cost is the dequant epilogue).  If int8 LOSES
# to bf16 by >10%, drop it from the default ladder; the knob structure
# survives either outcome.
#
# Serve legs talk to ONE server process started here (ephemeral port,
# --port-file); loadgen itself never imports jax, so only the server
# occupies the TPU.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results8}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

# Circuit breaker (r4 pattern): after any failed leg, verify the
# tunnel still runs REAL compute; abort the firing if not (the
# watcher re-fires in the next window and done_ok() skips landed legs).
tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r7 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. per-arm serve bench: each --set serve.precision tag keys its
#       own baseline (bench folds overrides into the vs_baseline key).
for arm in f32 bf16 int8; do
  run "serve_bench_$arm" 900 $BENCH --mode serve --config minet_r50_dp \
      --steps 200 --warmup 8 \
      --set "serve.precision=$arm" \
      --set "serve.precision_arms=f32,bf16,int8"
done

# -- 3+4. per-arm throughput-vs-p99 curve against ONE long-lived
#         server with every arm AOT-warmed.
SERVE_PORT_FILE="$R/serve.port"
rm -f "$SERVE_PORT_FILE"
python tools/serve.py --config minet_r50_dp --init-random --device tpu \
  --port 0 --port-file "$SERVE_PORT_FILE" \
  --set "serve.batch_buckets=1,4,8,16" \
  --set "serve.precision_arms=f32,bf16,int8" \
  > "$R"/serve_server.out 2> "$R"/serve_server.err &
SERVE_PID=$!
for _ in $(seq 1 120); do [ -f "$SERVE_PORT_FILE" ] && break; sleep 2; done
if [ -f "$SERVE_PORT_FILE" ]; then
  URL="http://127.0.0.1:$(cat "$SERVE_PORT_FILE")"
  LG="python tools/loadgen.py --url $URL --wait-ready 600 --size 320"
  # closed-loop concurrency sweep per arm: the (throughput, p99) curve,
  # split by precision — smaller c-grid than r7 so three arms still fit
  # a short tunnel window (the r7 f32 curve anchors the fine grid).
  for arm in f32 bf16 int8; do
    for c in 1 8 32; do
      run "serve_closed_${arm}_c$c" 900 $LG --mode closed \
          --precision "$arm" --concurrency "$c" --requests 200
    done
  done
  # open-loop SLO probes at fixed offered rates with a 500 ms deadline,
  # per arm — the served-arm breakdown in the summary shows whether the
  # ladder stepped precision down under pressure.
  for arm in f32 bf16; do
    for rps in 60 120; do
      run "serve_open_${arm}_rps$rps" 900 $LG --mode open \
          --precision "$arm" --rps "$rps" --duration 20 \
          --slo-ms 500 --server-stats
    done
  done
  kill -TERM "$SERVE_PID" 2>/dev/null
  wait "$SERVE_PID"
  echo "{\"step\": \"serve_server_drain\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl
else
  echo "serve server never bound a port — skipping curve legs" | tee -a "$R"/agenda.log
  kill -9 "$SERVE_PID" 2>/dev/null
fi

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
