#!/bin/bash
# Round-5 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically).  Round 5 landed the
# fine-resolution decoder work (docs/PERFORMANCE.md ranked levers #1
# and #2): the Pallas fused resample-merge kernel behind
# model.resample_impl=fused, the layout-stable upsample interleave
# (default; DSOD_RESIZE_INTERLEAVE=stack is the old arm), and the
# per-site roofline ledger (tools/roofline.py --resize fused) every
# fused leg here is queued against.  Pre-committed rule: the fused arm
# becomes a default ONLY if its A/B beats the fast arm beyond noise at
# the canonical operating point; the interleave default already
# flipped (bit-identical, strictly fewer formatting ops per
# tools/hlo_guard.py) and the stack leg here quantifies the win.
#
# Ordered by value-per-minute; every leg is a bounded subprocess whose
# JSON lands in $R/results.jsonl the moment it finishes.  Any r4 legs
# still lacking numbers (tools/tpu_agenda_r4.sh) can be re-fired after
# this agenda drains — this one carries ONLY the round-5 questions:
#
#   1. canonical b128 headline refresh (the comparison anchor)
#   2. fused resample A/B  — flagship b128/b64(+remat)/b32, the
#      roofline ledger's falsifiable total (~1.6 ms ideal at b64,
#      more if the 160/80 conv-fusion pressure drops as lever #1
#      predicts)
#   3. interleave A/B      — layout-stable (default) vs stack form:
#      isolates the relayout-copy win (~10-27 ms/step predicted from
#      the round-2 trace's data-formatting bucket)
#   4. convt cross-check   — the r4 third arm under the NEW knob
#      (model.resample_impl=convt), so all three arms share one key
#      scheme
#   5. zoo fused legs      — u2net / gatenet / hdfnet decoder users
#   6. profile of the best fused arm for the roofline reconciliation
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results5}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

# Circuit breaker (r4 pattern): after any failed leg, verify the
# tunnel still runs REAL compute; abort the firing if not (the
# watcher re-fires in the next window and done_ok() skips landed legs).
tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (this round's comparison anchor;
#       fresh key, self-reported mfu).  NOTE: the layout-stable
#       interleave is now the default, so this number already contains
#       lever #2 — leg 3 isolates it.
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. fused resample-merge A/B (model.resample_impl is a --set
#       override, so bench keys the arms apart automatically).  The
#       ledger prediction to beat is printed by
#       `python tools/roofline.py --batch <b> --resize fused`.
run rsmpl_fused_b128  900 $BENCH --config minet_r50_dp --set model.resample_impl=fused
run rsmpl_fused_b64r  900 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set model.resample_impl=fused --set model.remat=true
run rsmpl_fused_b32   900 $BENCH --config minet_r50_dp --batch-per-chip 32 \
    --set model.resample_impl=fused
# fast-arm twins for the non-canonical operating points (b128 fast is
# the headline leg above)
run rsmpl_fast_b64r   900 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set model.remat=true
run rsmpl_fast_b32    900 $BENCH --config minet_r50_dp --batch-per-chip 32

# -- 3. interleave A/B: the stack+reshape arm (env-tagged key via
#       DSOD_RESIZE_INTERLEAVE in bench's _PROGRAM_ENV_VARS).  The
#       delta vs headline_b128 is lever #2 in milliseconds.
export DSOD_RESIZE_INTERLEAVE=stack
run ilv_stack_b128 900 $BENCH --config minet_r50_dp
run ilv_stack_b64r 900 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set model.remat=true
unset DSOD_RESIZE_INTERLEAVE

# -- 4. convt cross-check under the knob (replaces the r4 env-arm
#       spelling; numerics-identical, key differs only in the --set)
run rsmpl_convt_b128 900 $BENCH --config minet_r50_dp --set model.resample_impl=convt

# -- 5. zoo decoder users: fused vs default at their standard batches.
run u2net_fused    900 $BENCH --config u2net_ds   --set model.resample_impl=fused
run u2net_fast     900 $BENCH --config u2net_ds
run gatenet_fused  900 $BENCH --config gatenet_vgg16 --set model.resample_impl=fused
run gatenet_fast   900 $BENCH --config gatenet_vgg16
run hdfnet_fused   900 $BENCH --config hdfnet_rgbd --set model.resample_impl=fused
run hdfnet_fast    900 $BENCH --config hdfnet_rgbd

# -- 6. profile the fused flagship for the roofline reconciliation
#       (did the 160/80 buckets move toward streaming bandwidth?)
run prof_fused_b128 900 $BENCH --config minet_r50_dp \
    --set model.resample_impl=fused --profile-dir "$R"/trace_fused_b128

# Host-side analysis (no tunnel needed): trace buckets + the
# prediction-vs-measured table for docs/PERFORMANCE.md.
run an_fused  600 python tools/analyze_trace.py "$R"/trace_fused_b128 --top 25
run rl_fused  600 python tools/roofline.py --batch 128 --resize fused \
    --trace "$R"/trace_fused_b128

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
