#!/bin/bash
# Round-13 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 13).  Round 13 landed CAPACITY & SLO observability: the live
# per-compiled-program cost ledger (utils/capacity.py →
# dsod_capacity_* MFU/roofline/HBM gauges from each executable's own
# cost_analysis + the measured device EWMA), declarative SLO
# objectives with multi-window burn-rate accounting (utils/slo.py →
# dsod_slo_* + /slo on router/server/sidecar), and the synthetic
# canary prober (serve/prober.py → dsod_probe_*, zero-traffic outage
# detection) — docs/OBSERVABILITY.md "Capacity & SLO".  Correctness is
# proven on CPU (tests/test_capacity.py, tests/test_slo.py,
# tools/slo_smoke.py: burn alert fires at zero live traffic off
# canaries alone, /slo ≡ router book, ledger ≡ cost_analysis on the
# same executable); what only hardware can answer:
#
#   1. canonical b128 headline refresh (comparison anchor)
#   2. LEDGER-OVERHEAD serve A/B: the same closed-loop serve bench
#      with serve.capacity_ledger off vs on — the ledger reads
#      cost_analysis ONCE per program at warmup and pays one EWMA
#      fold per completed batch, so the tax should be unmeasurable.
#   3. PROBER-OVERHEAD fleet A/B: open-loop loadgen against a
#      router+engine fleet with the prober off vs on at 1 probe/s —
#      probes are admitted traffic, so the cost model is "one extra
#      b1 forward per second", amortized invisible at load.
#   4. live capacity/SLO leg: loadgen --slo against the armed fleet
#      records budget/burn next to the latency curve; /slo, /alerts,
#      and metrics_lint --url check the live surface; the REAL
#      per-program MFU numbers land in serve_capacity.json — the
#      first measured live-MFU table for the serving stack.
#
# Predictions on record (docs/OBSERVABILITY.md "Capacity & SLO"):
# (a) serve p50 tax with capacity_ledger on: < 2% (one dict EWMA fold
#     per completed batch on the fetch thread, off the request path);
# (b) open-loop p50/p99 tax with the prober at 1/s: < 2% (one extra
#     batch-1 forward per second ≈ <1% device occupancy at b128-class
#     throughput; probes shed first under overload by tenant class);
# (c) live dsod_capacity_mfu at b128 within ±20% of bench.py's own
#     MFU self-report for the same shapes (they share peak constants;
#     the ledger divides by the device EWMA, bench by wall time).
#
# Serve legs talk to processes started here (ephemeral ports,
# --port-file); loadgen itself never imports jax.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results13}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r12 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. ledger-overhead serve A/B (prediction (a)).
run serve_ledger_off 1500 $BENCH --config minet_r50_dp --mode serve \
    --steps 300 --set "serve.batch_buckets=1,4,8,16"
run serve_ledger_on 1500 $BENCH --config minet_r50_dp --mode serve \
    --steps 300 --set "serve.batch_buckets=1,4,8,16" \
    --set serve.capacity_ledger=true

# -- 3+4. prober-overhead fleet A/B + the live capacity/SLO surface.
#    One fleet process per arm (prober off / on); open-loop loadgen at
#    the same offered rate against each; the armed arm also records
#    /slo, /alerts, live MFU, and the live-inventory lint.
fleet_leg() { # fleet_leg NAME EXTRA_FLEET_JSON_FIELDS
  local name=$1 extra=$2
  local pfile="$R/${name}.port"
  local ffile="$R/${name}.fleet.json"
  rm -f "$pfile"
  cat > "$ffile" <<EOF
{"models": [{"name": "minet", "config": "minet_r50_dp",
             "overrides": ["serve.batch_buckets=1,4,8,16",
                           "serve.capacity_ledger=true"]}]$extra}
EOF
  python tools/serve.py --fleet-config "$ffile" --device tpu \
    --port 0 --port-file "$pfile" \
    > "$R/${name}.out" 2> "$R/${name}.err" &
  FLEET_PID=$!
  for _ in $(seq 1 240); do [ -f "$pfile" ] && break; sleep 2; done
  if [ ! -f "$pfile" ]; then
    echo "$name never bound a port — skipping" | tee -a "$R"/agenda.log
    kill -9 "$FLEET_PID" 2>/dev/null
    return 1
  fi
  FURL="http://127.0.0.1:$(cat "$pfile")"
  return 0
}

if fleet_leg fleet_prober_off ""; then
  run prober_off_loadgen 900 python tools/loadgen.py --url "$FURL" \
      --mode open --rps 50 --duration 30 --wait-ready 240
  kill -TERM "$FLEET_PID" 2>/dev/null; wait "$FLEET_PID"
fi
if fleet_leg fleet_prober_on ', "prober_interval_s": 1.0,
    "slo_objectives": ["avail:model=minet:availability:0.999:3600",
                       "fast:model=minet:latency:0.95:3600:500"]'; then
  run prober_on_loadgen 900 python tools/loadgen.py --url "$FURL" \
      --mode open --rps 50 --duration 30 --wait-ready 240 --slo
  run slo_endpoint 60 curl -sf "$FURL/slo"
  run slo_alerts 60 curl -sf "$FURL/alerts"
  run serve_capacity 60 sh -c "curl -sf $FURL/metrics | grep dsod_capacity_ > $R/serve_capacity.json && echo '{\"metric\": \"serve_capacity\", \"recorded\": true}'"
  run slo_lint 120 python tools/metrics_lint.py --url "$FURL"
  kill -TERM "$FLEET_PID" 2>/dev/null; wait "$FLEET_PID"
  echo "{\"step\": \"fleet_prober_exit\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl
fi

# -- trainer-side capacity ledger + goodput SLO: a short REAL fit()
#    window (bench's step-bench bypasses the loop, and the ledger/SLO
#    live in the loop) with the sidecar up; record live train MFU and
#    /slo, then drain.  The A/B cost of the ledger's one extra AOT
#    compile per shape is visible in the startup gap vs train_health
#    legs of r12 (same config, no ledger).
TPORT_FILE="$R/train_capacity.port"
rm -f "$TPORT_FILE"
timeout 1200 python train.py --config minet_r50_dp --device tpu \
  --max-steps 60 --telemetry-port 0 --telemetry-port-file "$TPORT_FILE" \
  --workdir "$R/train_capacity_ck" \
  --set capacity_ledger=true \
  --set "slo_objectives=goodput:all:latency:0.99:600:2000" \
  --set log_every_steps=20 --set checkpoint_every_steps=60 \
  > "$R"/train_capacity.out 2> "$R"/train_capacity.err &
TRAIN_PID=$!
for _ in $(seq 1 300); do [ -f "$TPORT_FILE" ] && break; sleep 2; done
if [ -f "$TPORT_FILE" ]; then
  TURL="http://127.0.0.1:$(cat "$TPORT_FILE")"
  sleep 60  # past compile + warmup so the MFU EWMA is fed
  run train_capacity_metrics 60 sh -c "curl -sf $TURL/metrics | grep dsod_capacity_ > $R/train_capacity_mfu.txt && echo '{\"metric\": \"train_capacity\", \"recorded\": true}'"
  run train_slo 60 curl -sf "$TURL/slo"
fi
wait "$TRAIN_PID"
echo "{\"step\": \"train_capacity_exit\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
