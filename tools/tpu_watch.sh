#!/bin/bash
# Probe the axon TPU tunnel in a loop; the moment it answers, run the
# current round's agenda and exit.  Run in the background at session
# start — the tunnel's observed behavior is "wedged now, back later in
# the session" and the window can be short.
#
#   mkdir -p tpu_results4 && \
#     nohup bash tools/tpu_watch.sh > tpu_results4/watch.out 2>&1 &
#
# AGENDA / RDIR select the agenda script and results dir (default: the
# current round's).  RDIR is forwarded to the agenda as R; only
# tpu_agenda_r4.sh and later honor it — the frozen r2/r3 agendas
# hardcode their own results dir and ignore R.  The probe is a
# throwaway subprocess under timeout: a wedged tunnel hangs PJRT
# client creation indefinitely and only an out-of-process dial
# converts that into a retryable failure (see bench.py).
#
# Unit-test hooks (tests/test_tools.py): the probe parser and the
# circuit-breaker decision are pure functions, callable directly —
#   tools/tpu_watch.sh parse-probe "<raw probe output>"
#       -> "PROBE OK <platform>" (exit 0) | "PROBE WEDGED <raw>" (exit 1)
#   tools/tpu_watch.sh decide <firings> <max_firings> <bad> <err>
#       -> "DONE" | "BUDGET_SPENT" | "REFIRE"
#   tools/tpu_watch.sh count-results <results.jsonl>
#       -> "<bad> <err>" (single line, integers; missing file -> "0 0")
cd "$(dirname "$0")/.." || exit 1

# The probe must run REAL compute, not just enumerate devices: the
# 2026-08-02 window showed the tunnel answering jax.devices() in <5s
# while every dispatched program (even a 1024x1024 matmul) wedged
# forever.  An enumerate-only probe would burn an agenda firing
# (MAX_FIRINGS budget) on a tunnel that cannot execute anything.
run_probe() {
  timeout 100 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print(d.platform)" 2>/dev/null | tail -1
}

# Classify the probe's raw output into the one-line parseable contract.
# OK requires BOTH: the matmul completed (any output at all) AND the
# platform is an accelerator — a cpu fallback answering the probe is
# NOT a usable window.
probe_parse() {
  local raw="$1"
  case "$raw" in
    tpu|TPU|axon)
      echo "PROBE OK $raw"
      return 0
      ;;
    *)
      echo "PROBE WEDGED ${raw:-timeout}"
      return 1
      ;;
  esac
}

# Circuit breaker after an agenda firing: stop when every leg is clean
# (DONE) or the firing budget is spent (BUDGET_SPENT); otherwise keep
# probing for another window (REFIRE).  Pure decision on counts so the
# policy is unit-testable without a tunnel.
decide() {
  local firings="$1" max_firings="$2" bad="$3" err="$4"
  if [ "$bad" -eq 0 ] && [ "$err" -eq 0 ]; then
    echo "DONE"
  elif [ "$firings" -ge "$max_firings" ]; then
    echo "BUDGET_SPENT"
  else
    echo "REFIRE"
  fi
}

# Leg-result counts for decide(), as ONE line of two integers.
# grep -c prints "0" AND exits 1 when nothing matches, so a naive
# `|| echo 0` yields the two-line "0\n0" and breaks decide's integer
# tests; default only the missing-file case (grep prints nothing).
count_results() {
  local f="$1" bad err
  bad=$(grep -cv '"rc": 0' "$f" 2>/dev/null); bad=${bad:-0}
  err=$(grep -c '"error"' "$f" 2>/dev/null); err=${err:-0}
  echo "$bad $err"
}

case "$1" in
  parse-probe)
    probe_parse "$2"
    exit $?
    ;;
  decide)
    decide "$2" "$3" "$4" "$5"
    exit 0
    ;;
  count-results)
    count_results "$2"
    exit 0
    ;;
esac

AGENDA=${AGENDA:-tools/tpu_agenda_r19.sh}
RDIR=${RDIR:-tpu_results19}
mkdir -p "$RDIR"
MAX_HOURS=${MAX_HOURS:-11}
MAX_FIRINGS=${MAX_FIRINGS:-3}
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
n=0
firings=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  n=$((n + 1))
  verdict=$(probe_parse "$(run_probe)")
  echo "$(date -u +%FT%TZ) probe $n: $verdict" >> "$RDIR/watch.log"
  case "$verdict" in
    "PROBE OK"*)
      firings=$((firings + 1))
      echo "$(date -u +%FT%TZ) tunnel UP — agenda firing $firings/$MAX_FIRINGS" >> "$RDIR/watch.log"
      R="$RDIR" bash "$AGENDA"
      # The agenda skips legs that already succeeded, so a re-fire in
      # a later window only runs what's missing.  Keep probing until
      # every leg has a clean record or the firing budget is spent —
      # the observed tunnel serves SHORT windows, and exiting after a
      # partial one (the r3 design) would waste any second window.
      read -r bad err <<< "$(count_results "$RDIR/results.jsonl")"
      echo "$(date -u +%FT%TZ) agenda firing $firings done (nonzero-rc: $bad, error-results: $err)" >> "$RDIR/watch.log"
      case "$(decide "$firings" "$MAX_FIRINGS" "$bad" "$err")" in
        DONE)
          echo "$(date -u +%FT%TZ) all legs clean — watcher done" >> "$RDIR/watch.log"
          exit 0
          ;;
        BUDGET_SPENT)
          echo "$(date -u +%FT%TZ) firing budget spent with failed legs remaining" >> "$RDIR/watch.log"
          exit 0
          ;;
      esac
      sleep 120
      ;;
    *)
      sleep 60
      ;;
  esac
done
echo "$(date -u +%FT%TZ) gave up after ${MAX_HOURS}h" >> "$RDIR/watch.log"
exit 1
