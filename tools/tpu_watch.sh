#!/bin/bash
# Probe the axon TPU tunnel in a loop; the moment it answers, run the
# round-3 agenda (tools/tpu_agenda_r3.sh) and exit.  Run in the
# background at session start — the tunnel's observed behavior is
# "wedged now, back later in the session" and the window can be short.
#
#   nohup bash tools/tpu_watch.sh > tpu_results3/watch.out 2>&1 &
#
# The probe is a throwaway subprocess under timeout: a wedged tunnel
# hangs PJRT client creation indefinitely and only an out-of-process
# dial converts that into a retryable failure (see bench.py).
cd "$(dirname "$0")/.." || exit 1
mkdir -p tpu_results3
MAX_HOURS=${MAX_HOURS:-11}
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
n=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  n=$((n + 1))
  plat=$(timeout 100 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
  case "$plat" in
    tpu|TPU|axon)
      echo "$(date -u +%FT%TZ) probe $n: tunnel UP ($plat) — starting agenda" >> tpu_results3/watch.log
      bash tools/tpu_agenda_r3.sh
      echo "$(date -u +%FT%TZ) agenda finished" >> tpu_results3/watch.log
      exit 0
      ;;
    *)
      echo "$(date -u +%FT%TZ) probe $n: down (got '${plat:-wedge/timeout}')" >> tpu_results3/watch.log
      sleep 60
      ;;
  esac
done
echo "$(date -u +%FT%TZ) gave up after ${MAX_HOURS}h" >> tpu_results3/watch.log
exit 1
