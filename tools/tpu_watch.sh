#!/bin/bash
# Probe the axon TPU tunnel in a loop; the moment it answers, run the
# current round's agenda and exit.  Run in the background at session
# start — the tunnel's observed behavior is "wedged now, back later in
# the session" and the window can be short.
#
#   mkdir -p tpu_results4 && \
#     nohup bash tools/tpu_watch.sh > tpu_results4/watch.out 2>&1 &
#
# AGENDA / RDIR select the agenda script and results dir (default: the
# current round's).  RDIR is forwarded to the agenda as R; only
# tpu_agenda_r4.sh and later honor it — the frozen r2/r3 agendas
# hardcode their own results dir and ignore R.  The probe is a
# throwaway subprocess under timeout: a wedged tunnel hangs PJRT
# client creation indefinitely and only an out-of-process dial
# converts that into a retryable failure (see bench.py).
cd "$(dirname "$0")/.." || exit 1
AGENDA=${AGENDA:-tools/tpu_agenda_r4.sh}
RDIR=${RDIR:-tpu_results4}
mkdir -p "$RDIR"
MAX_HOURS=${MAX_HOURS:-11}
MAX_FIRINGS=${MAX_FIRINGS:-3}
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
n=0
firings=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  n=$((n + 1))
  # The probe must run REAL compute, not just enumerate devices: the
  # 2026-08-02 window showed the tunnel answering jax.devices() in <5s
  # while every dispatched program (even a 1024x1024 matmul) wedged
  # forever.  An enumerate-only probe would burn an agenda firing
  # (MAX_FIRINGS budget) on a tunnel that cannot execute anything.
  plat=$(timeout 100 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print(d.platform)" 2>/dev/null | tail -1)
  case "$plat" in
    tpu|TPU|axon)
      firings=$((firings + 1))
      echo "$(date -u +%FT%TZ) probe $n: tunnel UP ($plat) — agenda firing $firings/$MAX_FIRINGS" >> "$RDIR/watch.log"
      R="$RDIR" bash "$AGENDA"
      # The agenda skips legs that already succeeded, so a re-fire in
      # a later window only runs what's missing.  Keep probing until
      # every leg has a clean record or the firing budget is spent —
      # the observed tunnel serves SHORT windows, and exiting after a
      # partial one (the r3 design) would waste any second window.
      bad=$(grep -cv '"rc": 0' "$RDIR/results.jsonl" 2>/dev/null || echo 0)
      err=$(grep -c '"error"' "$RDIR/results.jsonl" 2>/dev/null || echo 0)
      echo "$(date -u +%FT%TZ) agenda firing $firings done (nonzero-rc: $bad, error-results: $err)" >> "$RDIR/watch.log"
      if [ "$bad" -eq 0 ] && [ "$err" -eq 0 ]; then
        echo "$(date -u +%FT%TZ) all legs clean — watcher done" >> "$RDIR/watch.log"
        exit 0
      fi
      if [ "$firings" -ge "$MAX_FIRINGS" ]; then
        echo "$(date -u +%FT%TZ) firing budget spent with failed legs remaining" >> "$RDIR/watch.log"
        exit 0
      fi
      sleep 120
      ;;
    *)
      echo "$(date -u +%FT%TZ) probe $n: down (got '${plat:-wedge/timeout}')" >> "$RDIR/watch.log"
      sleep 60
      ;;
  esac
done
echo "$(date -u +%FT%TZ) gave up after ${MAX_HOURS}h" >> "$RDIR/watch.log"
exit 1
