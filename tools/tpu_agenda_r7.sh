#!/bin/bash
# Round-7 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 7).  Round 7 landed the online serving subsystem (serve/:
# dynamic micro-batching over AOT-compiled bucket programs, admission
# control, SLO shedding, hot weight reload — docs/SERVING.md).  The
# questions this agenda answers:
#
#   1. canonical b128 headline refresh (comparison anchor; untouched
#      by the serving work, so any drift is environmental)
#   2. bench --mode serve: serving throughput + latency tail through
#      the full HTTP stack, joining the recorded perf trajectory
#   3. the throughput-vs-p99 curve: a long-lived server (flagship
#      model, 320px) swept with the CLOSED-loop generator at rising
#      concurrency — each leg records (throughput, p99) so the curve's
#      knee (where added concurrency buys latency, not throughput)
#      prices the static batch buckets
#   4. SLO behavior at the knee: OPEN-loop legs at fixed offered rates
#      with a 500 ms deadline — shed/expired counts tell whether
#      admission control holds p99 by rejecting, not by queueing
#
# Serve legs talk to ONE server process started here (ephemeral port,
# --port-file); loadgen itself never imports jax, so only the server
# occupies the TPU.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results7}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

# Circuit breaker (r4 pattern): after any failed leg, verify the
# tunnel still runs REAL compute; abort the firing if not (the
# watcher re-fires in the next window and done_ok() skips landed legs).
tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5/r6 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. serving throughput joins the recorded trajectory
run serve_bench 900 $BENCH --mode serve --config minet_r50_dp --steps 200 --warmup 8

# -- 3+4. throughput-vs-p99 curve against ONE long-lived server.
SERVE_PORT_FILE="$R/serve.port"
rm -f "$SERVE_PORT_FILE"
python tools/serve.py --config minet_r50_dp --init-random --device tpu \
  --port 0 --port-file "$SERVE_PORT_FILE" \
  --set "serve.batch_buckets=1,4,8,16" \
  > "$R"/serve_server.out 2> "$R"/serve_server.err &
SERVE_PID=$!
for _ in $(seq 1 120); do [ -f "$SERVE_PORT_FILE" ] && break; sleep 2; done
if [ -f "$SERVE_PORT_FILE" ]; then
  URL="http://127.0.0.1:$(cat "$SERVE_PORT_FILE")"
  LG="python tools/loadgen.py --url $URL --wait-ready 600 --size 320"
  # closed-loop concurrency sweep: the (throughput, p99) curve
  for c in 1 4 8 16 32; do
    run "serve_closed_c$c" 900 $LG --mode closed --concurrency "$c" --requests 200
  done
  # open-loop SLO probes at fixed offered rates with a 500 ms deadline
  for rps in 20 60 120; do
    run "serve_open_rps$rps" 900 $LG --mode open --rps "$rps" --duration 20 \
        --slo-ms 500 --server-stats
  done
  kill -TERM "$SERVE_PID" 2>/dev/null
  wait "$SERVE_PID"
  echo "{\"step\": \"serve_server_drain\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl
else
  echo "serve server never bound a port — skipping curve legs" | tee -a "$R"/agenda.log
  kill -9 "$SERVE_PID" 2>/dev/null
fi

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
