#!/usr/bin/env python
"""Serve smoke for tools/t1.sh: start tools/serve.py as a real
subprocess on an ephemeral port, push one request round-trip through
tools/loadgen.py's machinery, then SIGTERM and assert a CLEAN shutdown
(exit 0).  Prints one JSON line; exits non-zero on any broken link.

Budget contract: the internal deadlines (120 s bind incl. AOT warm +
60 s healthz + 60 s requests + 60 s drain) sum under t1.sh's 420 s
wrapper, so a stall always reports its OWN JSON diagnostic instead of
dying to the outer timeout mid-wait.

Deliberately out-of-process: the smoke must exercise the same process
lifecycle a deployment does (signal handling, drain, port-file), not an
in-process thread server (tests/test_serving.py covers that side).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_sod_project_tpu.serve.loadgen import (  # noqa: E402
    run_loadgen, wait_ready)

TOOLS = os.path.dirname(os.path.abspath(__file__))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--precision", default=None,
                   help="serve at this precision arm: sets "
                        "serve.precision on the server AND sends "
                        "X-Precision on every request, then asserts "
                        "the per-arm breakdown shows every response "
                        "was served at that arm (t1.sh runs the bf16 "
                        "leg)")
    args = p.parse_args(argv)
    port_file = tempfile.mktemp(prefix="dsod_serve_port_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.join(TOOLS, "serve.py"),
           "--config", "minet_vgg16_ref", "--init-random", "--device", "cpu",
           "--port", "0", "--port-file", port_file,
           "--set", "data.image_size=64,64",
           "--set", "serve.resolution_buckets=64",
           "--set", "serve.batch_buckets=1,2"]
    if args.precision:
        cmd += ["--set", f"serve.precision={args.precision}"]
    proc = subprocess.Popen(cmd, env=env)
    try:
        deadline = time.monotonic() + 120
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                print(json.dumps({"error": "server died before binding",
                                  "rc": proc.returncode}), flush=True)
                return 1
            if time.monotonic() > deadline:
                print(json.dumps({"error": "server never bound a port"}),
                      flush=True)
                return 1
            time.sleep(0.25)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read().strip())}"
        if not wait_ready(url, timeout_s=60):
            print(json.dumps({"error": "server never became healthy"}),
                  flush=True)
            return 1
        summary = run_loadgen(url, mode="closed", concurrency=1,
                              requests=2, sizes=((48, 56),), seed=0,
                              timeout_s=60, precision=args.precision)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        summary["server_rc"] = rc
        print(json.dumps(summary), flush=True)
        ok = summary.get("ok", 0) == 2 and rc == 0
        if args.precision:
            # Both responses must have been SERVED at the asked arm
            # (echoed in X-Precision; no ladder pressure at 2 requests).
            served = summary.get("arms", {}).get(args.precision, {})
            ok = ok and served.get("ok", 0) == 2
        return 0 if ok else 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if os.path.exists(port_file):
            os.unlink(port_file)


if __name__ == "__main__":
    raise SystemExit(main())
