#!/usr/bin/env python
"""Gradient wire-compression quality gate — CPU-runnable, per-PR.

The rules engine's bucketed allreduce can compress the gradient wire:
``parallel.grad_compression=bf16`` casts each flat bucket to bfloat16
before the ``psum`` (half the bytes); ``int8_ef`` quantizes to int8
against a global scale with a persistent error-feedback residual
(``state.comm_residual``) carrying each replica's rounding error into
the next step (quarter the achievable bytes).  The step-time win is a
TPU-window measurement (``tools/tpu_agenda_r18.sh``); the QUALITY cost
is not — wire rounding is a pure function of the
model/data/optimizer, measurable on CPU at t1 time.  This tool trains
the same model from the same init on the same deterministic synthetic
batches — f32 wire vs each compressed arm — and ledgers the
trajectory divergence in
``tools/grad_comm_baseline.json``, the same discipline as
``tools/precision_gate.py`` / ``tools/hlo_guard.py``:

- every run prints ONE JSON line with the arm deltas and the delta
  against the recorded ledger;
- ``--fail-on-increase`` exits 2 when a delta exceeds its recorded
  budget by more than ``--tolerance`` (off in shared CI: the t1.sh
  posture is recorded, non-gating);
- ``--update-baseline`` re-seeds after an intentional change;
- a run whose own invariants failed (non-finite loss, exploding drift)
  NEVER seeds or updates the ledger.

Each arm ledgers under its own key: the bf16 row keeps the original
``<config>@<px>-b<batch>-k<steps>-s<seed>`` key (baseline continuity),
the int8_ef row appends ``-int8_ef``.

Ledgered quantities ("worse" is positive):

- ``delta_final_loss`` — the compressed arm's last-step training loss
  minus the f32 arm's (positive = compression slowed the descent);
- ``param_rel_drift`` — relative L2 distance between the two final
  param trees, ‖p_arm − p_f32‖ / ‖p_f32‖ (how far the trajectories
  separated, magnitude-normalised).

Usage:
    python tools/grad_comm_gate.py                      # print deltas
    python tools/grad_comm_gate.py --update-baseline    # re-seed
    python tools/grad_comm_gate.py --fail-on-increase   # gate locally
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "grad_comm_baseline.json")


def run_arm(cfg, model, mesh, batches, *, steps: int,
            grad_compression: str):
    """Train ``steps`` steps through the rules-engine DP preset with the
    given wire precision; returns (final params, per-step losses)."""
    import jax

    from distributed_sod_project_tpu.parallel.engine import (
        make_unified_train_step, seed_comm_residual)
    from distributed_sod_project_tpu.parallel.mesh import (
        global_batch_array, replicated_sharding)
    from distributed_sod_project_tpu.train import (build_optimizer,
                                                   create_train_state)

    tx, sched = build_optimizer(cfg.optim, steps)
    state = jax.device_put(
        create_train_state(jax.random.key(cfg.seed), model, tx,
                           batches[0], ema=cfg.optim.ema_decay > 0),
        replicated_sharding(mesh))
    if grad_compression == "int8_ef":
        state = seed_comm_residual(state, mesh)
    step = make_unified_train_step(
        model, cfg.loss, tx, mesh, preset="dp", schedule=sched,
        donate=False, ema_decay=cfg.optim.ema_decay,
        comm_bucket_mb=cfg.parallel.comm_bucket_mb,
        grad_compression=grad_compression)
    losses = []
    for host in batches:
        state, metrics = step(state, global_batch_array(host, mesh))
        losses.append(float(jax.device_get(metrics["total"])))
    return jax.device_get(state.params), losses


def build_report(f32, comp, arm: str = "bf16") -> dict:
    """Arm deltas + the run's own invariants.  ``invariant_failed``
    means the measurements cannot be trusted — callers must not seed or
    update the ledger from it.  ``arm`` names the compressed side in
    the report (the gated delta keys stay arm-independent so every row
    shares one budget vocabulary)."""
    import jax
    import numpy as np

    p32, l32 = f32
    pbf, lbf = comp
    reasons = []
    for label, losses in (("f32", l32), (arm, lbf)):
        if not all(math.isfinite(v) for v in losses):
            reasons.append(f"{label} loss stream not finite: {losses}")
    num = math.sqrt(sum(
        float(np.sum((np.asarray(a, np.float64)
                      - np.asarray(b, np.float64)) ** 2))
        for a, b in zip(jax.tree_util.tree_leaves(pbf),
                        jax.tree_util.tree_leaves(p32))))
    den = math.sqrt(sum(
        float(np.sum(np.asarray(a, np.float64) ** 2))
        for a in jax.tree_util.tree_leaves(p32)))
    drift = num / den if den else float("nan")
    if not math.isfinite(drift):
        reasons.append("param_rel_drift is not finite")
    elif drift > 0.5:
        # A compressed WIRE should nudge the trajectory, not replace
        # it — half the weight norm means the arm is broken, and a
        # broken arm must not become the recorded budget.
        reasons.append(f"param_rel_drift {drift:.3f} > 0.5")
    arms = {
        "final_loss_f32": round(l32[-1], 6),
        f"final_loss_{arm}": round(lbf[-1], 6),
        "delta_final_loss": round(lbf[-1] - l32[-1], 6),
        "param_rel_drift": round(drift, 6) if math.isfinite(drift)
        else drift,
    }
    return {"arms": arms, "invariant_failed": bool(reasons),
            "reasons": reasons}


_GATED = ("delta_final_loss", "param_rel_drift")


def apply_baseline(report: dict, baseline: dict, key: str, *,
                   update: bool = False, fail_on_increase: bool = False,
                   tolerance: float = 0.005):
    """Ledger bookkeeping → ``(rc, baseline, summary)`` — invariant
    failures never write (rc 1), first contact or ``update`` seeds,
    otherwise each gated delta compares against the recorded budget and
    ``fail_on_increase`` turns a breach into rc 2."""
    summary = {"metric": f"grad_comm_gate[{key}]", "arms": report["arms"]}
    if report["invariant_failed"]:
        summary["invariant_failed"] = True
        summary["reasons"] = report["reasons"]
        return 1, baseline, summary
    recorded = baseline.get(key)
    if update or recorded is None:
        baseline = dict(baseline)
        baseline[key] = report["arms"]
        summary["recorded"] = True
        return 0, baseline, summary
    rc = 0
    over = {}
    for k in _GATED:
        excess = report["arms"][k] - recorded.get(k, 0.0)
        if excess > tolerance:
            over[k] = round(excess, 6)
    if over:
        summary["over_budget"] = over
        if fail_on_increase:
            rc = 2
    summary["delta_vs_recorded"] = {
        k: round(report["arms"][k] - recorded.get(k, 0.0), 6)
        for k in _GATED}
    return rc, baseline, summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="minet_vgg16_ref",
                   help="registered config whose model/optimizer/loss "
                        "the gate trains")
    p.add_argument("--image-size", type=int, default=32,
                   help="square train resolution (small keeps the CPU "
                        "gate fast; the delta is a gradient-rounding "
                        "effect, not a resolution effect)")
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--steps", type=int, default=4,
                   help="train steps per arm (enough for the rounding "
                        "error to compound visibly)")
    p.add_argument("--seed", type=int, default=0,
                   help="init + data seed (part of the ledger key)")
    p.add_argument("--device", default="cpu", choices=["tpu", "cpu"],
                   help="cpu by default — the gate must run at t1 time "
                        "with no TPU window")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="PATH=VALUE", help="dotted config override")
    p.add_argument("--arm", default="both",
                   choices=["bf16", "int8_ef", "both"],
                   help="which compressed arm(s) to gate; the f32 "
                        "reference trains once either way")
    p.add_argument("--baseline", default=_BASELINE)
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--fail-on-increase", action="store_true",
                   help="exit 2 when a delta exceeds its recorded "
                        "budget by more than --tolerance (off in "
                        "shared CI: recorded, not gating — the t1.sh "
                        "posture)")
    p.add_argument("--tolerance", type=float, default=0.005,
                   help="slack on the recorded deltas before a breach "
                        "(loss / relative-drift units)")
    args = p.parse_args(argv)

    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    import numpy as np

    from distributed_sod_project_tpu.configs import (apply_overrides,
                                                     get_config)
    from distributed_sod_project_tpu.configs.base import validate_parallel
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel import make_mesh

    hw = args.image_size
    cfg = apply_overrides(
        get_config(args.config),
        [f"data.image_size={hw},{hw}", f"seed={args.seed}",
         "parallel.engine=rules", "optim.warmup_steps=0"]
        + list(args.overrides))
    validate_parallel(cfg)
    model = build_model(cfg.model)
    mesh = make_mesh(cfg.mesh)

    rng = np.random.default_rng(args.seed)
    batches = []
    for _ in range(args.steps):
        img = rng.normal(size=(args.batch_size, hw, hw, 3)
                         ).astype(np.float32)
        batch = {"image": img,
                 "mask": (img.mean(-1, keepdims=True) > 0
                          ).astype(np.float32)}
        if cfg.data.use_depth:
            batch["depth"] = img.mean(-1, keepdims=True)
        batches.append(batch)

    arms = ["bf16", "int8_ef"] if args.arm == "both" else [args.arm]
    f32 = run_arm(cfg, model, mesh, batches, steps=args.steps,
                  grad_compression="none")

    baseline = {}
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baseline = json.load(f)
    base_key = (f"{cfg.name}@{hw}px-b{args.batch_size}-k{args.steps}"
                f"-s{args.seed}")
    rc = 0
    for arm in arms:
        report = build_report(
            f32, run_arm(cfg, model, mesh, batches, steps=args.steps,
                         grad_compression=arm), arm=arm)
        # bf16 keeps the pre-int8 key verbatim (ledger continuity);
        # every other arm gets its own suffixed row.
        key = base_key if arm == "bf16" else f"{base_key}-{arm}"
        arm_rc, new_baseline, summary = apply_baseline(
            report, baseline, key, update=args.update_baseline,
            fail_on_increase=args.fail_on_increase,
            tolerance=args.tolerance)
        if arm_rc == 1:
            print(f"grad_comm_gate: invariant failed — NOT seeding/"
                  f"updating baseline for {key}: {report['reasons']}",
                  file=sys.stderr)
        elif new_baseline is not baseline:
            baseline = new_baseline
            with open(args.baseline, "w") as f:
                json.dump(baseline, f, indent=2, sort_keys=True)
                f.write("\n")
        print(json.dumps(summary), flush=True)
        rc = max(rc, arm_rc)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
