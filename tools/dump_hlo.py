#!/usr/bin/env python
"""Dump the compiled train step's HLO for a config — regression diffing.

SURVEY.md §5 (tracing/profiling): the TPU-native analogue of "did my
change alter the compiled program?" is an HLO diff.  This tool lowers
the full sharded train step for a registered config on a virtual
n-device CPU mesh and writes:

    <out>/<config>.stablehlo.txt   — pre-optimization StableHLO (stable
                                     across machines; the diffing target)
    <out>/<config>.cost.json       — XLA's per-program cost analysis
                                     (flops, bytes accessed) when
                                     available

Usage:
    python tools/dump_hlo.py --config minet_r50_dp --out hlo/
    diff hlo_before/minet_r50_dp.stablehlo.txt hlo_after/...
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def dump(config_name: str, out_dir: str, n_devices: int = 8,
         batch_per_device: int = 1, image_size: int = 64,
         compile_cost: bool = True, overrides=(),
         post_opt: bool = False) -> dict:
    """Lower the config's train step; returns {'stablehlo': path, ...}.

    ``compile_cost=False`` skips the (slow) compile that only feeds the
    cost-analysis sidecar — tools/hlo_guard.py lowers the step several
    times per run and needs just the StableHLO text.  ``overrides`` are
    extra ``section.field=value`` config overrides applied on top of
    the standard virtual-mesh shrink — e.g. pin an execution-strategy
    arm (``model.resample_impl=convt``) to dump/diff arm-specific
    programs.  (The ``fast`` resample arm cannot be pinned this way:
    it is the env-subsumed default, so hlo_guard pins its arms via the
    env vars instead.)

    ``post_opt=True`` also compiles and writes the POST-optimization
    HLO (``<config>.hlo_post.txt``).  GSPMD presets (fsdp/tp) need it:
    their pre-opt StableHLO carries only sharding annotations — the
    SPMD partitioner inserts the collectives during compilation, so
    the JIT all-gathers/reduce-scatters are countable only post-opt.
    Post-opt text is backend-dependent (do NOT diff it across
    machines); hlo_guard only counts collective op names in it.
    """
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={n_devices}")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass
    import numpy as np

    from distributed_sod_project_tpu.configs import (apply_overrides,
                                                     get_config)
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel.mesh import (
        batch_sharding, make_mesh)
    from distributed_sod_project_tpu.train import (
        build_optimizer, create_train_state)

    cfg = get_config(config_name)
    cfg = apply_overrides(cfg, [
        f"global_batch_size={batch_per_device * n_devices}",
        f"data.image_size={image_size},{image_size}",
        "mesh.data=-1", "mesh.model=1", "mesh.seq=1",
    ] + list(overrides))
    mesh = make_mesh(cfg.mesh, jax.devices()[:n_devices])
    model = build_model(cfg.model)
    tx, sched = build_optimizer(cfg.optim, 100)

    rng = np.random.RandomState(0)
    b, hw = cfg.global_batch_size, image_size
    batch = {
        "image": rng.randn(b, hw, hw, 3).astype(np.float32),
        "mask": (rng.rand(b, hw, hw, 1) > 0.5).astype(np.float32),
    }
    if cfg.data.use_depth:
        batch["depth"] = rng.randn(b, hw, hw, 1).astype(np.float32)
    state = create_train_state(jax.random.key(0), model, tx, batch)
    dbatch = jax.device_put(batch, batch_sharding(mesh))

    # The unified rules engine (parallel/engine.py, the only engine):
    # same preset routing as fit(), so hlo_guard's comm arms can pin
    # parallel.* overrides (preset=fsdp, data_hosts, grad_compression)
    # and count the lowered collectives.
    from distributed_sod_project_tpu.parallel.engine import (
        prepare_train_step)

    state, step, _plan = prepare_train_step(
        cfg, model, tx, mesh, sched, state, donate=False)
    lowered = step.lower(state, dbatch)

    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    shlo = os.path.join(out_dir, f"{config_name}.stablehlo.txt")
    with open(shlo, "w") as f:
        f.write(lowered.as_text())
    paths["stablehlo"] = shlo

    if post_opt:
        compiled = lowered.compile()
        ppath = os.path.join(out_dir, f"{config_name}.hlo_post.txt")
        with open(ppath, "w") as f:
            f.write(compiled.as_text())
        paths["hlo_post"] = ppath

    if not compile_cost:
        return paths
    try:
        compiled = lowered.compile()
        cost = compiled.cost_analysis() or {}
        cost = {k: float(v) for k, v in cost.items()
                if isinstance(v, (int, float))}
        cpath = os.path.join(out_dir, f"{config_name}.cost.json")
        with open(cpath, "w") as f:
            json.dump(cost, f, indent=2, sort_keys=True)
        paths["cost"] = cpath
    except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
        print(f"[warn] cost analysis unavailable: {e}", file=sys.stderr)
    return paths


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", required=True)
    p.add_argument("--out", default="hlo")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--batch-per-device", type=int, default=1)
    p.add_argument("--image-size", type=int, default=64)
    args = p.parse_args(argv)
    paths = dump(args.config, args.out, args.devices,
                 args.batch_per_device, args.image_size)
    for k, v in paths.items():
        print(f"{k}: {v}  ({os.path.getsize(v)} bytes)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
