#!/usr/bin/env python
"""One-command TPU measurement session — run the moment the tunnel lives.

The axon TPU tunnel has been available for exactly one session across
two rounds; when it comes back the window may be short.  This tool
captures the full round-2 measurement agenda (VERDICT.md items 1-3)
in one invocation, each step bounded and failure-isolated, appending
everything to an output directory the BASELINE.md tables can be
written from:

    python tools/tpu_capture.py --out tpu_results/

Agenda (each a bounded subprocess; a wedge or failure in one step
never loses the others):

  1. probe        — out-of-process dial with timeout; abort if no TPU
  2. headline     — MINet-R50 @320 bf16 train, batch 64 + remat
                    (the BASELINE.md governing number)
  3. batch sweep  — batch 32 / 96 / 128 (remat on) around the headline
  4. eval         — forward+device-metrics throughput (test.py hot loop)
  5. zoo          — tools/bench_zoo.py over every config, train+eval
  6. fused A/B    — loss.fused_kernel on/off (basnet_ds, the 8-output
                    deep-supervision hybrid-loss member)
  7. flash A/B    — vit_sod attention xla vs Pallas flash @512px at a
                    batch both cores survive, plus a flash_big step
                    (batch 16 + remat=dots) at a batch whose XLA-core
                    scores would exceed HBM — the memory-lever demo
  8. profile      — jax.profiler trace of the headline step for the
                    MFU push (VERDICT.md "what's weak" #1)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(name: str, cmd: list[str], out_dir: str, timeout: int,
         results: dict) -> dict | None:
    """Run one step; parse the last JSON line of stdout; log everything."""
    log_path = os.path.join(out_dir, f"{name}.log")
    t0 = time.time()
    print(f"[{name}] {' '.join(cmd)}", flush=True)
    try:
        proc = subprocess.run(cmd, cwd=_REPO, capture_output=True,
                              text=True, timeout=timeout)
        out, err, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
        rc = f"timeout>{timeout}s"
    with open(log_path, "w") as f:
        f.write(f"$ {' '.join(cmd)}\nrc={rc}\n--- stdout ---\n{out}"
                f"\n--- stderr ---\n{err}\n")
    parsed = None
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    results[name] = {"rc": rc, "seconds": round(time.time() - t0, 1),
                     "parsed": parsed}
    status = "ok" if parsed and "error" not in (parsed or {}) else f"rc={rc}"
    val = (parsed or {}).get("value")
    unit = (parsed or {}).get("unit", "")
    print(f"[{name}] {status}  value={val} {unit}  "
          f"({results[name]['seconds']}s)", flush=True)
    return parsed


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="tpu_results")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--step-timeout", type=int, default=1200,
                   help="per-step subprocess bound (compile ~20-40s + "
                        "timed steps; zoo gets 4x this)")
    p.add_argument("--skip", default="",
                   help="comma-separated step names to skip "
                        "(e.g. zoo,profile)")
    p.add_argument("--device", default="tpu", choices=["tpu", "cpu"],
                   help="cpu = smoke-test THIS TOOL's machinery "
                        "(tiny shapes); the measurement agenda is tpu")
    args = p.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    skip = {s.strip() for s in args.skip.split(",") if s.strip()}
    smoke = args.device == "cpu"
    results: dict = {}
    py = sys.executable

    # 1. probe — out of process, so a wedge is a clean abort.
    try:
        probe = subprocess.run(
            [py, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "print('cpu', jax.device_count())" if smoke else
             "import jax; d=jax.devices(); print(d[0].platform, len(d))"],
            cwd=_REPO, capture_output=True, text=True, timeout=150)
        plat = probe.stdout.strip().split()
    except subprocess.TimeoutExpired:
        plat = []
    want = ("cpu",) if smoke else ("tpu", "axon")
    if not plat or plat[0] not in want:
        print(f"no {want[0]} (probe said {plat or 'wedge/timeout'}); "
              "aborting", flush=True)
        results["probe"] = {"ok": False, "detail": plat}
        with open(os.path.join(args.out, "results.json"), "w") as f:
            json.dump(results, f, indent=2)
        return 1
    results["probe"] = {"ok": True, "platform": plat}
    print(f"{plat[0]} up: {plat}", flush=True)

    # CPU smoke shrinks every shape so one pass finishes in minutes.
    # b_vit=2 for the flash A/B: the XLA core materialises
    # B·H·N² f32 scores (batch 8 @512px ≈ 25 GB — past v5e HBM), so
    # the apples-to-apples pair runs at a batch both cores survive;
    # flash_big then shows the lever at a batch the XLA core cannot.
    hw, hw_hi, b_head, b_mid, b_hi, b_vit, b_vit_big = (
        ("64", "64", "2", "1", "2", "1", "2") if smoke
        else ("320", "512", "64", "32", "96", "2", "16"))
    bench = [py, "bench.py", "--device", args.device,
             "--steps", str(args.steps), "--image-size", hw]
    agenda = [
        ("headline", bench + ["--config", "minet_r50_dp",
                              "--batch-per-chip", b_head,
                              "--set", "model.remat=true"]),
        ("batch_lo", bench + ["--config", "minet_r50_dp",
                              "--batch-per-chip", b_mid]),
        ("batch_hi_remat", bench + ["--config", "minet_r50_dp",
                                    "--batch-per-chip", b_hi,
                                    "--set", "model.remat=true"]),
        ("batch_max_remat", bench + ["--config", "minet_r50_dp",
                                     "--batch-per-chip",
                                     "4" if smoke else "128",
                                     "--set", "model.remat=true"]),
        ("eval", bench + ["--config", "minet_r50_dp", "--mode", "eval",
                          "--batch-per-chip", b_head]),
        ("fused_off", bench + ["--config", "basnet_ds",
                               "--batch-per-chip", b_mid]),
        ("fused_on", bench + ["--config", "basnet_ds",
                              "--batch-per-chip", b_mid,
                              "--set", "loss.fused_kernel=true"]),
        ("dlf_off", bench + ["--config", "hdfnet_rgbd",
                             "--batch-per-chip", b_mid]),
        ("dlf_on", bench + ["--config", "hdfnet_rgbd",
                            "--batch-per-chip", b_mid,
                            "--set", "model.dlf_impl=pallas"]),
        ("flash_off", [*bench[:-1], hw_hi, "--config", "vit_sod_sp",
                       "--batch-per-chip", b_vit,
                       "--set", "mesh.seq=1",
                       "--set", "model.attn_impl=xla"]),
        ("flash_on", [*bench[:-1], hw_hi, "--config", "vit_sod_sp",
                      "--batch-per-chip", b_vit,
                      "--set", "mesh.seq=1",
                      "--set", "model.attn_impl=flash"]),
        ("flash_big", [*bench[:-1], hw_hi, "--config", "vit_sod_sp",
                       "--batch-per-chip", b_vit_big,
                       "--set", "mesh.seq=1",
                       "--set", "model.attn_impl=flash",
                       "--set", "model.remat=true",
                       "--set", "model.remat_policy=dots"]),
        ("profile", bench + ["--config", "minet_r50_dp",
                             "--batch-per-chip", b_head,
                             "--set", "model.remat=true",
                             "--profile-dir",
                             os.path.join(args.out, "trace")]),
    ]
    for name, cmd in agenda:
        if name in skip:
            continue
        _run(name, cmd, args.out, args.step_timeout, results)
        with open(os.path.join(args.out, "results.json"), "w") as f:
            json.dump(results, f, indent=2)

    if "zoo" not in skip:
        # Per-item budget (round-2 lesson: one flat 4x bound let a
        # single stage eat 80 minutes and time out the whole table);
        # the partial table flushes to zoo_table.md after every row.
        # swin_sod eval is EXCLUDED — it crashes the TPU worker and can
        # wedge the tunnel for hours (tpu_results/zoo.log); its train
        # row runs via the bisect/agenda tooling instead.
        per_item = max(args.step_timeout // 2, 120)
        # One source of truth for zoo membership (minus the
        # worker-killing swin eval); tpu_agenda_r3.sh is the only
        # remaining manual copy (shell can't import).
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        from bench_zoo import ZOO

        zoo_configs = [c for c in ZOO if c != "swin_sod"]
        zoo_modes = ["train", "eval"]
        n_items = len(zoo_configs) * len(zoo_modes)
        _run("zoo", [py, "tools/bench_zoo.py", "--device", args.device,
                     "--modes", ",".join(zoo_modes),
                     "--steps", str(args.steps),
                     "--image-size", hw, "--timeout", str(per_item),
                     "--retry-budget", "0", "--init-retries", "2",
                     "--configs", ",".join(zoo_configs),
                     *([] if not smoke else ["--batch-per-chip", "1"]),
                     "--out", os.path.join(args.out, "zoo_table.md")],
             args.out, n_items * per_item + 300, results)
        with open(os.path.join(args.out, "results.json"), "w") as f:
            json.dump(results, f, indent=2)

    # Markdown summary for BASELINE.md.
    lines = ["| step | value | unit | seconds |", "|---|---|---|---|"]
    for name, r in results.items():
        if name == "probe":
            continue
        parsed = r.get("parsed") or {}
        lines.append(f"| {name} | {parsed.get('value', '—')} | "
                     f"{parsed.get('unit', '')} | {r.get('seconds', '')} |")
    md = os.path.join(args.out, "summary.md")
    with open(md, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {md}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
