#!/usr/bin/env python
"""Fleet smoke for tools/t1.sh: start tools/serve.py --fleet-config as
a REAL subprocess serving TWO models on an ephemeral port, push a
mixed-model loadgen round through the router (weighted X-Model /
X-Tenant traffic), assert the per-model breakdown and the fleet-wide
accounting identity, then SIGTERM and assert a CLEAN drain (exit 0).
Prints one JSON line; exits non-zero on any broken link.

Budget contract: the internal deadlines (180 s bind incl. two models'
AOT warms + 60 s healthz + 90 s requests + 60 s drain) sum under the
t1.sh wrapper's 480 s, so a stall always reports its OWN JSON
diagnostic instead of dying to the outer timeout mid-wait.

Deliberately out-of-process (the serve_smoke posture, one tier up):
the smoke must exercise the same process lifecycle a fleet deployment
does — fleet-config parsing, two engines warming behind one
interleaved dispatcher, signal handling, drain, port-file.
tests/test_fleet.py covers the in-process side.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_sod_project_tpu.serve.loadgen import (  # noqa: E402
    run_loadgen, wait_ready)

TOOLS = os.path.dirname(os.path.abspath(__file__))

# Two REAL zoo architectures, shrunk to smoke size: 64 px, two batch
# buckets, f32 only (each extra arm is another AOT program per model).
FLEET = {
    "default_tenant": "free",
    "tenants": [
        {"name": "gold", "priority": 1},
        {"name": "free", "priority": 0},
    ],
    "models": [
        {"name": "minet", "config": "minet_vgg16_ref", "overrides": [
            "data.image_size=64,64", "serve.resolution_buckets=64",
            "serve.batch_buckets=1,2", "serve.precision_arms=f32",
            "serve.precision=f32"]},
        {"name": "u2net", "config": "u2net_ds", "overrides": [
            "data.image_size=64,64", "serve.resolution_buckets=64",
            "serve.batch_buckets=1,2", "serve.precision_arms=f32",
            "serve.precision=f32"]},
    ],
}


def main(argv=None) -> int:
    argparse.ArgumentParser(description=__doc__).parse_args(argv)
    port_file = tempfile.mktemp(prefix="dsod_fleet_port_")
    fleet_file = tempfile.mktemp(prefix="dsod_fleet_cfg_", suffix=".json")
    with open(fleet_file, "w") as f:
        json.dump(FLEET, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, os.path.join(TOOLS, "serve.py"),
           "--fleet-config", fleet_file, "--device", "cpu",
           "--port", "0", "--port-file", port_file]
    proc = subprocess.Popen(cmd, env=env)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                print(json.dumps({"error": "fleet died before binding",
                                  "rc": proc.returncode}), flush=True)
                return 1
            if time.monotonic() > deadline:
                print(json.dumps({"error": "fleet never bound a port"}),
                      flush=True)
                return 1
            time.sleep(0.25)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read().strip())}"
        if not wait_ready(url, timeout_s=60):
            print(json.dumps({"error": "fleet never became healthy"}),
                  flush=True)
            return 1
        # Mixed traffic through ONE router: weighted models x tenants.
        summary = run_loadgen(
            url, mode="closed", concurrency=2, requests=6,
            sizes=((48, 56),), seed=0, timeout_s=90,
            mix=[{"model": "minet", "tenant": "gold", "weight": 2},
                 {"model": "u2net", "tenant": "free", "weight": 1}])
        with urllib.request.urlopen(url + "/stats", timeout=10) as r:
            stats = json.loads(r.read().decode())
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        summary["server_rc"] = rc
        summary["fleet"] = stats.get("fleet", {})
        print(json.dumps(summary), flush=True)
        models = summary.get("models", {})
        ok = (summary.get("ok", 0) == 6 and rc == 0
              # every request served by the model it named …
              and models.get("minet", {}).get("ok", 0) \
              == models.get("minet", {}).get("sent", -1)
              and models.get("u2net", {}).get("ok", 0) \
              == models.get("u2net", {}).get("sent", -1)
              and models.get("u2net", {}).get("sent", 0) >= 1
              # … and the fleet-wide book balances.
              and stats.get("fleet", {}).get("consistent") is True
              and stats.get("fleet", {}).get("submitted") == 6)
        return 0 if ok else 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        for f in (port_file, fleet_file):
            if os.path.exists(f):
                os.unlink(f)


if __name__ == "__main__":
    raise SystemExit(main())
