#!/usr/bin/env python
"""Fleet smoke for tools/t1.sh: start a REAL two-model fleet — minet
as an in-process engine inside the router process, u2net as a REAL
remote replica subprocess proxied by URL — push a mixed-model loadgen
round through the router (weighted X-Model / X-Tenant traffic), assert
the per-model breakdown and the fleet-wide accounting identity, then
SIGKILL the remote replica mid-fleet and assert the failure semantics:
/healthz flips to ``degraded`` NAMING the dead model, the surviving
model keeps serving, a request to the dead model terminates in a
counted error (no hang, no lost response), and the book still
balances.  Finally SIGTERM the fleet and assert a CLEAN drain (exit
0).  Prints one JSON line; exits non-zero on any broken link.

Budget contract: the internal deadlines — 150 s replica bind + 150 s
fleet bind (each ONE model's AOT warm) + 60 s healthz + the request
legs at their WORST-CASE per-request timeouts (mixed round: 6 req /
concurrency 2 x 45 s = 135 s; kill leg: 20 s degraded poll + 2 x 45 s
survivor + 30 s dead-model) + 60 s drain — sum to ~650 s, under the
t1.sh wrapper's 720 s, so a stall always reports its OWN JSON
diagnostic instead of dying to the outer timeout mid-wait.

Deliberately out-of-process (the serve_smoke posture, one tier up):
the smoke must exercise the same process lifecycle a scaled-out fleet
deployment does — fleet-config parsing, a remote replica behind a real
socket, the background health prober, signal handling, drain,
port-file.  The kill leg uses SIGKILL (no drain, no goodbye) and the
replacement policy is a FRESH subprocess — per the RESILIENCE.md
jaxlib note, nothing is ever revived in-process.
tests/test_fleet.py + tests/test_failover.py cover the in-process
side.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_sod_project_tpu.serve.loadgen import (  # noqa: E402
    run_loadgen, wait_ready)

TOOLS = os.path.dirname(os.path.abspath(__file__))

# Two REAL zoo architectures, shrunk to smoke size: 64 px, two batch
# buckets, f32 only (each extra arm is another AOT program per model).
SMOKE_OVERRIDES = [
    "data.image_size=64,64", "serve.resolution_buckets=64",
    "serve.batch_buckets=1,2", "serve.precision_arms=f32",
    "serve.precision=f32"]


def fleet_config(u2net_url: str) -> dict:
    return {
        "default_tenant": "free",
        "tenants": [
            {"name": "gold", "priority": 1},
            {"name": "free", "priority": 0},
        ],
        "models": [
            {"name": "minet", "config": "minet_vgg16_ref",
             "overrides": SMOKE_OVERRIDES},
            {"name": "u2net", "url": u2net_url},
        ],
        # Tight health window so the SIGKILL leg's degraded flip is
        # observable within the smoke budget.
        "health_poll_s": 0.5,
        "retry_backoff_ms": 5,
    }


def main(argv=None) -> int:
    argparse.ArgumentParser(description=__doc__).parse_args(argv)
    port_file = tempfile.mktemp(prefix="dsod_fleet_port_")
    replica_port_file = tempfile.mktemp(prefix="dsod_fleet_replica_port_")
    fleet_file = tempfile.mktemp(prefix="dsod_fleet_cfg_", suffix=".json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    replica_cmd = [sys.executable, os.path.join(TOOLS, "serve.py"),
                   "--config", "u2net_ds", "--init-random",
                   "--device", "cpu", "--port", "0",
                   "--port-file", replica_port_file]
    for ov in SMOKE_OVERRIDES:
        replica_cmd += ["--set", ov]
    replica = subprocess.Popen(replica_cmd, env=env)
    proc = None
    try:
        deadline = time.monotonic() + 150
        while not os.path.exists(replica_port_file):
            if replica.poll() is not None:
                print(json.dumps({"error": "replica died before binding",
                                  "rc": replica.returncode}), flush=True)
                return 1
            if time.monotonic() > deadline:
                print(json.dumps({"error": "replica never bound a port"}),
                      flush=True)
                return 1
            time.sleep(0.25)
        with open(replica_port_file) as f:
            replica_url = f"http://127.0.0.1:{int(f.read().strip())}"
        with open(fleet_file, "w") as f:
            json.dump(fleet_config(replica_url), f)
        cmd = [sys.executable, os.path.join(TOOLS, "serve.py"),
               "--fleet-config", fleet_file, "--device", "cpu",
               "--port", "0", "--port-file", port_file]
        proc = subprocess.Popen(cmd, env=env)
        deadline = time.monotonic() + 150
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                print(json.dumps({"error": "fleet died before binding",
                                  "rc": proc.returncode}), flush=True)
                return 1
            if time.monotonic() > deadline:
                print(json.dumps({"error": "fleet never bound a port"}),
                      flush=True)
                return 1
            time.sleep(0.25)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read().strip())}"
        if not wait_ready(url, timeout_s=60):
            print(json.dumps({"error": "fleet never became healthy"}),
                  flush=True)
            return 1
        # Mixed traffic through ONE router: weighted models x tenants,
        # minet in-process and u2net proxied over a real socket.
        summary = run_loadgen(
            url, mode="closed", concurrency=2, requests=6,
            sizes=((48, 56),), seed=0, timeout_s=45,
            mix=[{"model": "minet", "tenant": "gold", "weight": 2},
                 {"model": "u2net", "tenant": "free", "weight": 1}])

        # -- SIGKILL the remote replica mid-fleet ----------------------
        replica.kill()
        replica.wait(timeout=30)
        kill = {}
        # The background prober must flip /healthz to DEGRADED naming
        # the dead model within its 0.5 s window (plus probe timeout).
        degraded_seen = False
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=10) as r:
                    health = json.loads(r.read().decode())
            except urllib.error.HTTPError as e:
                health = json.loads(e.read().decode())
            if (health.get("status") == "degraded"
                    and "u2net" in health.get("unhealthy", [])):
                degraded_seen = True
                break
            time.sleep(0.25)
        kill["degraded_names_model"] = degraded_seen
        # The SURVIVING model still serves through the same router...
        alive = run_loadgen(url, mode="closed", concurrency=1,
                            requests=2, sizes=((48, 56),), seed=1,
                            timeout_s=45, model="minet", tenant="gold")
        kill["survivor_ok"] = alive.get("ok", 0)
        # ...and a request to the DEAD model terminates in a counted
        # error (503 no-healthy-replica or 502 transport) — never a
        # hang, never a lost response.
        dead = run_loadgen(url, mode="closed", concurrency=1,
                           requests=1, sizes=((48, 56),), seed=2,
                           timeout_s=30, model="u2net", tenant="free")
        kill["dead_model_outcomes"] = {
            k: dead.get(k, 0)
            for k in ("ok", "unhealthy", "transport", "error")}
        with urllib.request.urlopen(url + "/stats", timeout=10) as r:
            stats = json.loads(r.read().decode())
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        summary["server_rc"] = rc
        summary["fleet"] = stats.get("fleet", {})
        summary["kill_leg"] = kill
        print(json.dumps(summary), flush=True)
        models = summary.get("models", {})
        dead_terminated = (dead.get("done", 0) == 1
                           and dead.get("ok", 0) == 0)
        ok = (summary.get("ok", 0) == 6 and rc == 0
              # every request served by the model it named …
              and models.get("minet", {}).get("ok", 0) \
              == models.get("minet", {}).get("sent", -1)
              and models.get("u2net", {}).get("ok", 0) \
              == models.get("u2net", {}).get("sent", -1)
              and models.get("u2net", {}).get("sent", 0) >= 1
              # … the kill leg's failure semantics held …
              and degraded_seen
              and kill["survivor_ok"] == 2
              and dead_terminated
              # … and the fleet-wide book balances THROUGH the kill
              # (6 mixed + 2 survivor + 1 dead-model terminal error).
              and stats.get("fleet", {}).get("consistent") is True
              and stats.get("fleet", {}).get("submitted") == 9)
        return 0 if ok else 1
    finally:
        for pr in (proc, replica):
            if pr is not None and pr.poll() is None:
                pr.kill()
                pr.wait(timeout=30)
        for f in (port_file, replica_port_file, fleet_file):
            if os.path.exists(f):
                os.unlink(f)


if __name__ == "__main__":
    raise SystemExit(main())
