#!/usr/bin/env python
"""Metrics-family inventory lint — catch telemetry-surface drift at t1
time (hlo_guard discipline: one JSON line, ``--update-baseline``, exit
2 on undocumented or vanished families).

Dashboards, alerts, and the agenda scripts key on metric FAMILY names
(``dsod_serve_e2e_latency_ms``, ``dsod_fleet_routed_total``,
``dsod_train_data_starved_ms_total``, …).  A renamed or dropped family
breaks them silently — Prometheus happily scrapes whatever is there.
This tool renders the full family surface of BOTH stacks and diffs the
``{family: type}`` inventory against the checked-in
``tools/metrics_inventory.json``:

- ``fleet``   — the aggregated fleet /metrics (router families, replica
  up/breaker gauges, every ServeStats family incl. the per-arm ones),
  rendered in-process from synthetically POPULATED stats objects: the
  inventory needs every lazily-created family (arms, hedges, …) to
  exist, and standing up real engines would cost AOT compiles for a
  name check.  The construction goes through the real ``Fleet``
  aggregation code path, so renames there are caught too.
- ``trainer`` — the trainer sidecar /metrics via the SAME
  ``trainer_prom_families`` function the sidecar serves (one renderer,
  no drift by construction).

``--url URL`` (repeatable) instead scrapes live endpoints and lints
their families against the union inventory — the form the TPU agenda
runs against a real fleet + trainer sidecar.

``--ring DIR`` (repeatable) lints a flight-recorder segment ring
(utils/flightrecorder.py): every family a sample record carries must
be in the inventory union — the recorder's ON-DISK schema is the same
family surface /metrics exposes, and a renamed family would otherwise
silently break every archived ring tools/incident.py diffs against.
``--ring-selftest`` builds a synthetic ring from the same populated
surfaces the inventory render uses and lints it (the t1.sh leg).

Usage:
    python tools/metrics_lint.py                     # print delta line
    python tools/metrics_lint.py --update-baseline   # re-seed the file
    python tools/metrics_lint.py --url http://127.0.0.1:8080
    python tools/metrics_lint.py --ring /data/flightrec
    python tools/metrics_lint.py --ring-selftest
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "metrics_inventory.json")


def _family_types(families) -> dict:
    return {name: typ for name, typ, _samples in families}


def _populated_capacity():
    """A CapacityLedger with one recorded + observed program, so every
    dsod_capacity_* family (static cost, live utilization, stage
    share, HBM gauges) renders — the inventory is a NAME check, so a
    stub executable's analyses are as good as a warmed engine's."""
    from distributed_sod_project_tpu.utils.capacity import CapacityLedger

    class _StubCompiled:
        def cost_analysis(self):
            return {"flops": 1.0, "bytes accessed": 1.0}

        def memory_analysis(self):
            return None

    cap = CapacityLedger(
        share_fn=lambda: {"device": 0.5, "queue": 0.25, "host": 0.25})
    cap.record("m/r64b1/fast/f32", _StubCompiled())
    cap.observe("m/r64b1/fast/f32", 1.0)
    # One synthetic comm plan so the round-18 dsod_capacity_comm_*
    # families render (they are `if samples`-gated like the per-program
    # families).  The hierarchical legs (round 18) carry a DCN-level
    # collective so the dsod_capacity_comm_dcn_* split renders too.
    cap.record_comm("m/r64b1/fast/f32", {
        "collectives": [
            {"name": "grad_bucket_00_rs", "kind": "reduce_scatter",
             "axis": "data", "axis_size": 2, "level": "ici", "bytes": 8},
            {"name": "grad_bucket_00_ar", "kind": "psum",
             "axis": "data", "axis_size": 2, "level": "dcn", "bytes": 4},
            {"name": "grad_bucket_00_ag", "kind": "all_gather",
             "axis": "data", "axis_size": 2, "level": "ici", "bytes": 8},
        ],
        "n_buckets": 1, "overlap_frac": 0.0,
        "zero_hbm_saved_bytes": 0})
    return cap


def fleet_inventory() -> dict:
    """Render the aggregated fleet /metrics surface from populated
    stats objects through the real Fleet aggregation path."""
    from distributed_sod_project_tpu.serve.fleet import Fleet
    from distributed_sod_project_tpu.utils.observability import ServeStats

    stats = ServeStats()
    for key in ServeStats.COUNTERS:
        stats.inc(key)
    stats.observe_batch(1, 2, arm="f32")
    stats.set_queue_depth(1)
    stats.set_inflight(1)
    stats.set_degraded(1)
    for h in (stats.queue_ms, stats.device_ms, stats.e2e_ms):
        h.observe(1.0)
    arm = stats.arm("f32")
    arm.inc_served()
    arm.device_ms.observe(1.0)
    arm.e2e_ms.observe(1.0)

    # Model-health surface (serve/quality.py + utils/alerts.py): the
    # quality monitors and alert engine are lazily constructed per
    # engine, so the inventory populates them synthetically — every
    # conditionally-rendered family (psi, per-arm shadow) must exist.
    import numpy as np

    from distributed_sod_project_tpu.configs import ServeConfig
    from distributed_sod_project_tpu.serve.quality import (
        PSI_BINS, QualityMonitor, default_quality_rules)
    from distributed_sod_project_tpu.utils.alerts import AlertEngine

    quality = QualityMonitor("m", shadow_sample=1.0,
                             reference={"input_mean": [1.0] * PSI_BINS,
                                        "fg_fraction": [1.0] * PSI_BINS},
                             psi_min_count=1)
    quality.observe_input(0.5)
    quality.observe_output(np.full((4, 4), 0.7, np.float32))
    quality.record_shadow("bf16", 0.001, 0.0)
    quality.record_shadow_dropped()
    alerts = AlertEngine(default_quality_rules(ServeConfig()))
    alerts.evaluate({"quality_psi_max": 0.5, "shadow_mae_max": 0.1})

    # Capacity & SLO surface (utils/capacity.py, utils/slo.py,
    # serve/prober.py): populated synthetically through the SAME
    # prom_families providers the engine/router register, so every
    # knob-gated family is in the inventory.
    capacity = _populated_capacity()

    class _StubBackend:
        """Metric-surface stand-in for one replica: real ServeStats
        families, no engine (the inventory is a NAME check — an AOT
        warmup would buy nothing)."""

        kind = "stub"
        name = "m"

        def healthy(self):
            return True

        def prom_families(self, labels):
            # The EngineBackend path renders the engine's full registry
            # (ServeStats + quality + alerts + capacity); mirror it.
            return (stats.prom_families(labels)
                    + quality.prom_families(labels)
                    + alerts.prom_families(labels)
                    + capacity.prom_families(labels))

        def stats_snapshot(self):
            return stats.snapshot()

        def debug_traces(self, n=50):
            return {}

        def describe(self):
            return {"kind": self.kind}

    # The fleet with the router-tier SLO tracker and prober armed:
    # Fleet itself constructs both off the config, exactly the
    # serve_fleet_forever path.
    from distributed_sod_project_tpu.configs import (FleetConfig,
                                                     FleetTenantConfig)

    # Controller + rollout armed so the dsod_ctrl_* control-plane
    # families render: both ctors are side-effect-free by design (no
    # threads, no subprocesses, no ckpt reads until start()/tick()),
    # so arming them here costs a name check exactly what it should.
    # Router cache armed too (near-dup + shadow) so every dsod_cache_*
    # family renders — the ctor is threadless by design.
    fleet = Fleet([_StubBackend()], FleetConfig(
        tenants=(FleetTenantConfig(name="_probe", priority=-1),),
        slo_objectives=("avail:model=m:availability:0.99:60",),
        prober_interval_s=1.0, controller=True,
        rollout_ckpt_dir="/nonexistent-dsod-lint",
        cache_bytes=1 << 20, cache_near_dup=True,
        cache_near_dup_hamming=8, cache_shadow_sample=1,
        stream_sessions=4, stream_reuse_hamming=8))
    fleet.slo.observe_outcome("ok", 1.0, model="m")
    fleet.slo.observe_outcome("error", 1.0, model="m")
    fleet.probe_stats.record("m", True, 1.0, mae=0.01, iou=0.9)
    fleet.probe_stats.record("m", False, 1.0)
    fleet.probe_stats.record_dropped()
    r = fleet.rstats
    r.inc_submitted("default")
    r.inc_shed("default", "budget")
    r.inc_routed("m")
    r.inc_retry("m")
    r.inc_hedge("m")
    r.inc_failover("m")
    r.inc_response("default", "ok")
    # Populate the lazily-labeled control-plane families (decisions /
    # restarts / verdicts / canary-mae render only once booked).
    c = fleet.controller.stats
    c.inc_decision("scale_out", "queue_bound")
    c.inc_restart("m")
    c.set_supervised("m", "running", 1)
    ro = fleet.rollout.stats
    ro.set_state("m", "canary")
    ro.set_denylisted("m", 1)
    ro.set_canary_mae("m", 0.01)
    ro.inc_verdict("m", "promote")
    # Cache families render per model/kind only once booked.
    ca = fleet.cache.stats
    ca.inc_hit("m", "exact")
    ca.inc_hit("m", "near")
    ca.inc_miss("m")
    ca.inc_coalesced("m")
    ca.inc_insert("m")
    ca.inc_evictions()
    ca.record_shadow(0.01)
    ca.record_shadow_dropped()
    # Stream session families (serve/streams.py) render only while
    # streaming is armed (off-path /metrics stays byte-identical);
    # the StreamTable ctor is threadless by design.
    _, sess = fleet.streams.touch("s1")
    fleet.streams.pin(sess, "m")
    fleet.streams.note_reuse(sess, 1.0)
    from distributed_sod_project_tpu.utils.observability import \
        parse_prom_text

    global _SELFTEST_FLEET_TEXT
    text = fleet.metrics_text()
    _SELFTEST_FLEET_TEXT = text  # the ring selftest samples this too
    return _family_types(parse_prom_text(text))


def trainer_inventory() -> dict:
    """Render the trainer sidecar /metrics surface via the function the
    sidecar itself serves."""
    from distributed_sod_project_tpu.utils.observability import \
        PipelineStats
    from distributed_sod_project_tpu.utils.telemetry import \
        trainer_prom_families
    from distributed_sod_project_tpu.utils.timing import StepTimer
    from distributed_sod_project_tpu.utils.tracing import Tracer

    stats = PipelineStats()
    for key in PipelineStats.CANONICAL:
        stats.add(key, 1.0)
    stats.observe_depth(1, 2)
    timer = StepTimer(warmup=0)
    timer.tick()
    timer.tick()
    fams = trainer_prom_families(
        data_stats=stats, timer=timer, batch_size=8,
        writer_backend="noop", step_fn=lambda: 1,
        tracer=Tracer(sample=1.0), device_memory=False)
    # Model-health surface (utils/modelhealth.py + utils/alerts.py):
    # the sidecar registers these as extra providers when
    # health_numerics is on; the inventory populates them synthetically
    # through the SAME prom_families methods the providers are.
    from distributed_sod_project_tpu.utils.alerts import AlertEngine
    from distributed_sod_project_tpu.utils.modelhealth import (
        HealthMonitor, default_numerics_rules)

    health = HealthMonitor(("backbone", "head"))
    health.observe({"total": 1.0, "grad_norm": 1.0,
                    "health/nonfinite_group": 0.0,
                    "health/grad_group_norm/backbone": 1.0,
                    "health/grad_group_norm/head": 1.0,
                    "health/update_weight_ratio": 0.1,
                    "health/weight_norm": 1.0,
                    "notfinite_count": 0.0})
    alerts = AlertEngine(default_numerics_rules())
    sigs, details = health.signals()
    alerts.evaluate(sigs, details=details)
    fams = fams + health.prom_families() + alerts.prom_families()
    # Capacity & goodput-SLO surface (utils/capacity.py, utils/slo.py):
    # the sidecar registers these as extra providers when the knobs are
    # on; populate through the same prom_families the providers are.
    from distributed_sod_project_tpu.utils.slo import build_tracker

    slo = build_tracker(("goodput:all:latency:0.99:600:2000",),
                        burn_threshold=10.0, alert_for_s=0.0,
                        alert_clear_s=1.0)
    slo.observe(True, latency_ms=5.0, n=1)
    fams = (fams + _populated_capacity().prom_families()
            + slo.prom_families() + slo.alerts.prom_families())
    global _SELFTEST_TRAINER_FAMS
    _SELFTEST_TRAINER_FAMS = fams  # the ring selftest samples this too
    return _family_types(fams)


def scrape_inventory(url: str) -> dict:
    from distributed_sod_project_tpu.utils.observability import \
        parse_prom_text

    with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                timeout=10) as r:
        return _family_types(parse_prom_text(r.read().decode()))


def ring_inventory(ring_dir: str) -> dict:
    """Family names present in a flight-recorder ring's sample records
    (the on-disk schema).  Types are unknowable from a flat sample —
    every family maps to ``"recorded"`` and the type check is skipped
    for ring sections (name presence is the contract)."""
    from distributed_sod_project_tpu.utils.flightrecorder import \
        read_records

    fams = {}
    for rec in read_records(ring_dir):
        if rec.get("kind") != "sample":
            continue
        for series in (rec.get("v") or {}):
            fams[series.partition("{")[0]] = "recorded"
    return fams


def _ring_documented(name: str, base: dict) -> bool:
    """A ring series name is documented if the inventory has it
    verbatim, or (histogram ``_sum``/``_count`` series) has the family
    it derives from — tried second, so a counter family whose name
    itself ends in ``_sum`` (dsod_serve_batch_occupancy_sum) matches
    verbatim first."""
    if name in base:
        return True
    for suf in ("_sum", "_count"):
        if name.endswith(suf) and name[: -len(suf)] in base:
            return True
    return False


def selftest_ring_dir() -> str:
    """Build a synthetic ring in a temp dir: one FlightRecorder sample
    of the SAME populated fleet + trainer surfaces the inventory render
    uses — so the on-disk schema lint exercises the real
    flatten-families path end-to-end without a live process."""
    import tempfile

    from distributed_sod_project_tpu.utils.flightrecorder import \
        FlightRecorder
    from distributed_sod_project_tpu.utils.observability import \
        parse_prom_text
    from distributed_sod_project_tpu.utils.telemetry import \
        trainer_prom_families  # noqa: F401 — imported via inventories

    fleet_fams = parse_prom_text(_selftest_fleet_text())
    trainer_fams = _selftest_trainer_families()
    d = tempfile.mkdtemp(prefix="dsod_lint_ring_")
    rec = FlightRecorder(d, lambda: fleet_fams + trainer_fams,
                         sample_s=1.0)
    rec.sample()
    rec.ring.close()
    return d


# The populated surfaces, kept as module state so fleet_inventory() /
# trainer_inventory() and the ring selftest render the SAME text.
_SELFTEST_FLEET_TEXT = None
_SELFTEST_TRAINER_FAMS = None


def _selftest_fleet_text() -> str:
    global _SELFTEST_FLEET_TEXT
    if _SELFTEST_FLEET_TEXT is None:
        fleet_inventory()
    return _SELFTEST_FLEET_TEXT


def _selftest_trainer_families():
    global _SELFTEST_TRAINER_FAMS
    if _SELFTEST_TRAINER_FAMS is None:
        trainer_inventory()
    return _SELFTEST_TRAINER_FAMS


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--baseline", default=_BASELINE)
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--url", action="append", default=[],
                   help="scrape a live /metrics instead of the "
                        "in-process synthetic render (repeatable; "
                        "lints against the union inventory)")
    p.add_argument("--ring", action="append", default=[],
                   help="lint a flight-recorder segment ring's on-disk "
                        "sample schema against the union inventory "
                        "(repeatable; name check only — samples carry "
                        "no TYPE lines)")
    p.add_argument("--ring-selftest", action="store_true",
                   help="build a synthetic ring from the populated "
                        "fleet+trainer surfaces and lint it (the "
                        "non-gating t1.sh leg)")
    args = p.parse_args(argv)

    rings = list(args.ring)
    if args.ring_selftest:
        rings.append(selftest_ring_dir())
    if args.url or rings:
        sections = {}
        live = {}
        for u in args.url:
            live.update(scrape_inventory(u))
        if live:
            sections["live"] = live
        ring = {}
        for r in rings:
            inv = ring_inventory(r)
            if not inv:
                # A lint that read zero sample records must not report
                # success — a typo'd/empty --ring dir would otherwise
                # pass green without checking anything.
                print(json.dumps({
                    "metric": "metrics_inventory",
                    "error": f"ring {r!r} has no readable sample "
                             "records"}), flush=True)
                return 1
            ring.update(inv)
        if rings:
            ring.pop("", None)
            sections["ring"] = ring
    else:
        sections = {"fleet": fleet_inventory(),
                    "trainer": trainer_inventory()}

    baseline = None
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baseline = json.load(f)

    if args.update_baseline or baseline is None:
        if args.url or rings:
            print("metrics_lint: refusing to seed the baseline from a "
                  "live scrape or recorded ring (the synthetic render "
                  "is the canonical surface; run without --url/--ring)",
                  file=sys.stderr)
            return 1
        with open(args.baseline, "w") as f:
            json.dump(sections, f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({
            "metric": "metrics_inventory",
            "families": {s: len(v) for s, v in sections.items()},
            "recorded": True,
        }), flush=True)
        return 0

    base_union = {}
    for sec in baseline.values():
        base_union.update(sec)
    rc = 0
    report = {"metric": "metrics_inventory",
              "families": {s: len(v) for s, v in sections.items()}}
    undocumented, vanished, retyped = {}, {}, {}
    for sec, inv in sections.items():
        # "live" and "ring" sections lint against the UNION inventory
        # (a scrape/ring sees one deployment's subset — absence is not
        # drift); only the synthetic render checks vanished families.
        union_based = sec in ("live", "ring")
        base = base_union if union_based else baseline.get(sec, {})
        if sec == "ring":
            extra = sorted(n for n in inv
                           if not _ring_documented(n, base))
        else:
            extra = sorted(set(inv) - set(base))
        if extra:
            undocumented[sec] = extra
        if not union_based:
            gone = sorted(set(base) - set(inv))
            if gone:
                vanished[sec] = gone
        if sec != "ring":  # ring samples carry no TYPE lines
            changed = sorted(n for n in set(inv) & set(base)
                             if inv[n] != base[n])
            if changed:
                retyped[sec] = changed
    if undocumented:
        report["undocumented"] = undocumented
        rc = 2
    if vanished:
        report["vanished"] = vanished
        rc = 2
    if retyped:
        report["retyped"] = retyped
        rc = 2
    report["delta"] = 0 if rc == 0 else sum(
        len(v) for d in (undocumented, vanished, retyped)
        for v in d.values())
    print(json.dumps(report), flush=True)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
