#!/bin/bash
# Round-18 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 18).  Round 18 shipped the pod-scale communication engine on
# the (now only) rules engine: parallel.preset=fsdp as a first-class
# preset (params sharded over data, JIT all-gather fwd/bwd,
# reduce-scattered grads), hierarchical ICI×DCN collectives
# (mesh.data_hosts: per-bucket intra-host reduce-scatter → inter-host
# all-reduce on 1/chips of the bytes → intra-host all-gather), and
# int8 error-feedback wire compression
# (parallel.grad_compression=int8_ef, residual carried in train
# state).  FSDP-vs-DP parity (rtol 2e-6), hier-vs-flat bitwise on the
# integer wire, and the int8_ef quality budget are proven on CPU
# (tests/test_sharding_rules.py, tools/hlo_guard.py comm arms,
# tools/grad_comm_gate.py --arm int8_ef); tools/roofline.py --comm
# prices the flagship's ICI and DCN legs separately.  What only
# hardware can answer, predictions on record:
#
#   1. FSDP HBM: preset=fsdp at b64 (sync_bn off — GSPMD preset).
#      Prediction: per-device bytes_in_use drops MORE than zero=1's
#      measured drop (fsdp shards params + moments + EMA, zero=1 only
#      moments + EMA; ledger: zero_hbm_saved_bytes grows by the param
#      bytes × 7/8 at n_dp=8), step time within ±10% of the zero=1
#      arm at b64 — the JIT param all-gathers add wire but XLA
#      overlaps them with layer compute.
#   2. HIERARCHICAL @ 1 HOST: mesh.data_hosts=2 on the single-host
#      v5e-8 splits the ring into 2×4 — BOTH levels ride ICI here, so
#      the prediction is parity (±3% of the flat bucketed arm at
#      b128): the two-level program must not cost anything when DCN
#      isn't in the path.  The DCN win itself (ledger: 7/8 of
#      inter-host bytes off the slow hop) stays a multi-host-window
#      item — this arm proves the program shape is free.
#   3. INT8_EF WIRE: grad_compression=int8_ef at b128.  Prediction:
#      ledgered wire bytes <= 1/2 of the bf16 arm's (1 B/elem vs
#      2 B/elem achievable; XLA transports int32 today, so the STEP
#      TIME prediction is parity ±3% vs bf16 — the win this round is
#      the priced contract + quality budget, the transport win lands
#      with a wire-level int8 allreduce); quality delta stays within
#      the CPU-recorded grad_comm_gate int8_ef budget (drift 0.0011,
#      delta_loss +0.0031 at the gate's scale).
#
# Per the pre-committed rule defaults only flip where bit-identical:
# the rules engine IS the default (legacy deleted, bitwise-proven
# before removal); fsdp/data_hosts/int8_ef stay opt-in regardless of
# the numbers here (residency and wire arithmetic change), the
# predictions gate what configs get them recommended in
# PERFORMANCE.md.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results18}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 0. canonical headline refresh (the r5-r17 key replays unchanged —
#    engine=rules is the default now, so the bare flagship IS the
#    rules-engine bucketed arm).
run headline_b128      900 $BENCH --config minet_r50_dp

# -- 1. FSDP: step-time arms at b64 (the zero1 replay anchors the
#    comparison) + the direct HBM probe below.
run zero1_step_b64     900 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set parallel.zero=1 --set model.sync_bn=false
run fsdp_step_b64      900 $BENCH --config minet_r50_dp --batch-per-chip 64 \
    --set parallel.preset=fsdp --set model.sync_bn=false

# -- 2. hierarchical two-level collectives: flat bucketed ring vs the
#    2×4 intra/inter split on the same 8 chips (program-shape parity).
run hier_flat_b128     900 $BENCH --config minet_r50_dp
run hier_2host_b128    900 $BENCH --config minet_r50_dp \
    --set mesh.data_hosts=2

# -- 3. int8_ef gradient wire (quality budget held by grad_comm_gate
#    --arm int8_ef; bf16 replay is the byte-halving anchor).
run bf16_wire_b128     900 $BENCH --config minet_r50_dp \
    --set parallel.grad_compression=bf16
run int8_ef_wire_b128  900 $BENCH --config minet_r50_dp \
    --set parallel.grad_compression=int8_ef

cat > "$R"/fsdp_hbm_probe.py <<'EOF'
"""Per-device HBM in-use, zero=1 vs preset=fsdp, same model/batch: the
direct measurement behind agenda prediction 1 (one JSON line)."""
import gc
import json
import numpy as np

import jax


def in_use(label, cfg_overrides):
    from distributed_sod_project_tpu.configs import (apply_overrides,
                                                     get_config)
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel import make_mesh
    from distributed_sod_project_tpu.parallel.engine import \
        prepare_train_step
    from distributed_sod_project_tpu.train import (build_optimizer,
                                                   create_train_state)

    cfg = apply_overrides(get_config("minet_r50_dp"),
                          ["model.sync_bn=false"] + cfg_overrides)
    model = build_model(cfg.model)
    mesh = make_mesh(cfg.mesh)
    n = len(jax.devices())
    hw = 320
    batch = {"image": np.zeros((8 * n, hw, hw, 3), np.float32),
             "mask": np.zeros((8 * n, hw, hw, 1), np.float32)}
    tx, sched = build_optimizer(cfg.optim, 10)
    state = create_train_state(jax.random.key(0), model, tx, batch,
                               ema=cfg.optim.ema_decay > 0)
    state, step, plan = prepare_train_step(cfg, model, tx, mesh, sched,
                                           state, donate=False)
    jax.block_until_ready(state)
    stats = jax.devices()[0].memory_stats() or {}
    return {"arm": label,
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "zero_hbm_saved_bytes_planned":
                int(plan.get("zero_hbm_saved_bytes", 0))}


a = in_use("zero1", ["parallel.zero=1"])
gc.collect()  # release arm 0's buffers before arm 1 allocates
b = in_use("fsdp", ["parallel.preset=fsdp"])
print(json.dumps({"metric": "fsdp_hbm_probe",
                  "zero1": a, "fsdp": b,
                  "delta_bytes": a["bytes_in_use"] - b["bytes_in_use"]}))
EOF
run fsdp_hbm_probe 600 python "$R"/fsdp_hbm_probe.py

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
