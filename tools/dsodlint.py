#!/usr/bin/env python
"""dsodlint — AST invariant linter for the codebase's own hard-won
rules (docs/STATIC_ANALYSIS.md).

Thirteen PRs accreted invariants that lived only in CHANGES.md and
reviewers' heads.  This tool makes five of them machine-checked on
every ``tools/t1.sh`` run (pure-CPU, no imports of the checked code —
everything is ``ast`` over source text):

- ``traced-purity`` — no host synchronization or environment reads
  inside traced code: ``jax.device_get`` / ``.item()`` / ``float()`` /
  ``np.asarray`` / ``print`` / ``time.time`` / ``os.environ`` (and
  ``envvars.read``) calls reachable from any function passed to
  ``jit`` / ``shard_map`` / ``lax.scan`` / ``pallas_call`` — the PR-4
  one-device_get-per-chunk contract.  Env must be read at
  program-BUILD time; host syncs belong to the sanctioned flush seams
  (``TRACED_SEAMS`` below).
- ``lock-discipline`` — for classes in ``serve/`` / ``utils/`` that
  own a ``threading.Lock``/``RLock`` (or spawn threads), a ``self.*``
  attribute written both from a thread-entry call graph (Thread
  targets, executor submits, background loops) and elsewhere — or
  written locked in one place and unlocked in another — must only be
  mutated under ``with self._lock`` (the PR-7 check-then-put and PR-8
  inflight-gauge bug class).
- ``env-coherence`` — every ``DSOD_*`` env read goes through
  ``utils/envvars.py::read`` and every name read is registered there;
  the registry's ``program_affecting`` rows must equal
  ``bench.py::_PROGRAM_ENV_VARS`` exactly, both directions (the PR-3
  baseline-key contamination bug class).
- ``metrics-coherence`` — every ``dsod_*`` metric-family literal in
  source exists in ``tools/metrics_inventory.json`` and every
  inventory family is constructible from source literals (the static
  complement of the runtime ``tools/metrics_lint.py``).
- ``accounting-seams`` — the terminal counters
  (served/shed/expired/errors/submitted) may only move inside their
  declared booking seams (``BOOKING_SEAMS`` below), so the
  ``served + shed + expired + errors == submitted`` identity has
  exactly one owner per tier.

Waivers: ``# dsodlint: disable=<check>[,<check>] -- <reason>`` on the
finding's line, the line above, or the enclosing ``def`` line (scope
waiver).  A pragma without a reason is itself a finding.

Baseline discipline (the hlo_guard/metrics_lint conventions): one JSON
summary line, findings diffed against the checked-in
``tools/dsodlint_baseline.json``, ``--fail-on-new`` exit 2,
``--update-baseline`` re-seeds — and a run where any checker CRASHED
never writes a baseline (a crashed pass sees zero findings and would
seed an empty lie).

Usage:
    python tools/dsodlint.py                    # print delta line
    python tools/dsodlint.py --human            # readable findings
    python tools/dsodlint.py --fail-on-new      # gate (t1.sh leg)
    python tools/dsodlint.py --update-baseline  # re-seed the file
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHECKS = ("traced-purity", "lock-discipline", "env-coherence",
          "metrics-coherence", "accounting-seams", "pragma")

# What the suite scans (repo-relative).  Tests are deliberately out of
# scope: fixture code violates invariants on purpose.
SCAN_ROOTS = ("distributed_sod_project_tpu", "tools", "bench.py")

PKG = "distributed_sod_project_tpu"

# -- declared seams ----------------------------------------------------

# Host-reads sanctioned inside otherwise-traced reachability:
# (file, qualname).  Add a row ONLY with a comment saying why; the
# default posture is that the step builders stay pure.
TRACED_SEAMS: Set[Tuple[str, str]] = {
    # Build-time-only read: the flash block shapes are static ints
    # baked into the program at trace time, and both vars are
    # registered program-affecting (utils/envvars.py) so the bench
    # baseline key and AOT program caches stay coherent.
    (f"{PKG}/pallas/flash_attention.py", "_env_block"),
}

# The ONLY places a terminal counter may move, per tier
# (docs/SERVING.md "Failure semantics"; docs/STATIC_ANALYSIS.md).  A
# nested function inherits its enclosing seam (qualname prefix match).
BOOKING_SEAMS: Set[Tuple[str, str]] = {
    (f"{PKG}/serve/engine.py", "InferenceEngine.submit"),
    (f"{PKG}/serve/engine.py", "InferenceEngine.stop"),
    (f"{PKG}/serve/engine.py", "InferenceEngine._dispatch_group"),
    (f"{PKG}/serve/engine.py", "InferenceEngine._complete"),
    (f"{PKG}/serve/engine.py", "InferenceEngine._finish"),
    (f"{PKG}/serve/router.py", "RouterHandler.do_POST"),
    # Router-cache booking seam (serve/cache.py): the ONE place an
    # exact / near-dup / coalesced hit enters the router book as the
    # cache_hit terminal class — the fifth identity bucket
    # (served+shed+expired+errors+cache_hit == submitted).
    (f"{PKG}/serve/router.py", "RouterHandler._serve_cache_hit"),
    # Stream booking seam (serve/streams.py): the ONE place the
    # temporal-coherence fast path enters the router book as the
    # stream_reuse terminal class — the sixth identity bucket
    # (served+shed+expired+errors+cache_hit+stream_reuse == submitted).
    (f"{PKG}/serve/router.py", "RouterHandler._serve_stream_reuse"),
    # Control-plane decision seams: every autoscale/rollout counter
    # moves through ONE _record per plane, which also emits the
    # flight-recorder event — book and evidence cannot drift apart.
    (f"{PKG}/serve/controller.py", "FleetController._record"),
    (f"{PKG}/serve/rollout.py", "RolloutManager._record"),
}

# Terminal-counter families (the accounting identity's terms).
TERMINAL_COUNTERS = {"submitted", "served", "shed", "expired", "errors"}
# Router-book / arm-stat booking methods that move a terminal counter.
# The ctrl/rollout trio are the control-plane decision books — a stray
# inc_decision/inc_verdict outside the _record seams is exactly the
# book-without-evidence drift the seam exists to prevent.
TERMINAL_BOOKING_CALLS = {"inc_submitted", "inc_shed", "inc_response",
                          "inc_served", "inc_decision", "inc_restart",
                          "inc_verdict"}

# Functions that open a traced scope when a function object is passed
# to them (matched on the callee's terminal name: jax.jit, pl.jit,
# lax.scan, compat shard_map, pl.pallas_call all resolve).
TRACE_ENTRY_NAMES = {"jit", "shard_map", "scan", "pallas_call"}

_ENVVARS_FILE = f"{PKG}/utils/envvars.py"
_BENCH_FILE = "bench.py"
_INVENTORY = os.path.join(REPO, "tools", "metrics_inventory.json")

_PRAGMA_RE = re.compile(
    r"#\s*dsodlint:\s*disable=([A-Za-z0-9_,-]+)(?:\s*--\s*(.+?))?\s*$")
# A metric-family-shaped fragment: word-start ``dsod_`` (so
# ``libdsod_host.so`` / ``~/.cache/dsod_xla`` never match mid-token).
_DSOD_METRIC_RE = re.compile(r"(?<![A-Za-z0-9_])dsod_[a-z0-9_]+")


class Finding:
    __slots__ = ("check", "file", "line", "symbol", "detail", "msg")

    def __init__(self, check: str, file: str, line: int, symbol: str,
                 detail: str, msg: str):
        self.check = check
        self.file = file
        self.line = line
        self.symbol = symbol
        self.detail = detail
        self.msg = msg

    def key(self) -> str:
        """Line-number-free identity, so the baseline survives
        unrelated edits above a finding."""
        return f"{self.check} {self.file} {self.symbol} {self.detail}"

    def human(self) -> str:
        return (f"{self.file}:{self.line}: [{self.check}] {self.symbol}: "
                f"{self.msg}")


class SourceFile:
    """One parsed file: AST with parent/qualname annotations, raw
    lines, and pragma map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self._annotate()
        # line → {check_or_*: reason_or_None}
        self.pragmas: Dict[int, Dict[str, Optional[str]]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _PRAGMA_RE.search(line)
            if m:
                checks = {c.strip() for c in m.group(1).split(",")}
                reason = m.group(2)
                self.pragmas[i] = {c: reason for c in checks}

    def _annotate(self) -> None:
        scopes = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

        def walk(node, qual: str):
            for child in ast.iter_child_nodes(node):
                child._dsod_parent = node  # noqa: SLF001
                if isinstance(child, scopes):
                    q = f"{qual}.{child.name}" if qual else child.name
                    child._dsod_qualname = q  # noqa: SLF001
                    walk(child, q)
                else:
                    walk(child, qual)

        walk(self.tree, "")

    def qualname_at(self, node: ast.AST) -> str:
        n = node
        while n is not None:
            q = getattr(n, "_dsod_qualname", None)
            if q is not None:
                return q
            n = getattr(n, "_dsod_parent", None)
        return "<module>"

    def enclosing_def_lines(self, node: ast.AST) -> List[int]:
        out = []
        n = node
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                out.append(n.lineno)
            n = getattr(n, "_dsod_parent", None)
        return out

    def waiver(self, check: str, line: int,
               scope_lines: List[int]) -> Optional[Tuple[str, str]]:
        """A matching pragma for (check, line) — same line, the line
        above, or an enclosing def/class line.  Returns
        (reason_or_MISSING, at_line) or None."""
        for ln in [line, line - 1] + list(scope_lines):
            prag = self.pragmas.get(ln)
            if not prag:
                continue
            for key in (check, "*", "all"):
                if key in prag:
                    return (prag[key] if prag[key] is not None
                            else "__MISSING__"), str(ln)
        return None


# -- file discovery ----------------------------------------------------

def discover(root: str) -> List[str]:
    out = []
    for entry in SCAN_ROOTS:
        path = os.path.join(root, entry)
        if os.path.isfile(path):
            out.append(entry)
        elif os.path.isdir(path):
            for dirpath, _dirs, files in os.walk(path):
                for f in sorted(files):
                    if f.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, f),
                                              root)
                        out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def load_files(root: str) -> Tuple[Dict[str, SourceFile], List[str]]:
    files, errors = {}, []
    for rel in discover(root):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                text = f.read()
            files[rel] = SourceFile(rel, text)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
    return files, errors


# -- shared name-resolution engine -------------------------------------

def _dotted(rel: str) -> Optional[str]:
    """Repo-relative path → dotted module name (package files only)."""
    if not rel.endswith(".py"):
        return None
    mod = rel[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class ModuleIndex:
    """Cross-module symbol table: top-level functions + import map per
    file, so call edges can be followed into the package."""

    def __init__(self, files: Dict[str, SourceFile]):
        self.files = files
        self.by_module: Dict[str, SourceFile] = {}
        for rel, sf in files.items():
            mod = _dotted(rel)
            if mod:
                self.by_module[mod] = sf
        # rel → {name: FunctionDef} (module top level)
        self.top_funcs: Dict[str, Dict[str, ast.AST]] = {}
        # rel → {local_name: (module, original_name_or_None)}
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        for rel, sf in files.items():
            funcs, imps = {}, {}
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    funcs[node.name] = node
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ImportFrom):
                    mod = self._resolve_from(rel, node)
                    if mod:
                        for alias in node.names:
                            imps[alias.asname or alias.name] = \
                                (mod, alias.name)
                elif isinstance(node, ast.Import):
                    for alias in node.names:
                        imps[alias.asname or alias.name] = \
                            (alias.name, None)
            self.top_funcs[rel] = funcs
            self.imports[rel] = imps

    def _resolve_from(self, rel: str, node: ast.ImportFrom
                      ) -> Optional[str]:
        if node.level == 0:
            return node.module
        base = _dotted(rel) or ""
        parts = base.split(".")
        # level=1 is the CONTAINING package: for a plain module that
        # strips the module name; for a package __init__ it strips
        # nothing (the dotted name already IS the package).
        strip = node.level if not rel.endswith("/__init__.py") \
            else node.level - 1
        parts = parts[: len(parts) - strip] if strip <= len(parts) else []
        if node.module:
            parts += node.module.split(".")
        return ".".join(parts) if parts else None

    def module_file(self, mod: str) -> Optional[SourceFile]:
        return self.by_module.get(mod)

    def resolve_func(self, rel: str, name: str, _seen: Optional[Set] = None
                     ) -> Optional[Tuple[str, ast.AST]]:
        """A bare name at module scope of ``rel`` → (file, FunctionDef)
        within the repo, following from-import chains (packages
        re-export through __init__.py — recurse with a cycle guard)."""
        _seen = _seen if _seen is not None else set()
        if (rel, name) in _seen:
            return None
        _seen.add((rel, name))
        f = self.top_funcs.get(rel, {}).get(name)
        if f is not None:
            return rel, f
        imp = self.imports.get(rel, {}).get(name)
        if imp is not None:
            mod, orig = imp
            if orig is None:
                return None  # plain module import, not a function
            sf = self.module_file(mod)
            if sf is not None:
                hit = self.resolve_func(sf.rel, orig, _seen)
                if hit is not None:
                    return hit
            # from package import module?  (name is a module)
            sub = self.module_file(f"{mod}.{orig}")
            if sub is not None:
                return None
        return None

    def resolve_attr_func(self, rel: str, mod_alias: str, attr: str
                          ) -> Optional[Tuple[str, ast.AST]]:
        """``alias.attr(...)`` where alias is an imported repo module."""
        imp = self.imports.get(rel, {}).get(mod_alias)
        if imp is None:
            return None
        mod, orig = imp
        target = mod if orig is None else f"{mod}.{orig}"
        sf = self.module_file(target)
        if sf is None:
            return None
        f = self.top_funcs.get(sf.rel, {}).get(attr)
        return (sf.rel, f) if f is not None else None


def _callee_tail(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _local_defs(fn: ast.AST) -> Dict[str, ast.AST]:
    """Nested function defs immediately inside ``fn`` (any depth below
    fn but not inside deeper defs is fine to include — name lookup)."""
    out = {}
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


# -- checker: traced-purity --------------------------------------------

_SYNC_TIME_ATTRS = {"time", "monotonic", "perf_counter", "process_time"}


def _is_env_read(node: ast.Call) -> bool:
    """os.environ.get(...) / os.getenv(...) / envvars.read[...]()."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "get" and isinstance(f.value, ast.Attribute) \
                and f.value.attr == "environ":
            return True
        if f.attr == "getenv" and isinstance(f.value, ast.Name) \
                and f.value.id == "os":
            return True
        if f.attr in ("read", "read_int") and \
                isinstance(f.value, ast.Name) and \
                f.value.id == "envvars":
            return True
    elif isinstance(f, ast.Name) and f.id in ("getenv",):
        return True
    return False


def _sync_violation(node: ast.AST) -> Optional[str]:
    """The traced-purity violation a node constitutes, or None."""
    if isinstance(node, ast.Call):
        f = node.func
        tail = _callee_tail(f)
        if tail == "device_get":
            return "jax.device_get"
        if tail == "item" and isinstance(f, ast.Attribute):
            return ".item()"
        if isinstance(f, ast.Name) and f.id in ("print", "float"):
            return f"{f.id}()"
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in ("np", "numpy") and \
                    f.attr in ("asarray", "array"):
                return f"np.{f.attr}"
            if f.value.id == "time" and f.attr in _SYNC_TIME_ATTRS:
                return f"time.{f.attr}"
        if _is_env_read(node):
            return "environment read"
    elif isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "os":
        return "os.environ"
    return None


def check_traced_purity(files: Dict[str, SourceFile], index: ModuleIndex,
                        report) -> None:
    # 1. Collect traced roots: functions passed to jit/shard_map/scan/
    #    pallas_call, with one level of wrapper unwrapping (body =
    #    chunked_step_fn(step_fn, ...) → step_fn is a root too).
    roots: List[Tuple[str, ast.AST]] = []   # (file, funcdef)
    seen_ids: Set[int] = set()

    def add_root(rel: str, fn: ast.AST) -> None:
        if id(fn) not in seen_ids:
            seen_ids.add(id(fn))
            roots.append((rel, fn))

    for rel, sf in files.items():
        # local name → def node, per enclosing function scope
        for scope in ast.walk(sf.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Module)):
                continue
            local = _local_defs(scope) if not isinstance(scope, ast.Module) \
                else dict(index.top_funcs.get(rel, {}))
            # name → wrapped function args (body = wrapper(step_fn))
            assigned_from: Dict[str, ast.Call] = {}
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    assigned_from[node.targets[0].id] = node.value
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call)
                        and _callee_tail(node.func) in TRACE_ENTRY_NAMES):
                    continue
                args = list(node.args) + [kw.value for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Lambda):
                        add_root(rel, a)
                    elif isinstance(a, ast.Name):
                        if a.id in local:
                            add_root(rel, local[a.id])
                        elif a.id in assigned_from:
                            # one unwrap: the wrapper call's own
                            # function-name args become roots
                            inner = assigned_from[a.id]
                            for ia in (list(inner.args)
                                       + [k.value for k in inner.keywords]):
                                if isinstance(ia, ast.Name) \
                                        and ia.id in local:
                                    add_root(rel, local[ia.id])

    # 2. Reachability through the call graph (nested defs + module
    #    functions + one import hop), collecting violations per
    #    reached function body.
    visited: Set[Tuple[str, int]] = set()
    work = list(roots)
    while work:
        rel, fn = work.pop()
        if (rel, id(fn)) in visited:
            continue
        visited.add((rel, id(fn)))
        sf = files[rel]
        if (rel, sf.qualname_at(fn)) in TRACED_SEAMS:
            continue
        local = _local_defs(fn)
        # scan this function's own body, not nested defs' (they are
        # queued separately when actually called)
        nested = set()
        for name, nd in local.items():
            for sub in ast.walk(nd):
                nested.add(id(sub))
        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if id(node) in nested:
                    continue
                v = _sync_violation(node)
                if v is not None:
                    qn = sf.qualname_at(fn) if not isinstance(
                        fn, ast.Lambda) else sf.qualname_at(node)
                    report(Finding(
                        "traced-purity", rel, node.lineno, qn, v,
                        f"{v} reachable inside traced code (host "
                        "sync/IO belongs at the declared flush seams; "
                        "env is read at program-BUILD time)"))
                if isinstance(node, ast.Call):
                    f = node.func
                    target = None
                    if isinstance(f, ast.Name):
                        if f.id in local:
                            target = (rel, local[f.id])
                        else:
                            target = index.resolve_func(rel, f.id)
                    elif isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name):
                        target = index.resolve_attr_func(
                            rel, f.value.id, f.attr)
                    if target is not None:
                        work.append(target)
                    # function-valued ARGUMENTS stay traced too:
                    # jax.grad(loss_fn), maybe_remat(forward),
                    # tree_map(lambda ...) — the callee applies them
                    # inside the same trace.
                    for a in (list(node.args)
                              + [k.value for k in node.keywords]):
                        if isinstance(a, ast.Lambda):
                            work.append((rel, a))
                        elif isinstance(a, ast.Name):
                            if a.id in local:
                                work.append((rel, local[a.id]))
                            else:
                                t = index.resolve_func(rel, a.id)
                                if t is not None:
                                    work.append(t)


# -- checker: lock-discipline ------------------------------------------

def _is_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _write_targets(node: ast.AST) -> List[Tuple[str, int]]:
    """self.X = / self.X += / self.X[...] = writes in one statement."""
    out = []
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    for t in targets:
        for el in ast.walk(t):
            attr = _is_self_attr(el)
            if attr is not None:
                out.append((attr, node.lineno))
                break
            if isinstance(el, ast.Subscript):
                attr = _is_self_attr(el.value)
                if attr is not None:
                    out.append((attr, node.lineno))
                    break
    return out


def check_lock_discipline(files: Dict[str, SourceFile], report) -> None:
    scoped = {rel: sf for rel, sf in files.items()
              if rel.startswith((f"{PKG}/serve/", f"{PKG}/utils/"))}
    for rel, sf in scoped.items():
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if not methods:
                continue
            # lock attrs this class owns
            lock_attrs: Set[str] = set()
            spawns_threads = False
            for m in methods.values():
                for node in ast.walk(m):
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call) and \
                            _callee_tail(node.value.func) in \
                            ("Lock", "RLock"):
                        for t in node.targets:
                            attr = _is_self_attr(t)
                            if attr:
                                lock_attrs.add(attr)
                    if isinstance(node, ast.Call) and \
                            _callee_tail(node.func) in ("Thread", "Timer"):
                        spawns_threads = True
            if not lock_attrs and not spawns_threads:
                continue

            # thread entries: Thread(target=self.X)/Timer(.., self.X),
            # pool.submit(self.X, ...), local closures passed as
            # target= (their self.Y() calls and writes count as
            # thread-side, attributed to the enclosing method's
            # thread graph), plus the conventional run().
            entries: Set[str] = set()
            closure_thread_fns: List[ast.AST] = []
            for mname, m in methods.items():
                local = _local_defs(m)
                for node in ast.walk(m):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = _callee_tail(node.func)
                    cands: List[ast.AST] = []
                    if tail in ("Thread", "Timer"):
                        for kw in node.keywords:
                            if kw.arg == "target":
                                cands.append(kw.value)
                        if tail == "Timer" and len(node.args) >= 2:
                            cands.append(node.args[1])
                    elif tail == "submit" and node.args:
                        cands.append(node.args[0])
                    for c in cands:
                        attr = _is_self_attr(c)
                        if attr and attr in methods:
                            entries.add(attr)
                        elif isinstance(c, ast.Name) and c.id in local:
                            closure_thread_fns.append(local[c.id])
            if "run" in methods:
                entries.add("run")
            for fn in closure_thread_fns:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        attr = _is_self_attr(node.func)
                        if attr and attr in methods:
                            entries.add(attr)

            # close entries over the intra-class call graph
            calls: Dict[str, Set[str]] = {}
            for mname, m in methods.items():
                callees = set()
                for node in ast.walk(m):
                    if isinstance(node, ast.Call):
                        attr = _is_self_attr(node.func)
                        if attr and attr in methods:
                            callees.add(attr)
                calls[mname] = callees
            frontier = list(entries)
            while frontier:
                mname = frontier.pop()
                for c in calls.get(mname, ()):
                    if c not in entries:
                        entries.add(c)
                        frontier.append(c)

            # writes: attr → [(method, line, locked, thread_side)]
            writes: Dict[str, List[Tuple[str, int, bool, bool]]] = {}

            def scan_writes(m: ast.AST, mname: str,
                            thread_side: bool) -> None:
                nested = {id(s) for name, nd in _local_defs(m).items()
                          for s in ast.walk(nd)}
                # The ``*_locked`` naming convention: a method named
                # ``_foo_locked`` documents (and this linter trusts)
                # that every caller already holds the owning lock —
                # its writes count as locked.
                held_by_convention = mname.endswith("_locked")

                def locked_at(node):
                    if held_by_convention:
                        return True
                    n = node
                    while n is not None and n is not m:
                        if isinstance(n, ast.With):
                            for item in n.items:
                                ce = item.context_expr
                                attr = _is_self_attr(ce)
                                if attr is None and \
                                        isinstance(ce, ast.Call):
                                    attr = _is_self_attr(ce.func)
                                if attr in lock_attrs:
                                    return True
                        n = getattr(n, "_dsod_parent", None)
                    return False

                for node in ast.walk(m):
                    if id(node) in nested:
                        continue
                    for attr, line in _write_targets(node):
                        writes.setdefault(attr, []).append(
                            (mname, line, locked_at(node), thread_side))

            for mname, m in methods.items():
                scan_writes(m, mname, mname in entries)
            for fn in closure_thread_fns:
                # the closure runs ON the spawned thread
                nested_owner = sf.qualname_at(fn)
                scan_writes(fn, nested_owner.rsplit(".", 1)[-1], True)

            qual_prefix = sf.qualname_at(cls)
            for attr, sites in sorted(writes.items()):
                if attr in lock_attrs:
                    continue
                non_init = [s for s in sites if s[0] != "__init__"]
                if not non_init:
                    continue
                thread_writes = [s for s in non_init if s[3]]
                other_writes = [s for s in non_init if not s[3]]
                locked_writes = [s for s in non_init if s[2]]
                unlocked = [s for s in non_init if not s[2]]
                flag = None
                if thread_writes and other_writes and unlocked:
                    flag = ("cross-thread write of self.%s (thread "
                            "graph: %s; elsewhere: %s) outside the "
                            "owning lock" % (
                                attr,
                                ",".join(sorted({s[0]
                                                 for s in thread_writes})),
                                ",".join(sorted({s[0]
                                                 for s in other_writes}))))
                elif locked_writes and unlocked:
                    flag = ("mixed guard for self.%s: written under a "
                            "lock in %s but bare in %s" % (
                                attr,
                                ",".join(sorted({s[0]
                                                 for s in locked_writes})),
                                ",".join(sorted({s[0] for s in unlocked}))))
                if flag:
                    for mname, line, _lk, _th in unlocked:
                        report(Finding(
                            "lock-discipline", rel, line,
                            f"{qual_prefix}.{mname}", f"self.{attr}",
                            flag))


# -- checker: env-coherence --------------------------------------------

def _registry_entries(files: Dict[str, SourceFile]
                      ) -> Dict[str, bool]:
    """utils/envvars.py → {name: program_affecting}."""
    sf = files.get(_ENVVARS_FILE)
    if sf is None:
        raise RuntimeError(f"{_ENVVARS_FILE} not found")
    out: Dict[str, bool] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and \
                _callee_tail(node.func) == "EnvVar" and node.args:
            name = node.args[0]
            prog = node.args[2] if len(node.args) > 2 else None
            if isinstance(name, ast.Constant) and \
                    isinstance(name.value, str):
                out[name.value] = bool(
                    prog.value if isinstance(prog, ast.Constant) else False)
    return out


def _bench_program_vars(files: Dict[str, SourceFile]) -> Set[str]:
    sf = files.get(_BENCH_FILE)
    if sf is None:
        raise RuntimeError(f"{_BENCH_FILE} not found")
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        t.id == "_PROGRAM_ENV_VARS":
                    return {
                        el.value for el in ast.walk(node.value)
                        if isinstance(el, ast.Constant)
                        and isinstance(el.value, str)}
    raise RuntimeError("bench.py::_PROGRAM_ENV_VARS not found")


def _module_str_consts(sf: SourceFile) -> Dict[str, str]:
    out = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def check_env_coherence(files: Dict[str, SourceFile], report) -> None:
    registry = _registry_entries(files)
    bench_vars = _bench_program_vars(files)

    for name in registry:
        if not re.fullmatch(r"DSOD_[A-Z0-9_]+", name):
            report(Finding("env-coherence", _ENVVARS_FILE, 1,
                           "REGISTRY", name,
                           f"registry entry {name!r} is not a DSOD_* "
                           "name"))
    prog = {n for n, p in registry.items() if p}
    for name in sorted(prog - bench_vars):
        report(Finding("env-coherence", _BENCH_FILE, 1,
                       "_PROGRAM_ENV_VARS", name,
                       f"program-affecting registry entry {name} is "
                       "missing from bench.py::_PROGRAM_ENV_VARS "
                       "(baseline-key contamination)"))
    for name in sorted(bench_vars - prog):
        report(Finding("env-coherence", _BENCH_FILE, 1,
                       "_PROGRAM_ENV_VARS", name,
                       f"bench.py::_PROGRAM_ENV_VARS entry {name} is "
                       "not a program_affecting registry row in "
                       "utils/envvars.py"))

    for rel, sf in files.items():
        consts = _module_str_consts(sf)

        def lit_of(arg: ast.AST) -> Optional[str]:
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                return arg.value
            if isinstance(arg, ast.Name) and arg.id in consts:
                return consts[arg.id]
            return None

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_env_read(node):
                f = node.func
                via_registry = isinstance(f, ast.Attribute) and \
                    f.attr in ("read", "read_int")
                arg = node.args[0] if node.args else None
                name = lit_of(arg) if arg is not None else None
                qn = sf.qualname_at(node)
                if not via_registry and name is not None and \
                        name.startswith("DSOD_") and \
                        rel != _ENVVARS_FILE:
                    report(Finding(
                        "env-coherence", rel, node.lineno, qn,
                        f"bypass:{name}",
                        f"direct os.environ read of {name} bypasses "
                        "utils/envvars.py::read"))
                if name is not None and name.startswith("DSOD_") and \
                        name not in registry:
                    report(Finding(
                        "env-coherence", rel, node.lineno, qn,
                        f"unregistered:{name}",
                        f"{name} read but not registered in "
                        "utils/envvars.py"))
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "environ":
                name = lit_of(node.slice)
                if name is not None and name.startswith("DSOD_") and \
                        rel != _ENVVARS_FILE:
                    report(Finding(
                        "env-coherence", rel, node.lineno,
                        sf.qualname_at(node), f"bypass:{name}",
                        f"direct os.environ[{name!r}] read bypasses "
                        "utils/envvars.py::read"))


# -- checker: metrics-coherence ----------------------------------------

def _docstring_ids(sf: SourceFile) -> Set[int]:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


def check_metrics_coherence(files: Dict[str, SourceFile],
                            inventory_path: str, report) -> None:
    with open(inventory_path) as f:
        inv_doc = json.load(f)
    inventory: Set[str] = set()
    for section in inv_doc.values():
        inventory.update(section)

    # Namespaces that actually exist in the inventory (``serve`` from
    # ``dsod_serve_*`` etc.): a literal outside every known namespace
    # is a path/identifier (``dsod_xla`` cache dir, chaos run tags),
    # not a metric family — the runtime metrics_lint still catches a
    # genuinely new namespace when its surface first renders.
    namespaces = {fam.split("_", 2)[1] for fam in inventory
                  if fam.count("_") >= 2}

    def metric_shaped(m: str) -> bool:
        parts = m.split("_")
        return len(parts) >= 3 and parts[1] in namespaces

    names: Dict[str, Tuple[str, int]] = {}   # literal → first site
    prefixes: Set[str] = set()
    for rel, sf in files.items():
        if rel == "tools/dsodlint.py":
            continue  # self-referential examples
        doc_ids = _docstring_ids(sf)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if id(node) in doc_ids:
                continue
            for m in _DSOD_METRIC_RE.findall(node.value):
                if m.endswith("_"):
                    prefixes.add(m)
                elif metric_shaped(m) and m not in names:
                    names[m] = (rel, node.lineno)

    def documented(name: str) -> bool:
        if name in inventory:
            return True
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in inventory:
                return True
        return False

    for name, (rel, line) in sorted(names.items()):
        if not documented(name):
            report(Finding(
                "metrics-coherence", rel, line, "<literal>", name,
                f"metric-family literal {name!r} is not in "
                "tools/metrics_inventory.json (run tools/metrics_lint.py "
                "--update-baseline after an INTENDED surface change)"))

    def constructible(fam: str) -> bool:
        if fam in names:
            return True
        for suf in ("_bucket", "_sum", "_count", "_total"):
            if fam.endswith(suf) and fam[: -len(suf)] in names:
                return True
        return any(fam.startswith(p) for p in prefixes)

    for fam in sorted(inventory):
        if not constructible(fam):
            report(Finding(
                "metrics-coherence", "tools/metrics_inventory.json", 1,
                "<inventory>", fam,
                f"inventory family {fam!r} has no source literal or "
                "declared prefix that could render it"))


# -- checker: accounting-seams -----------------------------------------

def check_accounting_seams(files: Dict[str, SourceFile], report) -> None:
    for rel, sf in files.items():
        if not rel.startswith(f"{PKG}/serve/"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _callee_tail(node.func)
            hit = None
            if tail == "inc" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value in TERMINAL_COUNTERS:
                hit = f'inc("{node.args[0].value}")'
            elif tail in TERMINAL_BOOKING_CALLS and \
                    isinstance(node.func, ast.Attribute):
                # the booking METHODS' own definitions live outside
                # serve/ (utils/observability.py) or are the seam's
                # body (RouterStats.inc_* self-increments are plain
                # dict writes, not .inc calls)
                hit = f"{tail}()"
            if hit is None:
                continue
            qn = sf.qualname_at(node)
            ok = any(rel == f and (qn == q or qn.startswith(q + "."))
                     for f, q in BOOKING_SEAMS)
            if not ok:
                report(Finding(
                    "accounting-seams", rel, node.lineno, qn, hit,
                    f"terminal counter moved via {hit} outside the "
                    "declared booking seams (docs/STATIC_ANALYSIS.md: "
                    "extend BOOKING_SEAMS deliberately, with review)"))


# -- driver ------------------------------------------------------------

def run_checks(root: str, checks=CHECKS, inventory: Optional[str] = None):
    """Returns (findings, waived, crashed, parse_errors)."""
    files, parse_errors = load_files(root)
    index = ModuleIndex(files)
    findings: List[Finding] = []
    waived: List[Tuple[Finding, str, str]] = []
    crashed: Dict[str, str] = {}

    def reporter_for(check: str):
        def report(f: Finding) -> None:
            sf = files.get(f.file)
            if sf is not None:
                node_scope: List[int] = []
                # find enclosing def lines cheaply via pragma scan of
                # every def line is overkill; waiver() needs them, so
                # locate by qualname match
                for n in ast.walk(sf.tree):
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)) and \
                            getattr(n, "_dsod_qualname", None) and \
                            (f.symbol == n._dsod_qualname
                             or f.symbol.startswith(
                                 n._dsod_qualname + ".")):
                        node_scope.append(n.lineno)
                w = sf.waiver(f.check, f.line, node_scope)
                if w is not None:
                    reason, at = w
                    if reason == "__MISSING__":
                        findings.append(Finding(
                            "pragma", f.file, int(at), f.symbol,
                            f"missing-reason:{f.check}",
                            "dsodlint pragma without a reason string "
                            "(write: # dsodlint: disable=<check> -- "
                            "<why this is safe>)"))
                    else:
                        waived.append((f, reason, at))
                    return
            findings.append(f)
        return report

    for check in checks:
        if check == "pragma":
            continue
        try:
            if check == "traced-purity":
                check_traced_purity(files, index,
                                    reporter_for(check))
            elif check == "lock-discipline":
                check_lock_discipline(files, reporter_for(check))
            elif check == "env-coherence":
                check_env_coherence(files, reporter_for(check))
            elif check == "metrics-coherence":
                check_metrics_coherence(
                    files, inventory or _INVENTORY,
                    reporter_for(check))
            elif check == "accounting-seams":
                check_accounting_seams(files, reporter_for(check))
        except Exception as e:  # noqa: BLE001 — crash isolation per pass
            crashed[check] = f"{type(e).__name__}: {e}"
    return findings, waived, crashed, parse_errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=REPO)
    p.add_argument("--baseline", default=None,
                   help="findings baseline (default: "
                        "tools/dsodlint_baseline.json under --root — "
                        "NOT this repo's, so a --root run on another "
                        "tree can never clobber the checked-in file)")
    p.add_argument("--inventory", default=None,
                   help="metrics inventory path (default: "
                        "tools/metrics_inventory.json next to --root's "
                        "tools, falling back to this repo's)")
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--fail-on-new", action="store_true",
                   help="exit 2 when findings appear that are not in "
                        "the baseline")
    p.add_argument("--check", action="append", default=[],
                   choices=[c for c in CHECKS if c != "pragma"],
                   help="run only these checkers (repeatable)")
    p.add_argument("--human", action="store_true",
                   help="readable findings instead of the one-line "
                        "JSON summary")
    args = p.parse_args(argv)

    root = os.path.abspath(args.root)
    if args.baseline is None:
        args.baseline = os.path.join(root, "tools",
                                     "dsodlint_baseline.json")
    inventory = args.inventory
    if inventory is None:
        cand = os.path.join(root, "tools", "metrics_inventory.json")
        inventory = cand if os.path.exists(cand) else _INVENTORY
    checks = tuple(args.check) or CHECKS

    findings, waived, crashed, parse_errors = run_checks(
        root, checks=checks, inventory=inventory)

    current = sorted({f.key() for f in findings})
    baseline = None
    if os.path.exists(args.baseline):
        with open(args.baseline) as f:
            baseline = json.load(f)

    if crashed or parse_errors:
        # NEVER seed or refresh a baseline from a crashed run: a
        # crashed checker reports zero findings, and recording that as
        # the baseline would green-light every future violation.
        payload = {"metric": "dsodlint", "error": "checker crashed",
                   "crashed": crashed, "parse_errors": parse_errors}
        print(json.dumps(payload), flush=True)
        return 1

    # --fail-on-new never auto-seeds: a gate run on a baseline-less
    # tree must treat every finding as new, not silently bless it.
    if args.update_baseline or (baseline is None
                                and not args.fail_on_new):
        if args.human:
            for f in sorted(findings, key=lambda f: (f.file, f.line)):
                print(f.human())
        with open(args.baseline, "w") as f:
            json.dump({"version": 1, "findings": current}, f, indent=2)
            f.write("\n")
        print(json.dumps({
            "metric": "dsodlint", "findings": len(current),
            "waived": len(waived), "recorded": True}), flush=True)
        return 0

    base = set(baseline.get("findings", [])) if baseline else set()
    new = sorted(set(current) - base)
    fixed = sorted(base - set(current))

    if args.human:
        for f in sorted(findings, key=lambda f: (f.file, f.line)):
            marker = "NEW " if f.key() in set(new) else ""
            print(f"{marker}{f.human()}")
        for f, reason, at in sorted(waived,
                                    key=lambda w: (w[0].file, w[0].line)):
            print(f"waived {f.human()}  [pragma@{at}: {reason}]")
        if fixed:
            print("fixed since baseline:")
            for k in fixed:
                print(f"  {k}")
    summary = {
        "metric": "dsodlint",
        "checks": list(checks),
        "findings": len(current),
        "waived": len(waived),
        "new": new,
        "fixed": fixed,
        "delta": len(new),
    }
    print(json.dumps(summary), flush=True)
    if args.fail_on_new and new:
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
