#!/usr/bin/env python
"""Bisect the swin_sod EVAL TPU-worker crash (round-2 session 3).

``bench.py --config swin_sod --mode eval`` crashed the v5e worker
twice ("kernel fault", tpu_results/zoo.log); the train step is fine,
and eval of every other zoo member is fine.  The train/eval program
differences are small and enumerable, so each stage below isolates one
of them, IN A SUBPROCESS, smallest program first:

  metrics_only    the 256-bin scatter-add metric update, no model
  backbone        SwinT forward alone (ignores train — shared by the
                  working train step)
  fwd_b1          full model, train=False, batch 1
  fwd             full model, train=False, eval batch
  fwd_trainflag   full model, train=True + mutable BN (the working
                  train step's forward, minus grad) — isolates the
                  running-average-BN vs batch-BN program difference
  eval_step       make_eval_step (shard_map + sigmoid)
  eval_xla_resize eval_step with DSOD_RESIZE_IMPL=xla — isolates the
                  round-2 slice/lerp resize fast path
  eval_metrics_nofuse  the crasher's program with XLA fusion passes
                  disabled — implicates/exonerates a fused kernel
                  (the scatter-metrics fusion suspect) in one stage
  eval_metrics    eval_step + metric update, the reproduced crasher —
                  LAST: a worker kill can wedge the tunnel for hours

After any CRASHED/WEDGED stage the tool re-probes the backend
out-of-process; if the tunnel is dead it STOPS and reports, rather
than burning 900 s per remaining stage against a wedged transport.

    python tools/bisect_swin_eval.py            # all stages
    python tools/bisect_swin_eval.py --stage fwd_b1
    python tools/bisect_swin_eval.py --export-check   # no hardware

``--export-check`` (VERDICT r3 item 7) serializes every stage's
jitted program for platforms=['tpu'] via jax.export ON CPU at the
real crash shapes.  What it can exclude: StableHLO lowering /
cross-platform legalization failures.  What it cannot: Mosaic/XLA:TPU
*backend* compilation and runtime faults (the export path stops at
serialized StableHLO — no TPU codegen happens off-device).  Result of
the round-4 run: ALL stages export clean at b32@320 (see
docs/PERFORMANCE.md swin note), so the crash is a backend
compile/runtime fault, not a lowering bug — consistent with the
worker dying only on real hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PLATFORM = """
import jax
{platform_select}
import os as _os


def finish(label, jitted, fargs, run):
    '''Shared stage footer: execute the stage (default), or — with
    DSOD_BISECT_EXPORT=1 — serialize the same jitted program for the
    TPU platform via jax.export WITHOUT running it.  The export path
    works on the CPU backend, so it checks cross-platform (StableHLO)
    lowering of the exact crash-shaped program with no hardware.'''
    if _os.environ.get("DSOD_BISECT_EXPORT") == "1":
        from jax import export as _jexport

        exp = _jexport.export(jitted, platforms=["tpu"])(*fargs)
        print(label, "EXPORT-TPU ok:",
              len(exp.mlir_module_serialized), "bytes")
    else:
        print(label, "ok", run())
"""

_PRELUDE = _PLATFORM + """
import jax.numpy as jnp, numpy as np
from distributed_sod_project_tpu.configs import get_config, apply_overrides
from distributed_sod_project_tpu.models import build_model
from distributed_sod_project_tpu.parallel.mesh import (
    batch_sharding, make_mesh, replicated_sharding)
from distributed_sod_project_tpu.train import (
    build_optimizer, create_train_state)
from distributed_sod_project_tpu.train.state import TrainState

B = max({batch}, jax.device_count())  # batch must shard over the mesh
cfg = get_config("swin_sod")
cfg = apply_overrides(cfg, [f"global_batch_size={{B}}",
                            "data.image_size={hw},{hw}"])
mesh = make_mesh(cfg.mesh)
model = build_model(cfg.model)
rng = np.random.RandomState(0)
batch = {{
    "image": rng.randn(B, {hw}, {hw}, 3).astype(np.float32),
    "mask": (rng.rand(B, {hw}, {hw}, 1) > 0.5).astype(np.float32),
}}
tx, _ = build_optimizer(cfg.optim, 100)
state = create_train_state(jax.random.key(0), model, tx, batch)
state = TrainState(step=state.step, params=state.params,
                   batch_stats=state.batch_stats, opt_state=())
state = jax.device_put(state, replicated_sharding(mesh))
dev = jax.device_put(batch, batch_sharding(mesh))
"""

# No model at all: just the scatter-add metric kernel on random probs.
_METRICS_ONLY = _PLATFORM + """
import jax.numpy as jnp, numpy as np
from distributed_sod_project_tpu.metrics.streaming import (
    init_fbeta_state, update_fbeta_state)
B = {batch}
rng = np.random.RandomState(0)
probs = jnp.asarray(rng.rand(B, {hw}, {hw}).astype(np.float32))
gt = jnp.asarray((rng.rand(B, {hw}, {hw}, 1) > 0.5).astype(np.float32))
upd = jax.jit(update_fbeta_state, donate_argnums=0)


def _run():
    acc = init_fbeta_state()
    for _ in range(3):
        acc = upd(acc, probs, gt)
    return float(acc.mae_sum)


finish("metrics", upd, (init_fbeta_state(), probs, gt), _run)
"""

_BACKBONE = _PLATFORM + """
import jax.numpy as jnp, numpy as np
from distributed_sod_project_tpu.models.backbones.swin import SwinT
B = {batch}
rng = np.random.RandomState(0)
img = jnp.asarray(rng.randn(B, {hw}, {hw}, 3).astype(np.float32))
bb = SwinT(dtype=jnp.bfloat16)
vars_ = bb.init(jax.random.key(0), img)
fn = jax.jit(lambda v, x: [f.astype(jnp.float32).sum()
                           for f in bb.apply(v, x)])
finish("backbone", fn, (vars_, img),
       lambda: [float(s) for s in fn(vars_, img)])
"""

_FWD = _PRELUDE + """
fn = jax.jit(lambda s, b: model.apply(
    {{"params": s.params, "batch_stats": s.batch_stats}},
    b["image"], None, train=False)[0])
finish("fwd", fn, (state, dev),
       lambda: float(fn(state, dev).astype(jnp.float32).sum()))
"""

# The working train step's forward (train=True + mutable BN), no grad:
# if this passes where fwd crashes, the BN running-average program
# difference is implicated.
_FWD_TRAINFLAG = _PRELUDE + """
def f(s, b):
    outs, _ = model.apply(
        {{"params": s.params, "batch_stats": s.batch_stats}},
        b["image"], None, train=True, mutable=["batch_stats"],
        rngs={{"dropout": jax.random.key(0)}})
    return outs[0]
fn = jax.jit(f)
finish("fwd_trainflag", fn, (state, dev),
       lambda: float(fn(state, dev).astype(jnp.float32).sum()))
"""

_EVAL_STEP = _PRELUDE + """
from distributed_sod_project_tpu.train.step import make_eval_step
estep = make_eval_step(model, mesh)
finish("eval_step", estep, (state, dev),
       lambda: float(estep(state, dev).astype(jnp.float32).sum()))
"""

# Eval step + device-side metric accumulation (what bench --mode eval
# timed in round 2, and what crashed).
_EVAL_METRICS = _PRELUDE + """
from distributed_sod_project_tpu.train.step import make_eval_step
from distributed_sod_project_tpu.metrics.streaming import (
    init_fbeta_state, update_fbeta_state)
estep = make_eval_step(model, mesh)
upd = jax.jit(update_fbeta_state, donate_argnums=0)


def _run():
    acc = init_fbeta_state()
    for _ in range(3):
        probs = estep(state, dev)
        acc = upd(acc, probs, dev["mask"])
    return float(acc.mae_sum)


def _combined(acc, s, b):
    return upd(acc, estep(s, b), b["mask"])


finish("eval+metrics", jax.jit(_combined), (init_fbeta_state(), state, dev),
       _run)
"""

# (name, source, extra_env, batch_override) — order = smallest program
# first; the known crasher stays LAST.  eval_metrics_nofuse (VERDICT
# r3 item 7) runs the crasher's program with XLA's fusion passes
# disabled: if IT survives where eval_metrics kills the worker, the
# fault lives in a fused kernel (the scatter-metrics fusion suspect),
# not in any single op — and vice versa.  Unknown pass names in
# --xla_disable_hlo_passes are ignored, so the stage degrades to a
# duplicate-of-crasher rather than an error on backends that name the
# passes differently.
_NOFUSE_FLAGS = ("--xla_disable_hlo_passes="
                 "fusion,priority-fusion,multi-output-fusion")
_STAGES = [
    ("metrics_only", _METRICS_ONLY, {}, None),
    ("backbone", _BACKBONE, {}, None),
    ("fwd_b1", _FWD, {}, 1),
    ("fwd", _FWD, {}, None),
    ("fwd_trainflag", _FWD_TRAINFLAG, {}, None),
    ("eval_step", _EVAL_STEP, {}, None),
    ("eval_xla_resize", _EVAL_STEP, {"DSOD_RESIZE_IMPL": "xla"}, None),
    ("eval_metrics_nofuse", _EVAL_METRICS, {"XLA_FLAGS": _NOFUSE_FLAGS},
     None),
    ("eval_metrics", _EVAL_METRICS, {}, None),
]


def _probe_backend(timeout: float = 90.0) -> bool:
    """Out-of-process dial: is the TPU still answering?"""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout, cwd=_REPO)
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and ("tpu" in r.stdout.lower()
                                  or "axon" in r.stdout.lower())


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--stage", default=None,
                   choices=[n for n, *_ in _STAGES])
    p.add_argument("--batch", type=int, default=32,
                   help="eval batch (round-2 crash was at the zoo's 32)")
    p.add_argument("--image-size", type=int, default=320)
    p.add_argument("--device", default="tpu", choices=["tpu", "cpu"],
                   help="cpu = smoke-test THIS TOOL's machinery on tiny "
                        "shapes (platform picked via config.update so a "
                        "wedged tunnel is never dialled); the bisect "
                        "itself is tpu")
    p.add_argument("--export-check", action="store_true",
                   help="no hardware: on the CPU backend, jax.export "
                        "each stage's jitted program for platforms="
                        "['tpu'] at the CRASH shapes instead of running "
                        "it — rules lowering-level causes in or out "
                        "(VERDICT r3 item 7); combine with the default "
                        "--batch/--image-size for the real shapes")
    p.add_argument("--timeout", type=int, default=900)
    p.add_argument("--json-out", default=None,
                   help="write a {stage: verdict} summary here")
    args = p.parse_args(argv)

    if args.export_check:
        args.device = "cpu"
    platform_select = (
        'jax.config.update("jax_platforms", "cpu")'
        if args.device == "cpu" else "")
    stages = [(n, s, e, b) for n, s, e, b in _STAGES
              if args.stage in (None, n)]
    verdicts = {}
    for name, src, extra_env, b_over in stages:
        b = b_over if b_over is not None else args.batch
        src = src.format(batch=b, hw=args.image_size,
                         platform_select=platform_select)
        env = dict(os.environ, **extra_env)
        if args.export_check:
            env["DSOD_BISECT_EXPORT"] = "1"
        print(f"== {name} (b={b}{', ' if extra_env else ''}"
              f"{' '.join(f'{k}={v}' for k, v in extra_env.items())})",
              flush=True)
        try:
            r = subprocess.run([sys.executable, "-c", src],
                               capture_output=True, text=True, env=env,
                               timeout=args.timeout, cwd=_REPO)
        except subprocess.TimeoutExpired:
            verdicts[name] = "WEDGED"
            print("   WEDGED (timeout)", flush=True)
        else:
            if r.returncode == 0:
                verdicts[name] = "OK"
                print("   OK:", (r.stdout or "").strip().splitlines()[-1:],
                      flush=True)
            else:
                verdicts[name] = f"CRASHED rc={r.returncode}"
                print(f"   CRASHED rc={r.returncode}", flush=True)
                for line in (r.stderr or "").strip().splitlines()[-8:]:
                    print("   |", line[:200], flush=True)
        if (verdicts[name] != "OK" and len(stages) > 1
                and args.device == "tpu"):
            # A worker kill can take the whole tunnel with it; do not
            # spend 900 s per remaining stage on a dead transport.
            if not _probe_backend():
                print("!! backend no longer answering — stopping bisect "
                      "(remaining stages would only measure the wedge)",
                      flush=True)
                verdicts["_aborted"] = "backend dead after failure"
                break
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdicts, f, indent=2)
    print(json.dumps(verdicts), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
