#!/usr/bin/env python
"""Bisect the swin_sod EVAL TPU-worker crash (round-2 session 3).

``bench.py --config swin_sod --mode eval`` crashed the v5e worker
twice ("kernel fault"; the train step is fine, and eval of every other
zoo member is fine).  This drives the eval program's pieces one at a
time IN SUBPROCESSES so the crashing stage is identified without
taking down the parent, smallest first:

    python tools/bisect_swin_eval.py            # all stages
    python tools/bisect_swin_eval.py --stage fwd_b1

Each stage prints CRASHED/OK plus the tail of stderr on failure.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

_STAGES = {}


def _stage(name):
    def deco(src):
        _STAGES[name] = src
        return src
    return deco


_PRELUDE = """
import jax, jax.numpy as jnp, numpy as np
from distributed_sod_project_tpu.configs import get_config, apply_overrides
from distributed_sod_project_tpu.models import build_model
from distributed_sod_project_tpu.parallel.mesh import (
    batch_sharding, make_mesh, replicated_sharding)
from distributed_sod_project_tpu.train import (
    build_optimizer, create_train_state)
from distributed_sod_project_tpu.train.state import TrainState

B = {batch}
cfg = get_config("swin_sod")
cfg = apply_overrides(cfg, [f"global_batch_size={{B}}",
                            "data.image_size=320,320"])
mesh = make_mesh(cfg.mesh)
model = build_model(cfg.model)
rng = np.random.RandomState(0)
batch = {{
    "image": rng.randn(B, 320, 320, 3).astype(np.float32),
    "mask": (rng.rand(B, 320, 320, 1) > 0.5).astype(np.float32),
}}
tx, _ = build_optimizer(cfg.optim, 100)
state = create_train_state(jax.random.key(0), model, tx, batch)
state = TrainState(step=state.step, params=state.params,
                   batch_stats=state.batch_stats, opt_state=())
state = jax.device_put(state, replicated_sharding(mesh))
dev = jax.device_put(batch, batch_sharding(mesh))
"""

# Plain forward, no eval-step machinery.
_STAGES["fwd_b1"] = _PRELUDE + """
fn = jax.jit(lambda s, b: model.apply(
    {"params": s.params, "batch_stats": s.batch_stats},
    b["image"], None, train=False)[0])
out = fn(state, dev)
print("fwd ok", float(out.astype(jnp.float32).sum()))
"""

# The real eval step (sigmoid probs) without metric accumulation.
_STAGES["eval_step"] = _PRELUDE + """
from distributed_sod_project_tpu.train.step import make_eval_step
estep = make_eval_step(model, mesh)
probs = estep(state, dev)
print("eval step ok", float(probs.astype(jnp.float32).sum()))
"""

# Eval step + device-side metric accumulation (what bench --mode eval
# times, and what crashed).
_STAGES["eval_metrics"] = _PRELUDE + """
from distributed_sod_project_tpu.train.step import make_eval_step
from distributed_sod_project_tpu.metrics.streaming import (
    init_fbeta_state, update_fbeta_state)
estep = make_eval_step(model, mesh)
upd = jax.jit(update_fbeta_state, donate_argnums=0)
acc = init_fbeta_state()
for _ in range(3):
    probs = estep(state, dev)
    acc = upd(acc, probs, dev["mask"])
print("eval+metrics ok", float(acc.mae_sum))
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--stage", default=None, choices=sorted(_STAGES))
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--timeout", type=int, default=900)
    args = p.parse_args(argv)

    names = [args.stage] if args.stage else list(_STAGES)
    for name in names:
        src = _STAGES[name].format(batch=args.batch)
        print(f"== {name} (b={args.batch})", flush=True)
        try:
            r = subprocess.run([sys.executable, "-c", src],
                               capture_output=True, text=True,
                               timeout=args.timeout)
        except subprocess.TimeoutExpired:
            print("   WEDGED (timeout)")
            continue
        if r.returncode == 0:
            print("   OK:", (r.stdout or "").strip().splitlines()[-1:])
        else:
            tail = (r.stderr or "").strip().splitlines()[-6:]
            print(f"   CRASHED rc={r.returncode}")
            for line in tail:
                print("   |", line[:200])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
