#!/bin/bash
# Round-16 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 16).  Round 16 landed the router-door response cache
# (serve/cache.py; docs/SERVING.md "Router cache"): content-addressed
# LRU keyed on payload×model×arm×loaded-step, in-flight coalescing,
# and a quality-gated perceptual-hash near-dup arm.  Correctness,
# accounting (the five-bucket identity), and the quality ledger are
# proven on CPU (tests/test_cache.py, tools/cache_gate.py); the CPU
# out-of-process A/B measured 20.4x closed-loop throughput at 96% hit
# rate with hit p50 2.9 ms.  What only hardware can answer is the
# cache's LEVERAGE against a real TPU forward and its tax on the miss
# path:
#
#   1. canonical b128 headline refresh (comparison anchor)
#   2. CACHE serve A/B under the Zipf duplicate mix: a real-process
#      TPU server (tools/serve.py --fleet-config), closed-loop
#      loadgen at --zipf 1.1:16.  Legs: cache off / exact+coalesce /
#      +near-dup(h=16, --perturb 0.3).  Predictions on record:
#      hit-path p50 < 5 ms (hash + dict read + socket, no device
#      round-trip — CPU measured 2.9 ms and the TPU box's faster
#      cores only help); >= 1.5x closed-loop throughput vs off at
#      >= 40% hit rate (CPU leverage was 20.4x at 96%; the TPU
#      forward is faster so the ratio compresses — 1.5x is the
#      conservative floor the acceptance bar prices); fleet identity
#      consistent on every leg (served+shed+expired+errors+cache_hit
#      == submitted).
#   3. MISS-PATH tax: same server, --zipf 0:400 (catalog so large and
#      flat that every draw is effectively unique — ~0% hit rate).
#      Prediction on record: < 2% p50 tax vs cache-off — a miss costs
#      one sha256 + one dict probe + (near arm) one 16x16 block-mean
#      phash, all host-side, nothing on the device path.
#
# Per the pre-committed rule the cache default stays OFF regardless of
# the numbers here (dedup rate is a property of the TRAFFIC, not the
# box); the predictions gate what hit rate makes arming it free lunch.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results16}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r15 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2 + 3. cache serve A/B: one real-process TPU server per leg, the
#    fleet config differing ONLY in the cache knobs; loadgen is a
#    separate process (the CPU A/B's lesson: an in-process client
#    understates the cache because forwards release the GIL in XLA
#    while hits are pure Python).
cache_leg() { # cache_leg NAME ZIPF PERTURB CACHE_JSON_FRAGMENT
  local name=$1 zipf=$2 perturb=$3 frag=$4
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
  local fleet="$R/${name}_fleet.json" pfile="$R/${name}_port"
  rm -f "$pfile"
  cat > "$fleet" <<EOF
{"models": [{"name": "minet", "config": "minet_r50_dp",
             "overrides": ["serve.precision_arms=f32",
                           "serve.precision=f32"]}]${frag}}
EOF
  timeout 900 python tools/serve.py --fleet-config "$fleet" \
      --device tpu --port 0 --port-file "$pfile" \
      > "$R/${name}_serve.out" 2>&1 &
  local spid=$!
  for _i in $(seq 1 300); do [ -f "$pfile" ] && break; sleep 1; done
  if [ ! -f "$pfile" ]; then
    echo "{\"step\": \"$name\", \"rc\": 1, \"result\": {\"error\": \"server never bound\"}}" >> "$R"/results.jsonl
    kill -9 $spid 2>/dev/null; return
  fi
  local port; port=$(cat "$pfile")
  # warmup fills the JIT + program caches (and, on cache legs, the LRU)
  timeout 300 python tools/loadgen.py --url "http://127.0.0.1:$port" \
      --mode closed --concurrency 4 --requests 40 --size 320 \
      --zipf "$zipf" --perturb "$perturb" --wait-ready 240 \
      > /dev/null 2>&1
  timeout 600 python tools/loadgen.py --url "http://127.0.0.1:$port" \
      --mode closed --concurrency 8 --requests 400 --size 320 \
      --zipf "$zipf" --perturb "$perturb" --server-stats \
      > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  kill -TERM $spid 2>/dev/null; wait $spid 2>/dev/null
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc" | tee -a "$R"/agenda.log
  if [ "$rc" -ne 0 ] && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing" | tee -a "$R"/agenda.log
    exit 2
  fi
}

cache_leg cache_off      "1.1:16" 0   ""
cache_leg cache_exact    "1.1:16" 0   ", \"cache_bytes\": 268435456"
cache_leg cache_near     "1.1:16" 0.3 ", \"cache_bytes\": 268435456, \"cache_near_dup\": true, \"cache_near_dup_hamming\": 16, \"cache_shadow_sample\": 8"
# miss-path tax: flat huge catalog — every draw effectively unique
cache_leg cache_miss_tax "0:400"  0   ", \"cache_bytes\": 268435456, \"cache_near_dup\": true, \"cache_near_dup_hamming\": 16"

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
