#!/bin/bash
# Round-11 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 11).  Round 11 landed end-to-end tracing + unified telemetry
# (utils/tracing.py spans through router/engine/batcher + the trainer
# telemetry sidecar — docs/OBSERVABILITY.md).  Correctness is proven
# on CPU (tests/test_tracing.py: every served request yields one
# rooted, gap-free span tree; retries/hedges share one trace id; the
# X-Timing header reconciles with the histograms); what only hardware
# can answer:
#
#   1. canonical b128 headline refresh (comparison anchor)
#   2. TRACING-OVERHEAD legs: the same serve bench at
#      trace_sample = 0 / 0.01 (default) / 1.0 — three identical
#      closed-loop runs against the real HTTP stack on the TPU.
#   3. ON-DEMAND PROFILE leg: a real train run with the telemetry
#      sidecar up; /debug/profile?seconds=5 mid-run must return a
#      non-empty jax.profiler dump and /metrics + /healthz must answer
#      while the device is mid-dispatch (the introspection promise).
#
# Predictions on record (docs/OBSERVABILITY.md "Overhead"):
# (a) p50 tax at 1% sampling < 1% vs sampled=0 (the unsampled path is
#     one crc32 + compare per request; CPU measured 0.3%/+2% noise
#     band — see the doc's CPU table);
# (b) p50 tax at 100% sampling < 5% (a handful of dict appends under
#     one lock per request; the ring is bounded so no growth term);
# (c) the on-demand profile leg perturbs step time only inside its
#     window: the sidecar /metrics dsod_train_step_time_ms within 5%
#     of the pre-profile value one logging interval after stop.
#
# Serve legs talk to processes started here (ephemeral ports,
# --port-file); loadgen itself never imports jax.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results11}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r10 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. tracing-overhead serve legs: identical closed-loop benches at
#       three sampling rates.  Compare p50/p99 across the three legs;
#       predictions (a)/(b) above.  The default-sampling leg also
#       doubles as the acceptance check the CPU measurement banked
#       (< 2% p50 at the default 1%).
for s in 0 0.01 1.0; do
  run "serve_trace_s${s}" 1500 $BENCH --config minet_r50_dp --mode serve \
      --steps 300 --set serve.trace_sample="$s" \
      --set "serve.batch_buckets=1,4,8,16"
done

# -- 3. on-demand profile + live-introspection leg: a real TPU train
#       run with the sidecar; mid-run, arm /debug/profile, scrape
#       /metrics + /healthz + /debug/traces, then lint the live
#       family inventory.
TELEM_PORT_FILE="$R/telemetry.port"
rm -f "$TELEM_PORT_FILE"
python train.py --config minet_r50_dp --device tpu \
  --workdir "$R/train_telem" --max-steps 60 \
  --set log_every_steps=10 --set trace_sample=0.25 \
  --telemetry-port 0 --telemetry-port-file "$TELEM_PORT_FILE" \
  > "$R"/train_telem.out 2> "$R"/train_telem.err &
TRAIN_PID=$!
for _ in $(seq 1 240); do [ -f "$TELEM_PORT_FILE" ] && break; sleep 2; done
if [ -f "$TELEM_PORT_FILE" ]; then
  TURL="http://127.0.0.1:$(cat "$TELEM_PORT_FILE")"
  # Let compilation finish and a few chunks land before profiling.
  sleep 30
  run telem_healthz 60 curl -sf "$TURL/healthz"
  run telem_metrics 60 curl -sf "$TURL/metrics" -o "$R"/telem_metrics.txt
  run telem_profile 180 curl -sf "$TURL/debug/profile?seconds=5"
  run telem_traces 60 curl -sf "$TURL/debug/traces?n=5" -o "$R"/telem_traces.json
  run telem_lint 120 python tools/metrics_lint.py --url "$TURL"
  # Profile dump non-empty? (jax.profiler writes plugins/profile/...)
  PROF_DIR=$(grep -o '"logdir": "[^"]*"' "$R"/telem_profile.out | cut -d'"' -f4)
  if [ -n "$PROF_DIR" ] && [ -n "$(find "$PROF_DIR" -type f 2>/dev/null | head -1)" ]; then
    echo "{\"step\": \"telem_profile_nonempty\", \"rc\": 0, \"result\": {\"dir\": \"$PROF_DIR\"}}" >> "$R"/results.jsonl
  else
    echo "{\"step\": \"telem_profile_nonempty\", \"rc\": 1, \"result\": null}" >> "$R"/results.jsonl
  fi
  wait "$TRAIN_PID"
  echo "{\"step\": \"train_telem_exit\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl
else
  echo "telemetry sidecar never bound a port — skipping profile legs" | tee -a "$R"/agenda.log
  kill -9 "$TRAIN_PID" 2>/dev/null
fi

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
