#!/usr/bin/env python
"""Generate a tiny real-file SOD dataset for convergence/overfit runs.

The shapes are learnable (masks are ellipses the image actually
contains, depth correlates with the mask), so a model that optimizes
end-to-end drives eval max-Fβ toward 1 on a held-in sweep — the
BASELINE.md convergence-evidence protocol.

    python tools/make_tiny_dataset.py --out /tmp/duts16 --n 16
    python tools/make_tiny_dataset.py --out /tmp/rgbd16 --n 16 --rgbd

``--eval-n K`` additionally writes K HELD-OUT samples (same generator
and layout, drawn from the rng stream *after* the train draws, so the
two sets are disjoint by construction) into ``--eval-out`` (default
``<out>_eval``).  Scoring the eval root with a model trained on the
train root is the in-env generalization signal (VERDICT r3 item 2):
a model that merely memorizes the 16 train images does not place
ellipses it never saw.

Layouts match data/folder.py:
  DUTS:  <out>/DUTS-TR-Image/*.jpg + <out>/DUTS-TR-Mask/*.png
  RGB-D: <out>/{RGB,depth,GT}/ with matching stems.
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image, ImageDraw


def make_sample(rng: np.random.RandomState, size: int):
    """(image RGB, mask L, depth L) with 1–3 salient ellipses."""
    img = Image.new(
        "RGB", (size, size),
        tuple(int(c) for c in rng.randint(0, 90, size=3)))
    mask = Image.new("L", (size, size), 0)
    di, dm = ImageDraw.Draw(img), ImageDraw.Draw(mask)
    for _ in range(rng.randint(1, 4)):
        w, h = rng.randint(size // 6, size // 2, size=2)
        x0, y0 = rng.randint(0, size - w), rng.randint(0, size - h)
        color = tuple(int(c) for c in rng.randint(140, 255, size=3))
        di.ellipse([x0, y0, x0 + w, y0 + h], fill=color)
        dm.ellipse([x0, y0, x0 + w, y0 + h], fill=255)
    # speckle noise so the mapping isn't a pure threshold
    noise = rng.randint(0, 25, size=(size, size, 3)).astype(np.uint8)
    img = Image.fromarray(
        np.clip(np.asarray(img, np.int16) + noise, 0, 255).astype(np.uint8))
    m = np.asarray(mask, np.float32) / 255.0
    depth = (0.25 + 0.6 * m) * 255.0 + rng.randn(size, size) * 8.0
    depth_im = Image.fromarray(np.clip(depth, 0, 255).astype(np.uint8), "L")
    return img, mask, depth_im


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--n", type=int, default=16)
    p.add_argument("--size", type=int, default=96)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--rgbd", action="store_true",
                   help="NJU2K/NLPR-style RGB+depth+GT layout")
    p.add_argument("--eval-n", type=int, default=0,
                   help="also write this many HELD-OUT samples (drawn "
                        "after the train draws — disjoint by "
                        "construction) into --eval-out")
    p.add_argument("--eval-out", default=None,
                   help="held-out root (default: <out>_eval)")
    args = p.parse_args(argv)

    rng = np.random.RandomState(args.seed)
    if args.rgbd:
        dirs = {"img": "RGB", "mask": "GT", "depth": "depth"}
    else:
        dirs = {"img": "DUTS-TR-Image", "mask": "DUTS-TR-Mask"}

    def write_split(out, n, stem_fmt):
        for d in dirs.values():
            os.makedirs(os.path.join(out, d), exist_ok=True)
        for i in range(n):
            img, mask, depth = make_sample(rng, args.size)
            stem = stem_fmt.format(i)
            img.save(os.path.join(out, dirs["img"], stem + ".jpg"),
                     quality=95)
            mask.save(os.path.join(out, dirs["mask"], stem + ".png"))
            if args.rgbd:
                depth.save(os.path.join(out, dirs["depth"],
                                        stem + ".png"))

    write_split(args.out, args.n, "tiny_{:04d}")
    print(f"wrote {args.n} samples to {args.out} "
          f"({'RGB-D' if args.rgbd else 'DUTS'} layout)")
    if args.eval_n:
        eval_out = args.eval_out or args.out.rstrip("/") + "_eval"
        write_split(eval_out, args.eval_n, "tinyeval_{:04d}")
        print(f"wrote {args.eval_n} HELD-OUT samples to {eval_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
