#!/bin/bash
# Round-9 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 9).  Round 9 landed the multi-model, multi-tenant serving
# fleet (serve/fleet.py + serve/router.py: X-Model routing, per-tenant
# token-bucket budgets, ONE interleaved dispatch loop draining
# co-resident per-model batchers fairly — docs/SERVING.md "Fleet").
# Routing/tenancy/accounting are already proven on CPU (tests +
# tools/fleet_smoke.py); what only hardware can answer:
#
#   1. canonical b128 headline refresh (comparison anchor; untouched by
#      the fleet work, so any drift is environmental)
#   2. the MIXED-MODEL throughput-vs-p99 curve: one fleet process
#      co-residing minet_r50_dp + u2net_ds on one chip, swept
#      closed-loop at rising concurrency with weighted mixed traffic
#      (loadgen --mix splits the curve per served model) — the measured
#      cost of sharing a device between two compiled-program families
#      vs the r7/r8 single-model curves
#   3. single-model-through-router legs at the same concurrency grid:
#      the ROUTER TAX in isolation (same model, same device, one front
#      door more) — if this exceeds a few ms at the knee, the router
#      needs a leaner in-process path before it fronts production
#   4. fairness + tenancy under pressure: open-loop one-hot overload on
#      minet with a trickle of u2net requests riding along, per-tenant
#      budgets armed — the per-model breakdown tells whether the cold
#      model's p99 survives the hot model's backlog (the interleaved
#      dispatcher's whole job), and /stats records the tenant sheds
#
# Predictions on record (docs/SERVING.md "Fleet"): (a) the router tax
# is < 5 ms p50 at c=1 and vanishes into batching at c>=8 (stdlib
# handler + one dict lookup + token-bucket read); (b) co-resident
# mixed 2:1 traffic lands each model within 25% of its solo r8
# throughput at matched per-model offered load (one device, two
# program families — the loop interleaves, the MXU does not multiply);
# (c) under one-hot minet overload the u2net trickle's p99 stays
# within 2x its unloaded p99 (round-robin guarantees its slot every
# cycle); if it does NOT, the dispatcher needs per-model inflight
# reservations, and that becomes the r10 lever.
#
# Serve legs talk to ONE fleet process started here (ephemeral port,
# --port-file); loadgen itself never imports jax, so only the fleet
# occupies the TPU.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results9}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

# Circuit breaker (r4 pattern): after any failed leg, verify the
# tunnel still runs REAL compute; abort the firing if not (the
# watcher re-fires in the next window and done_ok() skips landed legs).
tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r8 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2-4. the fleet: minet_r50_dp + u2net_ds co-resident on one chip
#         behind one router, gold/free tenants armed.
FLEET_CFG="$R/fleet.json"
cat > "$FLEET_CFG" <<'JSON'
{
  "default_tenant": "free",
  "tenants": [
    {"name": "gold", "priority": 1},
    {"name": "free", "priority": 0, "rate_rps": 200, "burst": 400}
  ],
  "models": [
    {"name": "minet", "config": "minet_r50_dp",
     "overrides": ["serve.batch_buckets=1,4,8,16"]},
    {"name": "u2net", "config": "u2net_ds",
     "overrides": ["serve.batch_buckets=1,4,8,16"]}
  ]
}
JSON
FLEET_PORT_FILE="$R/fleet.port"
rm -f "$FLEET_PORT_FILE"
python tools/serve.py --fleet-config "$FLEET_CFG" --device tpu \
  --port 0 --port-file "$FLEET_PORT_FILE" \
  > "$R"/fleet_server.out 2> "$R"/fleet_server.err &
FLEET_PID=$!
for _ in $(seq 1 180); do [ -f "$FLEET_PORT_FILE" ] && break; sleep 2; done
if [ -f "$FLEET_PORT_FILE" ]; then
  URL="http://127.0.0.1:$(cat "$FLEET_PORT_FILE")"
  LG="python tools/loadgen.py --url $URL --wait-ready 900 --size 320"
  # 2. mixed-model closed-loop sweep: THE fleet curve (2:1 minet:u2net,
  #    gold:free), per-model p50/p95/p99 split in every summary line.
  for c in 1 8 32; do
    run "fleet_mixed_c$c" 900 $LG --mode closed --concurrency "$c" \
        --requests 200 --mix minet:gold=2 --mix u2net:free=1
  done
  # 3. router tax: single-model legs THROUGH the router at the same
  #    grid — compare against the r8 serve_closed_f32_c* legs (same
  #    model family, no router) to price the extra tier.
  for c in 1 8 32; do
    run "fleet_minet_only_c$c" 900 $LG --mode closed --concurrency "$c" \
        --requests 200 --model minet --tenant gold
  done
  # 4. fairness under one-hot overload + tenant budgets: open-loop
  #    minet flood with a u2net trickle riding the SAME router; the
  #    summary's per-model breakdown shows whether u2net's p99
  #    survives, and --server-stats records tenant sheds + the fleet
  #    accounting block.
  for rps in 60 120; do
    run "fleet_onehot_rps$rps" 900 $LG --mode open --rps "$rps" \
        --duration 20 --slo-ms 500 --server-stats \
        --mix minet:free=19 --mix u2net:gold=1
  done
  kill -TERM "$FLEET_PID" 2>/dev/null
  wait "$FLEET_PID"
  echo "{\"step\": \"fleet_server_drain\", \"rc\": $?, \"result\": null}" >> "$R"/results.jsonl
else
  echo "fleet server never bound a port — skipping fleet legs" | tee -a "$R"/agenda.log
  kill -9 "$FLEET_PID" 2>/dev/null
fi

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
