#!/bin/bash
# Round-6 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 6).  Round 6 landed device-side step chunking
# (train.steps_per_dispatch=k: k train steps folded into one lax.scan
# dispatch, one stacked H2D and one metrics readback per chunk —
# docs/PERFORMANCE.md "Device-side step chunking").  The questions this
# agenda answers:
#
#   1. canonical b128 headline refresh (comparison anchor; k=1 key is
#      untouched by the chunking knob, so this replays the r5 key)
#   2. chunking sweep at the flagship operating point — k in {2,4,8}
#      at b128.  Prediction: per-dispatch overhead on the axon
#      transport was measured in the tens of ms (dispatch-latency
#      dominates under ~16 imgs/chip; BASELINE.md round-1 notes), but
#      at b128 the step itself is ~155 ms, so the b128 win is bounded
#      at a few percent — the sweep prices the overhead exactly:
#      (1/img_s_k1 - 1/img_s_k) * b = ms/step saved.
#   3. chunking sweep at b16 — the dispatch-bound regime.  Here
#      per-step time is ~20 ms and the same absolute overhead is a
#      10-30% tax; if chunking does NOT move b16 markedly, loop
#      overhead was already hidden by async run-ahead and the lever's
#      value is the multi-host sync story, not raw throughput.
#   4. k=4 with remat at b64 — chunking composes with the memory lever
#      (stacked batches cost k x input HBM; remat frees activations).
#
# The A/B legs carry steps_per_dispatch as a --set-style override, so
# bench.py keys them apart from the canonical baselines automatically.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results6}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

# Circuit breaker (r4 pattern): after any failed leg, verify the
# tunnel still runs REAL compute; abort the firing if not (the
# watcher re-fires in the next window and done_ok() skips landed legs).
tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (k=1; replays the canonical key)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2. chunking sweep at the flagship point.  --steps counts
#       DISPATCHES, so scale it down to keep wall time ~constant
#       (20/k dispatches x k steps = 20 steps of device work).
run spd2_b128 900 $BENCH --config minet_r50_dp --steps 10 --steps-per-dispatch 2
run spd4_b128 900 $BENCH --config minet_r50_dp --steps 5  --steps-per-dispatch 4
run spd8_b128 900 $BENCH --config minet_r50_dp --steps 3  --steps-per-dispatch 8

# -- 3. the dispatch-bound regime: small per-chip batch, where the
#       per-dispatch tax is a double-digit percentage of the step.
run b16_k1  900 $BENCH --config minet_r50_dp --batch-per-chip 16 --steps 40
run b16_k4  900 $BENCH --config minet_r50_dp --batch-per-chip 16 --steps 10 --steps-per-dispatch 4
run b16_k8  900 $BENCH --config minet_r50_dp --batch-per-chip 16 --steps 5  --steps-per-dispatch 8

# -- 4. composition with remat at b64 (stacked inputs cost k x input
#       HBM; remat frees the activation side).
run b64r_k1 900 $BENCH --config minet_r50_dp --batch-per-chip 64 --set model.remat=true
run b64r_k4 900 $BENCH --config minet_r50_dp --batch-per-chip 64 --steps 5 \
    --steps-per-dispatch 4 --set model.remat=true

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
