#!/bin/bash
# Round-19 TPU measurement agenda — run the moment the tunnel lives
# (tools/tpu_watch.sh fires this automatically; default agenda since
# round 19).  Round 19 landed streaming-video SOD serving
# (serve/streams.py; docs/SERVING.md "Streaming"): X-Stream-ID opens a
# bounded TTL-evicted per-stream session carrying the previous frame's
# mask + phash, the router pins a stream to its home replica (failover
# re-homes counted), and a temporal-coherence fast path replays the
# previous mask without a forward when consecutive frames' phashes
# agree within a Hamming budget — booked as the sixth terminal class
# (served+shed+expired+errors+cache_hit+stream_reuse == submitted).
# Correctness, accounting, and the quality ledger are proven on CPU
# (tests/test_streams.py, tools/stream_smoke.py, tools/stream_gate.py);
# what only hardware can answer is the fast path's LEVERAGE against a
# real TPU forward and what session affinity costs the tail.
# Predictions on record:
#
#   1. canonical b128 headline refresh (comparison anchor)
#   2. REUSE LEVERAGE: 4 streams x 10 fps, jitter frames with a 10%
#      scene-cut rate, reuse_hamming=16.  Prediction: reuse-arm p50
#      < 25% of the forward p50 on the same leg (a reuse answer is
#      hash + session read + socket, no device round-trip — the CPU
#      smoke measured 3.6 ms vs 380 ms, under 1%; 25% is the
#      conservative TPU floor since the forward side SHRINKS on
#      hardware), at reuse rate >= 60% (jitter frames minus cuts);
#      fleet identity consistent (six terms) on every leg.
#   3. AFFINITY TAX: same offered load, sessions armed but reuse OFF
#      (every frame forwards, pinned to the home replica) vs the
#      INDEPENDENT open-loop baseline at the same 40 rps.  Prediction:
#      per-stream p99 <= 1.5x the independent-request p99 — pinning
#      concentrates a stream on one replica's queue, but at smoke
#      scale the batcher's affinity coalescing wins back what the
#      loss of cross-replica spread costs.
#
# Per the pre-committed rule the streaming default stays OFF
# regardless of the numbers here (temporal coherence is a property of
# the TRAFFIC, not the box); the predictions gate what reuse rate and
# Hamming budget PERFORMANCE.md recommends arming it at.
cd "$(dirname "$0")/.." || exit 1
R=${R:-tpu_results19}
mkdir -p "$R"
BENCH="python bench.py --device tpu --steps 20 --watchdog 840 --retry-budget 0 --init-retries 2"

done_ok() {
  [ -f "$R"/results.jsonl ] || return 1
  local rec
  rec=$(grep "\"step\": \"$1\", \"rc\": 0" "$R"/results.jsonl | tail -1)
  [ -n "$rec" ] || return 1
  ! printf '%s' "$rec" | grep -q '"error"'
}

tunnel_computes() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print('computes')" 2>/dev/null | grep -q computes
}

run() { # run NAME TIMEOUT CMD... — bounded leg + flushed JSON record
  local name=$1 tmo=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]: $*" | tee -a "$R"/agenda.log
  timeout "$tmo" "$@" > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc ${line:-no-json}" | tee -a "$R"/agenda.log
  if { [ "$rc" -ne 0 ] || printf '%s' "$line" | grep -Eq 'wedged|unavailable'; } \
      && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing (watcher will re-fire)" \
      | tee -a "$R"/agenda.log
    exit 2
  fi
}

# -- 1. canonical headline refresh (the r5-r18 key replays unchanged)
run headline_b128 900 $BENCH --config minet_r50_dp

# -- 2 + 3. streaming serve legs: one real-process TPU server per leg,
#    the fleet config differing ONLY in the stream knobs; loadgen is a
#    separate process (the r16 cache A/B's lesson: an in-process
#    client understates the door paths because forwards release the
#    GIL in XLA while session hits are pure Python).
stream_leg() { # stream_leg NAME FLEET_FRAG LOADGEN_ARGS...
  local name=$1 frag=$2; shift 2
  if done_ok "$name"; then
    echo "[$name] skip: succeeded in a previous window" | tee -a "$R"/agenda.log
    return 0
  fi
  echo "=== $name [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
  local fleet="$R/${name}_fleet.json" pfile="$R/${name}_port"
  rm -f "$pfile"
  cat > "$fleet" <<EOF
{"models": [{"name": "minet", "config": "minet_r50_dp",
             "overrides": ["serve.precision_arms=f32",
                           "serve.precision=f32"]}]${frag}}
EOF
  timeout 900 python tools/serve.py --fleet-config "$fleet" \
      --device tpu --port 0 --port-file "$pfile" \
      > "$R/${name}_serve.out" 2>&1 &
  local spid=$!
  for _i in $(seq 1 300); do [ -f "$pfile" ] && break; sleep 1; done
  if [ ! -f "$pfile" ]; then
    echo "{\"step\": \"$name\", \"rc\": 1, \"result\": {\"error\": \"server never bound\"}}" >> "$R"/results.jsonl
    kill -9 $spid 2>/dev/null; return
  fi
  local port; port=$(cat "$pfile")
  # warmup fills the JIT + program caches (one short stream train)
  timeout 300 python tools/loadgen.py --url "http://127.0.0.1:$port" \
      --streams 2 --fps 4 --duration 5 --size 320 --wait-ready 240 \
      > /dev/null 2>&1
  timeout 600 python tools/loadgen.py --url "http://127.0.0.1:$port" \
      --size 320 --server-stats "$@" \
      > "$R/$name.out" 2> "$R/$name.err"
  local rc=$?
  kill -TERM $spid 2>/dev/null; wait $spid 2>/dev/null
  local line
  line=$(grep -E '^\{' "$R/$name.out" | tail -1)
  echo "{\"step\": \"$name\", \"rc\": $rc, \"result\": ${line:-null}}" >> "$R"/results.jsonl
  echo "[$name] rc=$rc" | tee -a "$R"/agenda.log
  if [ "$rc" -ne 0 ] && ! tunnel_computes; then
    echo "[$name] tunnel no longer computes — aborting firing" | tee -a "$R"/agenda.log
    exit 2
  fi
}

# independent baseline: same 40 req/s offered, no sessions anywhere
stream_leg stream_indep "" \
    --mode open --rps 40 --duration 30
# affinity only: sessions pin frames to the home replica, every frame
# still forwards (reuse off) — the per-stream tail vs the baseline
stream_leg stream_affinity ", \"stream_sessions\": 16" \
    --streams 4 --fps 10 --duration 30
# the fast path: jitter frames with a 10% scene-cut rate at h=16
stream_leg stream_reuse ", \"stream_sessions\": 16, \"stream_reuse_hamming\": 16" \
    --streams 4 --fps 10 --duration 30 --perturb 0.1
# flicker damping priced on top (blend decodes+re-encodes every
# forward's mask on the response path)
stream_leg stream_blend ", \"stream_sessions\": 16, \"stream_reuse_hamming\": 16, \"stream_ema_blend\": 0.5" \
    --streams 4 --fps 10 --duration 30 --perturb 0.1

# Host-side window report (touches no TPU).
timeout 120 python tools/window_report.py "$R"/results.jsonl \
    > "$R"/window_report.md 2> "$R"/window_report.err || true
tail -20 "$R"/window_report.md | tee -a "$R"/agenda.log

echo "=== agenda done [$(date -u +%H:%M:%S)]" | tee -a "$R"/agenda.log
