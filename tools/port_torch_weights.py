#!/usr/bin/env python
"""Port torchvision ImageNet backbone weights → flax param trees.

SURVEY.md §7.3 hard part 1: this zero-egress environment cannot download
ImageNet checkpoints, so paper-level DUTS numbers need this script run
once wherever network (or a cached ``~/.cache/torch``) exists:

    python tools/port_torch_weights.py --arch vgg16 --out vgg16.npz
    python tools/port_torch_weights.py --arch resnet50 --state-dict r50.pth \
        --out resnet50.npz
    python train.py --config minet_r50_dp --set model.pretrained=resnet50.npz

The mapping is structural, not name-matched: both torchvision and our
backbones enumerate convs/BNs in execution order, so the port walks the
two sequences in lockstep.  Layout transforms:

- conv kernels: torch OIHW → flax HWIO (transpose 2,3,1,0)
- linear: torch [out,in] → flax [in,out] (unused by the pyramids, kept
  for completeness)
- BN: weight/bias/running_mean/running_var → scale/bias/mean/var

Verified by tests/test_weight_port.py: random torch weights pushed
through torchvision's forward and ours agree to float tolerance.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _t2n(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy(), np.float32)


def _conv_kernel(t) -> np.ndarray:
    return _t2n(t).transpose(2, 3, 1, 0)  # OIHW → HWIO


def _ordered_convs_and_bns(state_dict) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Group a torchvision state_dict into execution-ordered conv/bn units.

    Works for vgg16/vgg16_bn/resnet* because their state_dicts enumerate
    modules in definition order == execution order.
    """
    units: List[Tuple[str, Dict[str, np.ndarray]]] = []
    by_prefix: Dict[str, Dict[str, np.ndarray]] = {}
    order: List[str] = []
    for key, val in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        prefix, leaf = key.rsplit(".", 1)
        if prefix not in by_prefix:
            by_prefix[prefix] = {}
            order.append(prefix)
        by_prefix[prefix][leaf] = val
    for prefix in order:
        leaves = by_prefix[prefix]
        if "running_mean" in leaves:
            units.append(("bn", {
                "scale": _t2n(leaves["weight"]),
                "bias": _t2n(leaves["bias"]),
                "mean": _t2n(leaves["running_mean"]),
                "var": _t2n(leaves["running_var"]),
            }))
        elif "weight" in leaves and leaves["weight"].dim() == 4:
            unit = {"kernel": _conv_kernel(leaves["weight"])}
            if "bias" in leaves:
                unit["bias"] = _t2n(leaves["bias"])
            units.append(("conv", unit))
        # linear heads (classifier) are dropped: pyramids don't use them.
    return units


def port_vgg16(state_dict, use_bn: bool):
    """→ (params, batch_stats) trees matching backbones/vgg.py VGG16."""
    units = _ordered_convs_and_bns(state_dict)
    convs = [u for k, u in units if k == "conv"]
    bns = [u for k, u in units if k == "bn"]
    n_convs = 13
    assert len(convs) == n_convs, f"vgg16 expects 13 convs, got {len(convs)}"
    if use_bn:
        assert len(bns) == n_convs, "vgg16_bn expects a BN per conv"
    params: Dict = {}
    stats: Dict = {}
    for i in range(n_convs):
        scope = f"ConvBNAct_{i}"
        conv = {"kernel": convs[i]["kernel"]}
        if not use_bn:
            conv["bias"] = convs[i]["bias"]
            params[scope] = {"Conv_0": conv}
        else:
            params[scope] = {
                "Conv_0": conv,
                "BatchNorm_0": {"scale": bns[i]["scale"],
                                "bias": bns[i]["bias"]},
            }
            stats[scope] = {"BatchNorm_0": {"mean": bns[i]["mean"],
                                            "var": bns[i]["var"]}}
    return params, stats


def _port_cba(state_dict, prefix: str):
    """One torch ``conv``(+``bn``) unit → the flax ConvBNAct subtree."""
    p: Dict = {"Conv_0": {"kernel": _conv_kernel(
        state_dict[prefix + ".conv.weight"])}}
    if prefix + ".conv.bias" in state_dict:
        p["Conv_0"]["bias"] = _t2n(state_dict[prefix + ".conv.bias"])
    s: Dict = {}
    if prefix + ".bn.weight" in state_dict:
        p["BatchNorm_0"] = {
            "scale": _t2n(state_dict[prefix + ".bn.weight"]),
            "bias": _t2n(state_dict[prefix + ".bn.bias"]),
        }
        s["BatchNorm_0"] = {
            "mean": _t2n(state_dict[prefix + ".bn.running_mean"]),
            "var": _t2n(state_dict[prefix + ".bn.running_var"]),
        }
    return p, s


def _put(params: Dict, stats: Dict, flax_scope: str, ported) -> None:
    """Stash a ported ``(params, stats)`` subtree, skipping empty
    stats — the shared idiom of every full-model port below."""
    p, s = ported
    params[flax_scope] = p
    if s:
        stats[flax_scope] = s


def _walk_cbas(state_dict, torch_scope: str):
    """All ``{torch_scope}.cbas.{j}`` units → (params, stats) subtrees
    keyed ``ConvBNAct_{j}`` (the shared torch-replica convention for
    full-model ports)."""
    scope_p: Dict = {}
    scope_s: Dict = {}
    j = 0
    while f"{torch_scope}.cbas.{j}.conv.weight" in state_dict:
        p, s = _port_cba(state_dict, f"{torch_scope}.cbas.{j}")
        scope_p[f"ConvBNAct_{j}"] = p
        if s:
            scope_s[f"ConvBNAct_{j}"] = s
        j += 1
    if not j:
        raise ValueError(f"no ConvBNAct units under {torch_scope!r}")
    return scope_p, scope_s


def port_u2net(state_dict):
    """FULL-model port: a torch U²-Net state_dict → (params,
    batch_stats) for models/u2net.py::U2Net.

    Expected torch layout (mirrored by the oracle replica in
    tests/test_weight_port.py): ``enc_rsus.{0..3}``, ``enc5``, ``en6``,
    ``dec5``, ``dec_rsus.{0..3}`` each holding ``cbas.{j}`` units in
    flax creation order, plus ``side.{0..5}`` and ``fuse`` head convs —
    protecting the nested-U deep-supervision composition ([B:10])
    at the 7-logit level.
    """
    params: Dict = {}
    stats: Dict = {}
    for i in range(4):
        _put(params, stats, f"RSU_{i}",
             _walk_cbas(state_dict, f"enc_rsus.{i}"))
    _put(params, stats, "RSU4F_0", _walk_cbas(state_dict, "enc5"))
    _put(params, stats, "RSU4F_1", _walk_cbas(state_dict, "en6"))
    _put(params, stats, "RSU4F_2", _walk_cbas(state_dict, "dec5"))
    for i in range(4):
        _put(params, stats, f"RSU_{i + 4}",
             _walk_cbas(state_dict, f"dec_rsus.{i}"))
    for j in range(6):
        params[f"Conv_{j}"] = {
            "kernel": _conv_kernel(state_dict[f"side.{j}.weight"]),
            "bias": _t2n(state_dict[f"side.{j}.bias"]),
        }
    params["Conv_6"] = {
        "kernel": _conv_kernel(state_dict["fuse.weight"]),
        "bias": _t2n(state_dict["fuse.bias"]),
    }
    return params, stats


def port_basnet(state_dict):
    """FULL-model port: a torch BASNet state_dict → (params,
    batch_stats) for models/basnet.py::BASNet.

    Expected torch layout (mirrored by the oracle replica in
    tests/test_weight_port.py): ``stem``, ``blocks.{0..21}`` (BasicBlock
    as cbas units incl. the optional 1×1 downsample), ``bridge.{0..2}``,
    ``dec.{0..5}.cbas.{0..2}``, ``side.{0..6}``, and ``refine`` (cbas +
    ``conv``) — protecting the predict+refine composition at the
    8-logit level ([B:10]).
    """
    params: Dict = {}
    stats: Dict = {}
    _put(params, stats, "ConvBNAct_0", _port_cba(state_dict, "stem"))
    for i in range(22):
        _put(params, stats, f"BasicBlock_{i}",
             _walk_cbas(state_dict, f"blocks.{i}"))
    for i in range(3):
        _put(params, stats, f"ConvBNAct_{i + 1}",
             _port_cba(state_dict, f"bridge.{i}"))
    for i in range(6):
        _put(params, stats, f"_DecoderStage_{i}",
             _walk_cbas(state_dict, f"dec.{i}"))
    for j in range(7):
        params[f"Conv_{j}"] = {
            "kernel": _conv_kernel(state_dict[f"side.{j}.weight"]),
            "bias": _t2n(state_dict[f"side.{j}.bias"]),
        }
    rp, rs = _walk_cbas(state_dict, "refine")
    rp["Conv_0"] = {
        "kernel": _conv_kernel(state_dict["refine.conv.weight"]),
        "bias": _t2n(state_dict["refine.conv.bias"]),
    }
    params["RefineModule_0"] = rp
    if rs:
        stats["RefineModule_0"] = rs
    return params, stats


def port_minet_vgg16(state_dict, use_bn: bool = True):
    """FULL-model port: a torch MINet-VGG16 state_dict → (params,
    batch_stats) for models/minet.py::MINet(backbone='vgg16').

    Expected torch layout (the canonical composition, mirrored by the
    oracle replica in tests/test_weight_port.py): ``backbone.*`` is a
    torchvision-style VGG16 features Sequential, decoder modules are
    ``aims.{0..4}.cbas.{j}``, ``sims.{0..4}.cbas.{0..6}``, and the head
    is ``head_cba`` + ``head_conv``, each ``cba`` a ``.conv``/``.bn``
    pair.  Module-level ports (port_vgg16 etc.) protect the backbone
    math; this protects the logit-level composition — feature indexing,
    AIM/SIM wiring, head — which is what the paper-level max-Fβ numbers
    actually flow through (SURVEY.md §7.3 hard part 1).
    """
    bb = {k[len("backbone."):]: v for k, v in state_dict.items()
          if k.startswith("backbone.")}
    vgg_p, vgg_s = port_vgg16(bb, use_bn=use_bn)
    params: Dict = {"VGG16_0": vgg_p}
    stats: Dict = {"VGG16_0": vgg_s} if vgg_s else {}

    for i in range(5):
        _put(params, stats, f"AIM_{i}",
             _walk_cbas(state_dict, f"aims.{i}"))
    for i in range(5):
        _put(params, stats, f"SIM_{i}",
             _walk_cbas(state_dict, f"sims.{i}"))
    _put(params, stats, "ConvBNAct_0", _port_cba(state_dict, "head_cba"))
    params["Conv_0"] = {
        "kernel": _conv_kernel(state_dict["head_conv.weight"]),
        "bias": _t2n(state_dict["head_conv.bias"]),
    }
    return params, stats


def port_hdfnet_vgg16(state_dict, use_bn: bool = True):
    """FULL-model port: a torch HDFNet-VGG16 state_dict → (params,
    batch_stats) for models/hdfnet.py::HDFNet(backbone='vgg16').

    Expected torch layout (mirrored by the oracle replica in
    tests/test_weight_port.py): ``backbone_rgb.*`` / ``backbone_depth.*``
    torchvision-style VGG16 features, ``guides.{0..2}``,
    ``ddpms.{i}.cba_in|cba_out|kgus.{j}.(cba|conv)``,
    ``dec_cbas.{0..5}``, ``heads.{0..2}`` — protecting the RGB-D
    composition ([B:9]): two-stream wiring, dynamic-filter kernel
    generation, decoder and deep-supervision heads.
    """
    def bb(prefix):
        sub = {k[len(prefix):]: v for k, v in state_dict.items()
               if k.startswith(prefix)}
        return port_vgg16(sub, use_bn=use_bn)

    rgb_p, rgb_s = bb("backbone_rgb.")
    dep_p, dep_s = bb("backbone_depth.")
    params: Dict = {"vgg_rgb": rgb_p, "vgg_depth": dep_p}
    stats: Dict = {}
    if rgb_s:
        stats["vgg_rgb"] = rgb_s
        stats["vgg_depth"] = dep_s

    for i in range(3):
        _put(params, stats, f"ConvBNAct_{i}",
             _port_cba(state_dict, f"guides.{i}"))
    for i in range(3):
        scope_p: Dict = {}
        scope_s: Dict = {}
        for flax_name, torch_prefix in (("ConvBNAct_0", f"ddpms.{i}.cba_in"),
                                        ("ConvBNAct_1", f"ddpms.{i}.cba_out")):
            _put(scope_p, scope_s, flax_name,
                 _port_cba(state_dict, torch_prefix))
        for j in range(3):
            p, s = _port_cba(state_dict, f"ddpms.{i}.kgus.{j}.cba")
            kgu: Dict = {"ConvBNAct_0": p, "Conv_0": {
                "kernel": _conv_kernel(
                    state_dict[f"ddpms.{i}.kgus.{j}.conv.weight"]),
                "bias": _t2n(state_dict[f"ddpms.{i}.kgus.{j}.conv.bias"]),
            }}
            scope_p[f"KernelGenUnit_{j}"] = kgu
            if s:
                scope_s[f"KernelGenUnit_{j}"] = {"ConvBNAct_0": s}
        params[f"DDPM_{i}"] = scope_p
        if scope_s:
            stats[f"DDPM_{i}"] = scope_s
    for j in range(6):
        _put(params, stats, f"ConvBNAct_{j + 3}",
             _port_cba(state_dict, f"dec_cbas.{j}"))
    for j in range(3):
        params[f"Conv_{j}"] = {
            "kernel": _conv_kernel(state_dict[f"heads.{j}.weight"]),
            "bias": _t2n(state_dict[f"heads.{j}.bias"]),
        }
    return params, stats


def port_gatenet_vgg16(state_dict, use_bn: bool = True):
    """FULL-model port: a torch GateNet-VGG16 state_dict → (params,
    batch_stats) for models/gatenet.py::GateNet(backbone='vgg16').

    Expected torch layout (mirrored by the oracle replica in
    tests/test_weight_port.py): ``backbone.*`` torchvision-style VGG16
    features, ``transfers.{0..4}``, bridge ``bridge.branches.{0..3}`` /
    ``bridge.gconv`` / ``bridge.fuse``, ``gates.{0..3}`` (creation
    order matches the decoder loop: gates.0 pairs with level 3),
    ``decs.{0..3}``, side heads ``sides.{0..4}`` (coarse → fine) —
    protecting the gated-skip composition: transfer indexing, gate
    wiring against the upsampled decoder state, bridge branches, and
    the reversed (finest-first) logit ordering.
    """
    bb = {k[len("backbone."):]: v for k, v in state_dict.items()
          if k.startswith("backbone.")}
    vgg_p, vgg_s = port_vgg16(bb, use_bn=use_bn)
    params: Dict = {"VGG16_0": vgg_p}
    stats: Dict = {"VGG16_0": vgg_s} if vgg_s else {}

    for i in range(5):  # transfers → ConvBNAct_0..4
        _put(params, stats, f"ConvBNAct_{i}",
             _port_cba(state_dict, f"transfers.{i}"))
    bridge_p: Dict = {}
    bridge_s: Dict = {}
    for j in range(4):
        _put(bridge_p, bridge_s, f"ConvBNAct_{j}",
             _port_cba(state_dict, f"bridge.branches.{j}"))
    _put(bridge_p, bridge_s, "ConvBNAct_4",
         _port_cba(state_dict, "bridge.gconv"))
    _put(bridge_p, bridge_s, "ConvBNAct_5",
         _port_cba(state_dict, "bridge.fuse"))
    params["DilatedPyramidBridge_0"] = bridge_p
    if bridge_s:
        stats["DilatedPyramidBridge_0"] = bridge_s
    for i in range(4):
        gate_p: Dict = {}
        gate_s: Dict = {}
        _put(gate_p, gate_s, "ConvBNAct_0",
             _port_cba(state_dict, f"gates.{i}"))
        params[f"GateUnit_{i}"] = gate_p
        if gate_s:
            stats[f"GateUnit_{i}"] = gate_s
        _put(params, stats, f"ConvBNAct_{i + 5}",
             _port_cba(state_dict, f"decs.{i}"))
    for j in range(5):  # side heads, coarse → fine = Conv_0..4
        params[f"Conv_{j}"] = {
            "kernel": _conv_kernel(state_dict[f"sides.{j}.weight"]),
            "bias": _t2n(state_dict[f"sides.{j}.bias"]),
        }
    return params, stats


def _resnet_block_unit_counts(arch: str) -> Tuple[List[int], int]:
    if arch in ("resnet34",):
        return [3, 4, 6, 3], 2  # convs per BasicBlock
    if arch in ("resnet50",):
        return [3, 4, 6, 3], 3  # convs per Bottleneck
    raise ValueError(f"unsupported arch {arch!r}")


def port_resnet(state_dict, arch: str):
    """→ (params, batch_stats) matching backbones/resnet.py ResNet.

    Our blocks are ConvBNAct chains with the projection shortcut LAST
    within each block's parameter list (it is created inside the
    ``if residual...`` after the main path), whereas torchvision puts
    ``downsample`` after the block's convs too — same relative order, so
    the lockstep walk holds.
    """
    import torch  # local import: tool usable only where torch exists

    stage_sizes, convs_per_block = _resnet_block_unit_counts(arch)
    units = _ordered_convs_and_bns(state_dict)
    # Pair every conv with its following bn (resnet always interleaves).
    pairs = []
    i = 0
    while i < len(units):
        kind, u = units[i]
        if kind == "conv":
            assert i + 1 < len(units) and units[i + 1][0] == "bn", \
                "resnet conv without bn"
            pairs.append((u, units[i + 1][1]))
            i += 2
        else:
            i += 1

    params: Dict = {}
    stats: Dict = {}

    def put(scope: str, conv, bn):
        params[scope] = {
            "Conv_0": {"kernel": conv["kernel"]},
            "BatchNorm_0": {"scale": bn["scale"], "bias": bn["bias"]},
        }
        stats[scope] = {"BatchNorm_0": {"mean": bn["mean"], "var": bn["var"]}}

    pi = 0
    put("ConvBNAct_0", *pairs[pi]); pi += 1  # stem
    block_cls = "BasicBlock" if convs_per_block == 2 else "Bottleneck"
    bi = 0
    for stage, n_blocks in enumerate(stage_sizes):
        for b in range(n_blocks):
            scope = f"{block_cls}_{bi}"; bi += 1
            blk_params: Dict = {}
            blk_stats: Dict = {}

            def bput(sub, conv, bn):
                blk_params[sub] = {
                    "Conv_0": {"kernel": conv["kernel"]},
                    "BatchNorm_0": {"scale": bn["scale"], "bias": bn["bias"]},
                }
                blk_stats[sub] = {"BatchNorm_0": {"mean": bn["mean"],
                                                  "var": bn["var"]}}

            for c in range(convs_per_block):
                bput(f"ConvBNAct_{c}", *pairs[pi]); pi += 1
            # torchvision: downsample conv+bn follow the block's convs
            # exactly when the block projects (first block of a stage
            # with stride/width change) — mirrored by our trailing
            # projection ConvBNAct.
            has_proj = (b == 0 and (stage > 0 or convs_per_block == 3))
            if has_proj:
                bput(f"ConvBNAct_{convs_per_block}", *pairs[pi]); pi += 1
            params[scope] = blk_params
            stats[scope] = blk_stats
    assert pi == len(pairs), f"consumed {pi} of {len(pairs)} conv/bn pairs"
    return params, stats


def _linear_kernel(t) -> np.ndarray:
    return _t2n(t).T  # torch [out,in] → flax [in,out]


def _ln(state_dict, prefix: str) -> Dict[str, np.ndarray]:
    return {"scale": _t2n(state_dict[prefix + ".weight"]),
            "bias": _t2n(state_dict[prefix + ".bias"])}


def _qkv_to_head_major(kernel: np.ndarray, bias: np.ndarray,
                       heads: int) -> Tuple[np.ndarray, np.ndarray]:
    """Permute a fused qkv projection's OUTPUT columns from the official
    (3, heads, hd) order to our WindowAttention's (heads, 3, hd) order
    (head-major columns keep a tensor-parallel column shard aligned to
    whole heads — parallel/tp.py)."""
    n_in, out = kernel.shape
    d = out // 3
    hd = d // heads
    k = kernel.reshape(n_in, 3, heads, hd).transpose(0, 2, 1, 3)
    b = bias.reshape(3, heads, hd).transpose(1, 0, 2)
    return k.reshape(n_in, out), b.reshape(out)


def port_swin_t(state_dict,
                depths=(2, 2, 6, 2),
                heads=(3, 6, 12, 24)) -> Tuple[Dict, Dict]:
    """Official Swin-Transformer checkpoint → our backbones/swin.py tree.

    Key schema is the microsoft/Swin-Transformer repo's (also used by
    its segmentation/detection forks): ``patch_embed.proj``,
    ``layers.{s}.blocks.{b}.{norm1,attn.qkv,attn.proj,norm2,mlp.fc1,
    mlp.fc2}``, ``layers.{s}.downsample.{norm,reduction}``.  Layout
    notes (verified numerically in tests/test_weight_port.py):

    - qkv packing: torch reshapes [.,3C]→(3,heads,hd); our
      WindowAttention packs HEAD-major (heads,3,hd) for tensor-parallel
      alignment, so the kernel ports as a transpose plus a fixed column
      permutation (_qkv_to_head_major);
    - the relative-position bias table is [(2w-1)², heads] under the
      identical index formula — copied as-is;
    - official attaches ``downsample`` at the END of stage s; our merge
      (LayerNorm + Dense) opens stage s+1 — same weights, same dataflow;
    - classification ckpts carry one final ``norm`` (→ our last
      stage-out LayerNorm); dense-prediction ckpts carry ``norm{0..3}``
      (→ every stage-out LayerNorm); absent ones keep fresh init.
    """
    params: Dict = {
        "Conv_0": {
            "kernel": _conv_kernel(state_dict["patch_embed.proj.weight"]),
            "bias": _t2n(state_dict["patch_embed.proj.bias"]),
        },
        "LayerNorm_0": _ln(state_dict, "patch_embed.norm"),
    }
    block_idx = 0
    for s, depth in enumerate(depths):
        if s:  # merge that opens stage s == official downsample of s-1
            params[f"LayerNorm_{2 * s}"] = _ln(
                state_dict, f"layers.{s - 1}.downsample.norm")
            params[f"Dense_{s - 1}"] = {"kernel": _linear_kernel(
                state_dict[f"layers.{s - 1}.downsample.reduction.weight"])}
        for b in range(depth):
            pre = f"layers.{s}.blocks.{b}"
            qkv_w, qkv_b = _qkv_to_head_major(
                _linear_kernel(state_dict[pre + ".attn.qkv.weight"]),
                _t2n(state_dict[pre + ".attn.qkv.bias"]),
                heads[s])
            params[f"SwinBlock_{block_idx}"] = {
                "LayerNorm_0": _ln(state_dict, pre + ".norm1"),
                "WindowAttention_0": {
                    "Dense_0": {
                        "kernel": qkv_w,
                        "bias": qkv_b,
                    },
                    "rel_pos_bias": _t2n(
                        state_dict[pre + ".attn.relative_position_bias_table"]),
                    "Dense_1": {
                        "kernel": _linear_kernel(
                            state_dict[pre + ".attn.proj.weight"]),
                        "bias": _t2n(state_dict[pre + ".attn.proj.bias"]),
                    },
                },
                "LayerNorm_1": _ln(state_dict, pre + ".norm2"),
                "Dense_0": {
                    "kernel": _linear_kernel(
                        state_dict[pre + ".mlp.fc1.weight"]),
                    "bias": _t2n(state_dict[pre + ".mlp.fc1.bias"]),
                },
                "Dense_1": {
                    "kernel": _linear_kernel(
                        state_dict[pre + ".mlp.fc2.weight"]),
                    "bias": _t2n(state_dict[pre + ".mlp.fc2.bias"]),
                },
            }
            block_idx += 1
        # Stage-out LayerNorm: dense-prediction ckpts name them norm{s};
        # classification ckpts only have the final `norm`.
        out_ln = f"LayerNorm_{2 * s + 1}"
        if f"norm{s}.weight" in state_dict:
            params[out_ln] = _ln(state_dict, f"norm{s}")
        elif s == len(depths) - 1 and "norm.weight" in state_dict:
            params[out_ln] = _ln(state_dict, "norm")
    return params, {}


def _port_pos_embed(pe: np.ndarray, grid: Tuple[int, int]) -> np.ndarray:
    """[1, (cls…)+N, D] → [grid_h*grid_w, D]: drop class/dist tokens,
    bicubic-resize the source grid to the target (the standard
    fine-tune-at-new-resolution practice for ViT)."""
    import torch
    import torch.nn.functional as F

    pe = np.asarray(pe)[0]
    n = pe.shape[0]
    for lead in (0, 1, 2):  # none / cls / cls+dist leading tokens
        side = int(round((n - lead) ** 0.5))
        if side * side == n - lead:
            pe = pe[lead:]
            break
    else:
        raise ValueError(f"cannot infer a square grid from pos_embed "
                         f"with {n} positions")
    g = torch.from_numpy(
        np.ascontiguousarray(pe.reshape(side, side, -1).transpose(2, 0, 1))
    )[None].float()
    g = F.interpolate(g, size=tuple(grid), mode="bicubic",
                      align_corners=False)
    return np.asarray(g[0].permute(1, 2, 0).reshape(
        grid[0] * grid[1], -1), np.float32)


def port_vit(state_dict, grid: Tuple[int, int] = (20, 20)
             ) -> Tuple[Dict, Dict]:
    """timm/DeiT ViT checkpoint (``vit_*_patch16_*``) →
    models/vit_sod.py tree.

    Schema: ``patch_embed.proj``, ``pos_embed`` (cls token dropped,
    grid bicubic-resized to ``grid`` — pass the TARGET grid, e.g.
    20,20 for 320px/patch16), ``blocks.{i}.{norm1,attn.qkv,attn.proj,
    norm2,mlp.fc1,mlp.fc2}``, final ``norm`` → our ``head_norm``.  The
    fused qkv rows split into our separate q/k/v projections (timm
    packs rows [0:D]=q, [D:2D]=k, [2D:3D]=v).  The classifier head and
    our SOD heads stay fresh.
    """
    d = int(state_dict["patch_embed.proj.weight"].shape[0])
    params: Dict = {
        "patch_embed": {
            "kernel": _conv_kernel(state_dict["patch_embed.proj.weight"]),
            "bias": _t2n(state_dict["patch_embed.proj.bias"]),
        },
        "pos_embed": _port_pos_embed(_t2n(state_dict["pos_embed"]), grid),
    }
    i = 0
    while f"blocks.{i}.norm1.weight" in state_dict:
        pre = f"blocks.{i}"
        qkv_w = _t2n(state_dict[pre + ".attn.qkv.weight"])  # [3D, D]
        qkv_b = _t2n(state_dict[pre + ".attn.qkv.bias"])
        params[f"block{i}"] = {
            "LayerNorm_0": _ln(state_dict, pre + ".norm1"),
            "q": {"kernel": qkv_w[0:d].T, "bias": qkv_b[0:d]},
            "k": {"kernel": qkv_w[d:2 * d].T, "bias": qkv_b[d:2 * d]},
            "v": {"kernel": qkv_w[2 * d:].T, "bias": qkv_b[2 * d:]},
            "proj": {
                "kernel": _linear_kernel(state_dict[pre + ".attn.proj.weight"]),
                "bias": _t2n(state_dict[pre + ".attn.proj.bias"]),
            },
            "LayerNorm_1": _ln(state_dict, pre + ".norm2"),
            "mlp_up": {
                "kernel": _linear_kernel(state_dict[pre + ".mlp.fc1.weight"]),
                "bias": _t2n(state_dict[pre + ".mlp.fc1.bias"]),
            },
            "mlp_down": {
                "kernel": _linear_kernel(state_dict[pre + ".mlp.fc2.weight"]),
                "bias": _t2n(state_dict[pre + ".mlp.fc2.bias"]),
            },
        }
        i += 1
    if i == 0:
        # Without this, a schema-mismatched checkpoint would port only
        # patch_embed/pos_embed — which the subset-matching loader
        # happily grafts, leaving every encoder block at random init.
        raise ValueError(
            "no 'blocks.{i}.*' keys found — not a timm/DeiT ViT "
            "state dict?")
    if "norm.weight" in state_dict:
        params["head_norm"] = _ln(state_dict, "norm")
    return params, {}


# npz IO lives in the package (the training path loads these files);
# re-exported here for script users.
from distributed_sod_project_tpu.models.pretrained import (  # noqa: E402
    load_npz, save_npz)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--arch", required=True,
                   choices=["vgg16", "vgg16_bn", "resnet34", "resnet50",
                            "swin_t", "vit", "minet_vgg16", "hdfnet_vgg16",
                            "u2net", "basnet", "gatenet_vgg16"])
    p.add_argument("--out", required=True, help="output .npz path")
    p.add_argument("--state-dict", default=None,
                   help="local .pth state_dict (default: download via "
                        "torchvision, needs network)")
    p.add_argument("--grid", default="20,20",
                   help="vit only: target patch grid rows,cols — "
                        "image_size/16 (default 20,20 for 320px)")
    args = p.parse_args(argv)

    import torch

    if args.state_dict:
        sd = torch.load(args.state_dict, map_location="cpu")
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
    elif args.arch == "swin_t":
        raise SystemExit(
            "swin_t ports the official microsoft/Swin-Transformer "
            "checkpoint schema — pass it via --state-dict "
            "(torchvision's swin_t uses a different naming)")
    elif args.arch == "vit":
        raise SystemExit(
            "vit ports the timm/DeiT checkpoint schema "
            "(vit_*_patch16_*) — pass it via --state-dict")
    elif args.arch in ("minet_vgg16", "hdfnet_vgg16", "u2net", "basnet",
                       "gatenet_vgg16"):
        raise SystemExit(
            f"{args.arch} is a FULL-model port (the canonical torch "
            "composition documented on its port_* function) — pass the "
            "checkpoint via --state-dict")
    else:
        import torchvision.models as tvm

        model = getattr(tvm, args.arch)(weights="IMAGENET1K_V1")
        sd = model.state_dict()

    if "model" in sd and isinstance(sd["model"], dict):
        sd = sd["model"]  # official Swin repo wraps the state_dict
    if args.arch == "u2net":
        params, stats = port_u2net(sd)
    elif args.arch == "basnet":
        params, stats = port_basnet(sd)
    elif args.arch in ("minet_vgg16", "hdfnet_vgg16", "gatenet_vgg16"):
        # BN-ness is a property of the checkpoint, not a flag: detect it
        # from the backbone keys (plain-VGG16 compositions have no
        # running stats) so both variants port without guesswork.
        bb = "backbone_rgb." if args.arch == "hdfnet_vgg16" else "backbone."
        use_bn = any(k.startswith(bb) and k.endswith("running_mean")
                     for k in sd)
        port_fn = {"minet_vgg16": port_minet_vgg16,
                   "hdfnet_vgg16": port_hdfnet_vgg16,
                   "gatenet_vgg16": port_gatenet_vgg16}[args.arch]
        params, stats = port_fn(sd, use_bn=use_bn)
    elif args.arch.startswith("vgg16"):
        params, stats = port_vgg16(sd, use_bn=args.arch.endswith("_bn"))
    elif args.arch == "swin_t":
        params, stats = port_swin_t(sd)
    elif args.arch == "vit":
        grid = tuple(int(x) for x in args.grid.split(","))
        params, stats = port_vit(sd, grid=grid)
    else:
        params, stats = port_resnet(sd, args.arch)
    meta = {"qkv_layout": "head_major"} if args.arch == "swin_t" else None
    save_npz(args.out, params, stats, meta=meta)
    n = sum(v.size for v in np.load(args.out).values())
    print(f"wrote {args.out}: {n/1e6:.1f}M params")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
