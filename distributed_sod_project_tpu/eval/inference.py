"""Inference + metric sweep — the reference's ``test.py`` path
(SURVEY.md §2 C2, §3.2).

Reference behavior reproduced: resize → forward → sigmoid →
resize-back-to-original → save PNG → stream (pred, gt) into the metric
aggregator.  TPU-shaped differences (SURVEY.md §7.3 hard part 5):

- the compiled forward only ever sees the static ``cfg.data.image_size``
  shape; per-image original-size handling (resize-back, PNG write,
  metric update) is host-side numpy,
- images run in fixed-size batches (last batch zero-padded and the pad
  masked out) so there is exactly ONE compiled program, not one per
  image size,
- prediction batches come back as one device array per batch; the host
  thread overlaps PNG/metric work with the next device batch.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import SODMetrics
from ..utils.logging import get_logger


def _original_mask(dataset, index: int, sample=None) -> np.ndarray:
    """GT at original resolution when the dataset is file-backed;
    falls back to the already-fetched (resized) sample mask otherwise."""
    if hasattr(dataset, "mask_paths") and hasattr(dataset, "stems"):
        from PIL import Image

        with Image.open(dataset.mask_paths[dataset.stems[index]]) as im:
            return (np.asarray(im.convert("L"), np.float32) / 255.0 > 0.5
                    ).astype(np.float32)
    if sample is None:
        sample = dataset[index]
    return np.asarray(sample["mask"]).squeeze()


def _stem(dataset, index: int) -> str:
    if hasattr(dataset, "stems"):
        return dataset.stems[index]
    return f"{index:06d}"


def _resize_pred(pred: np.ndarray, hw) -> np.ndarray:
    from PIL import Image

    if pred.shape == tuple(hw):
        return pred
    im = Image.fromarray((np.clip(pred, 0, 1) * 255).astype(np.uint8))
    im = im.resize((hw[1], hw[0]), Image.BILINEAR)
    return np.asarray(im, np.float32) / 255.0


def _save_pngs(items) -> None:
    """One eval batch of saliency maps → PNGs: C++ threaded writer when
    the native lib is built (GIL-free, SURVEY.md §3.2's dump hot loop),
    else PIL."""
    from ..data import native

    if native.png_writer_available():
        native.write_png_batch(items)
        return
    from PIL import Image

    for path, arr in items:
        Image.fromarray(arr).save(path)


def make_forward(model):
    """The canonical eval forward: ``(variables, batch) -> probs``
    (sigmoid on the primary logit, f32, [B,H,W]).  jitted once with the
    variables as an ARGUMENT so repeated calls never retrace.  Single
    definition shared by evaluate(), the in-training eval, and
    tools/predict.py — the mesh-sharded variant lives in
    train/step.py::make_eval_step."""

    @jax.jit
    def forward(variables, batch):
        outs = model.apply(variables, batch["image"], batch.get("depth"),
                           train=False)
        return jax.nn.sigmoid(outs[0][..., 0].astype(jnp.float32))

    return forward


def pad_to_batch(batch: Dict[str, np.ndarray], batch_size: int
                 ) -> Dict[str, np.ndarray]:
    """Zero-pad every leaf's leading dim to ``batch_size`` so the
    compiled forward only ever sees ONE static shape; callers slice the
    pad back off the output."""
    short = batch_size - next(iter(batch.values())).shape[0]
    if short <= 0:
        return batch
    return {k: np.concatenate(
        [v, np.zeros((short,) + v.shape[1:], v.dtype)])
        for k, v in batch.items()}


def restore_for_eval(ckpt_dir: str, config_name: Optional[str] = None,
                     overrides=(), step: Optional[int] = None):
    """Checkpoint directory → ``(cfg, model, state)``, shared by the
    eval-side CLIs (test.py, tools/predict.py).

    Config comes from the registry when ``config_name`` is given, else
    from the checkpoint's own ``config.json`` sidecar (checkpoints are
    self-describing).  The restore template is built from a zeros batch
    of the config's static eval shape — only shapes matter to orbax,
    and it must mirror training-time state (EMA slots included).
    """
    import json as _json

    from ..ckpt import CheckpointManager
    from ..configs import apply_overrides, config_from_dict, get_config
    from ..models import build_model
    from ..train import build_optimizer, create_train_state
    from ..utils.platform import maybe_enable_compilation_cache

    # Before the first compile (create_train_state's model.init) so the
    # persistent cache covers it too.
    maybe_enable_compilation_cache()

    if config_name:
        cfg = get_config(config_name)
    else:
        sidecar = os.path.join(ckpt_dir, "config.json")
        if not os.path.exists(sidecar):
            raise SystemExit(
                f"no --config given and {sidecar} missing — pass the "
                "config name explicitly")
        with open(sidecar) as f:
            cfg = config_from_dict(_json.load(f))
    cfg = apply_overrides(cfg, list(overrides))

    model = build_model(cfg.model)
    tx, _ = build_optimizer(cfg.optim, 1)
    h, w = cfg.data.image_size
    probe = {"image": np.zeros((1, h, w, 3), np.float32)}
    if cfg.data.use_depth:
        probe["depth"] = np.zeros((1, h, w, 1), np.float32)
    template = create_train_state(jax.random.key(0), model, tx, probe,
                                  ema=cfg.optim.ema_decay > 0)
    mgr = CheckpointManager(ckpt_dir, async_save=False)
    state = mgr.restore(template, step=step)
    mgr.close()
    return cfg, model, state


def run_inference(
    forward,
    dataset,
    batch_size: int = 8,
    use_depth: bool = False,
    save_dir: Optional[str] = None,
    compute_metrics: bool = True,
    compute_structure: bool = True,
    device_metrics: bool = False,
    shard: Optional[tuple] = None,
    return_state: bool = False,
) -> Dict[str, float]:
    """Sweep ``dataset`` through a compiled ``forward(batch)->probs``.

    ``forward`` maps a dict with 'image' (and optionally 'depth') of the
    static eval shape to per-pixel probabilities [B,H,W].  Returns the
    SOD metric dict (empty when ``compute_metrics=False``).

    ``device_metrics=True`` accumulates the threshold-curve metrics
    (max/mean-Fβ, Em, MAE) INSIDE jit at the eval resolution — the
    prediction never reaches the host unless PNGs or the per-image
    structure measures need it, and the device pipelines batch k+1's
    forward under batch k's update.  The host convention (PySODMetrics)
    scores at each image's ORIGINAL resolution, so numbers differ
    slightly from the default path; use it where throughput matters and
    the ranking is what counts (inline train eval, benchmarking).

    Host post-processing (original-size resize, S/E-measure, PNG
    encode) runs on a worker thread so it overlaps the next batch's
    device work instead of serialising after it.

    ``shard=(shard_id, num_shards)`` sweeps only every num_shards-th
    image (the multi-host split: each host scores a disjoint slice
    instead of all hosts duplicating the full set).
    ``return_state=True`` (requires ``device_metrics``) returns the raw
    ``FBetaState`` instead of the result dict so the caller can psum
    shard states across hosts before finalising.
    """
    if return_state and not (compute_metrics and device_metrics
                             and not compute_structure):
        raise ValueError(
            "return_state needs device_metrics=True and "
            "compute_structure=False (host structure measures have "
            "nowhere to go when only the device state is returned)")
    log = get_logger()
    if save_dir:
        os.makedirs(save_dir, exist_ok=True)

    host_fbeta = compute_metrics and not device_metrics
    host_structure = compute_metrics and compute_structure
    agg = (SODMetrics(compute_structure=host_structure,
                      compute_fbeta=host_fbeta)
           if (host_fbeta or host_structure) else None)
    need_host = agg is not None or bool(save_dir)

    dev_state = dev_update = None
    if compute_metrics and device_metrics:
        from ..metrics.streaming import init_fbeta_state, update_fbeta_state

        dev_state = init_fbeta_state()
        dev_update = jax.jit(update_fbeta_state, donate_argnums=0)

    # Host worker: drains (device probs, indices, samples) and does the
    # original-resolution work.  maxsize bounds in-flight device
    # outputs; np.asarray inside the worker is the blocking fetch.
    import queue
    import threading

    errors: list = []
    work_q: queue.Queue = queue.Queue(maxsize=2)

    def _host_batch(probs_np, idxs, samples):
        pending = []
        for j, i in enumerate(idxs):
            gt = _original_mask(dataset, i, samples[j])
            pred = _resize_pred(probs_np[j], gt.shape[:2])
            if agg is not None:
                agg.add(pred, gt)
            if save_dir:
                pending.append((
                    os.path.join(save_dir, f"{_stem(dataset, i)}.png"),
                    (np.clip(pred, 0, 1) * 255).astype(np.uint8)))
        if pending:
            _save_pngs(pending)

    def _worker():
        while True:
            item = work_q.get()
            try:
                if item is None:
                    return
                probs_dev, idxs, samples = item
                _host_batch(np.asarray(probs_dev)[: len(idxs)], idxs,
                            samples)
            except Exception as e:  # noqa: BLE001 — re-raised on main
                errors.append(e)
            finally:
                work_q.task_done()

    worker = None
    if need_host:
        worker = threading.Thread(target=_worker, daemon=True)
        worker.start()

    all_idxs = (list(range(len(dataset))) if shard is None
                else list(range(shard[0], len(dataset), shard[1])))
    n = len(all_idxs)
    # With no host consumer AND no device metric carry, NOTHING in this
    # loop ever syncs: every forward is an async dispatch and a sweep
    # would queue the entire dataset onto the device (a warmup/
    # throughput pass with compute_metrics=False did exactly that).
    # Bound in-flight dispatches by blocking on a batch every few steps.
    free_running = not need_host and dev_update is None
    sync_every = 4
    probs = None
    try:
        for bi, lo in enumerate(range(0, n, batch_size)):
            if errors:
                break
            idxs = all_idxs[lo:lo + batch_size]
            pad = batch_size - len(idxs)
            samples = [dataset[i] for i in idxs]
            batch = {"image": np.stack([s["image"] for s in samples])}
            if use_depth:
                batch["depth"] = np.stack([s["depth"] for s in samples])
            if pad:
                batch = pad_to_batch(batch, batch_size)
            # The batch build above is the loop's slow host section
            # (dataset decode); a worker error that landed during it
            # used to surface only at the NEXT loop top — after this
            # batch was already dispatched and enqueued for a worker
            # that will never drain it.  Re-check at both seams: before
            # the dispatch, and right after the (possibly blocking)
            # enqueue below.
            if errors:
                break
            probs = forward(batch)  # async dispatch — no host sync here
            if dev_update is not None:
                gts = np.stack([s["mask"] for s in samples])
                if pad:
                    gts = np.concatenate(
                        [gts, np.zeros((pad,) + gts.shape[1:], gts.dtype)])
                valid = np.concatenate(
                    [np.ones((len(idxs),), np.float32),
                     np.zeros((pad,), np.float32)])
                dev_state = dev_update(dev_state, probs, gts, valid=valid)
            if need_host:
                work_q.put((probs, idxs, samples))
                if errors:  # the put may have blocked across a failure
                    break
            elif free_running and bi % sync_every == sync_every - 1:
                jax.block_until_ready(probs)
        if free_running and probs is not None:
            jax.block_until_ready(probs)
    finally:
        if worker is not None:
            work_q.put(None)
            worker.join()
    if errors:
        raise errors[0]

    if return_state:
        return jax.device_get(dev_state)

    out: Dict[str, float] = {}
    if dev_state is not None:
        from ..metrics.aggregator import results_from_state

        out.update(results_from_state(jax.device_get(dev_state)))
    if agg is not None:
        out.update(agg.results())
    if out:
        log.info("eval: %s", {k: round(v, 4) if isinstance(v, float) else v
                              for k, v in out.items()})
    return out


def flip_tta(forward):
    """Wrap an eval ``forward(batch)->probs`` with horizontal-flip
    test-time augmentation: average the prediction with the unflipped
    prediction of the mirrored input (the classic SOD eval trick;
    masks are flip-equivariant).  Costs 2x forward."""

    def wrapped(batch):
        probs = forward(batch)
        flipped = {k: (v[:, :, ::-1] if k in ("image", "depth") else v)
                   for k, v in batch.items()}
        return 0.5 * (probs + forward(flipped)[:, :, ::-1])

    return wrapped


def evaluate(
    cfg,
    state,
    model=None,
    mesh=None,
    datasets: Optional[Dict[str, object]] = None,
    save_root: Optional[str] = None,
    batch_size: Optional[int] = None,
    compute_structure: bool = True,
    tta: bool = False,
    device_metrics: bool = False,
) -> Dict[str, Dict[str, float]]:
    """Test-entrypoint engine: run every test set through the model.

    ``datasets`` maps name → dataset; defaults to the config's dataset.
    Pass ``mesh`` to shard the forward over its ``data`` axis (all local
    chips work on every batch — the pod/donut eval path); without it the
    jit runs on the default device.  ``tta`` averages in the
    horizontally-flipped prediction (2x forward cost).
    ``device_metrics`` accumulates Fβ/Em/MAE in the compiled step at
    eval resolution (see run_inference).
    """
    from ..data import resolve_dataset
    from ..models import build_model

    model = model or build_model(cfg.model)
    if datasets is None:
        # hflip is a train-loader op, not a dataset property — resolve as-is.
        datasets = {cfg.data.dataset: resolve_dataset(cfg.data)}
    # Cap 32, not the old 8: eval is forward-only (no grad/optimizer
    # memory), and measured v5e eval throughput rises steeply with
    # batch (248 -> 365 img/s from b32 to b64, BASELINE.md) — while
    # tiny validation sets still pad at most one batch.
    bs = batch_size or min(cfg.global_batch_size, 32)
    # Only the eval variables (params + BN stats) go to the devices —
    # NOT the optimizer/EMA buffers a restored TrainState carries
    # (3-4x the param bytes, replicated onto every chip for nothing).
    variables = (state.eval_variables() if hasattr(state, "eval_variables")
                 else state.variables())
    from ..parallel.sp import (make_sp_eval_forward, sp_eval_batch_size,
                               wants_sp_eval)

    if wants_sp_eval(model, mesh):
        # Row-sharded ring-attention forward (same helper as the inline
        # eval in train/loop.py): a full-attention eval would
        # materialise the NxN score matrix per chip — the memory
        # profile an SP-trained model exists to avoid at long-context
        # resolutions.
        bs = sp_eval_batch_size(mesh, bs)
        forward = make_sp_eval_forward(model, mesh,
                                       cfg.mesh.sp_strategy)(variables)
    else:
        if mesh is not None:
            from ..parallel.mesh import (eval_batch_divisor,
                                         eval_batch_sharding,
                                         replicated_sharding)

            div = eval_batch_divisor(mesh)  # batch over flat (data, seq)
            bs = max(1, bs // div) * div
            variables = jax.device_put(variables,
                                       replicated_sharding(mesh))

        _apply = make_forward(model)

        def forward(batch):
            if mesh is not None:
                batch = jax.device_put(batch, eval_batch_sharding(mesh))
            return _apply(variables, batch)

    if tta:
        forward = flip_tta(forward)

    results = {}
    for name, ds in datasets.items():
        results[name] = run_inference(
            forward, ds,
            batch_size=bs,
            use_depth=cfg.data.use_depth,
            save_dir=os.path.join(save_root, name) if save_root else None,
            compute_structure=compute_structure,
            device_metrics=device_metrics,
        )
    return results
