from .inference import evaluate, run_inference

__all__ = ["evaluate", "run_inference"]
