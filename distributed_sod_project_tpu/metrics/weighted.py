"""Weighted F-measure and adaptive Fβ (PySODMetrics parity, SURVEY.md §2 C10).

- ``adaptive_fbeta``: Fβ at the per-image adaptive threshold
  ``min(2·mean(pred), 1)`` — the classic "adp" column of SOD tables.
- ``weighted_fmeasure``: Margolin et al., CVPR 2014 ("How to Evaluate
  Foreground Maps?").  Errors are (1) smoothed by a Gaussian on their
  distance to the foreground — nearby mistakes count less — and (2)
  false positives are discounted by distance from the object.  Host-side
  numpy (per-image, eval path only) since it needs a distance transform.
"""

from __future__ import annotations

import numpy as np

BETA2 = 0.3


def adaptive_fbeta(pred: np.ndarray, gt: np.ndarray,
                   beta2: float = BETA2, eps: float = 1e-8) -> float:
    p = np.asarray(pred, np.float64).squeeze()
    g = np.asarray(gt).squeeze() > 0.5
    thr = min(2.0 * p.mean(), 1.0)
    binary = p >= thr
    tp = float(np.logical_and(binary, g).sum())
    precision = tp / max(float(binary.sum()), eps)
    recall = tp / max(float(g.sum()), eps)
    return float((1 + beta2) * precision * recall
                 / max(beta2 * precision + recall, eps))


def _gaussian_kernel(size: int = 7, sigma: float = 5.0) -> np.ndarray:
    ax = np.arange(size) - size // 2
    g = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    k = np.outer(g, g)
    return k / k.sum()


def _convolve2d_same(x: np.ndarray, k: np.ndarray) -> np.ndarray:
    from scipy.signal import convolve2d  # scipy ships with the image

    # Zero padding: matches PySODMetrics / the original imfilter, so
    # border-touching objects score identically to published numbers.
    return convolve2d(x, k, mode="same", boundary="fill", fillvalue=0.0)


def weighted_fmeasure(pred: np.ndarray, gt: np.ndarray,
                      beta2: float = 1.0, eps: float = 1e-8) -> float:
    """Margolin's wFβ (β²=1 as in the paper and PySODMetrics)."""
    from scipy.ndimage import distance_transform_edt

    p = np.asarray(pred, np.float64).squeeze()
    g = (np.asarray(gt).squeeze() > 0.5)
    if not g.any():
        return 0.0

    e = np.abs(p - g.astype(np.float64))
    # Distance transform of the background w.r.t. the foreground, with
    # the index of the nearest foreground pixel.
    dst, idx = distance_transform_edt(~g, return_indices=True)
    # Errors outside the object borrow the error of the nearest object
    # pixel (dependency between neighbouring pixels).
    et = e.copy()
    et[~g] = e[idx[0][~g], idx[1][~g]]
    # Gaussian-smoothed error inside the object neighbourhood.
    ea = _convolve2d_same(et, _gaussian_kernel(7, 5.0))
    min_ea = np.where(g & (ea < e), ea, e)
    # Pixel importance: background errors decay with distance from the
    # object.
    b = np.where(g, 1.0, 2.0 - np.exp(np.log(0.5) / 5.0 * dst))
    ew = min_ea * b

    tpw = float(g.sum()) - float(ew[g].sum())
    fpw = float(ew[~g].sum())
    recall = 1.0 - float(ew[g].mean())
    precision = tpw / max(tpw + fpw, eps)
    return float((1 + beta2) * precision * recall
                 / max(beta2 * precision + recall, eps))
