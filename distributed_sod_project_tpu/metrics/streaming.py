"""Device-side streaming SOD metrics (SURVEY.md §2 C10, §5).

The governing quality metric is DUTS-TE max-Fβ + MAE (BASELINE.json:2).
Convention note: the standard SOD evaluator (PySODMetrics) is
**macro-averaged** — a 256-threshold Fβ curve is computed per image,
curves are averaged over the dataset, and max-Fβ is the max of the mean
curve.  That is what ``max_fbeta`` returns.

TPU-first formulation: instead of looping 255 thresholds per image, each
image contributes a 256-bin prediction histogram split by ground-truth
class (k=⌊p·255⌋); reverse cumulative sums give TP/FP at every threshold
at once, so the per-image curve is O(H·W + 256) and fully vectorised.
The streamed state is a small pytree — accumulable across batches and
hosts with a single psum — holding the per-image curve sum (macro) plus
dataset-pooled histograms (micro, kept for diagnostics).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NUM_BINS = 256
BETA2 = 0.3  # β² for Fβ, the SOD-standard 0.3


class FBetaState(NamedTuple):
    """Accumulated sufficient statistics; a pytree → psum/checkpoint-able."""

    f_curve_sum: jnp.ndarray  # [256] Σ over images of per-image Fβ curves
    e_curve_sum: jnp.ndarray  # [256] Σ over images of per-image Em curves
    pos_hist: jnp.ndarray  # [256] pooled prediction-bin counts where gt==1
    neg_hist: jnp.ndarray  # [256] pooled prediction-bin counts where gt==0
    mae_sum: jnp.ndarray  # Σ per-image MAE
    count: jnp.ndarray  # number of images


def init_fbeta_state() -> FBetaState:
    return FBetaState(
        f_curve_sum=jnp.zeros((NUM_BINS,), jnp.float32),
        e_curve_sum=jnp.zeros((NUM_BINS,), jnp.float32),
        pos_hist=jnp.zeros((NUM_BINS,), jnp.float32),
        neg_hist=jnp.zeros((NUM_BINS,), jnp.float32),
        mae_sum=jnp.zeros((), jnp.float32),
        count=jnp.zeros((), jnp.float32),
    )


def _curves_from_hists(pos, neg, *, beta2: float, eps: float):
    """(precision, recall, f) curves from class-split histograms; works
    for one pooled histogram [256] or a batch of per-image ones [B,256].
    Threshold convention: prediction ≥ k/255 counts as positive, so TP at
    threshold k is a reverse cumulative sum over bins."""
    tp = jnp.cumsum(pos[..., ::-1], axis=-1)[..., ::-1]
    fp = jnp.cumsum(neg[..., ::-1], axis=-1)[..., ::-1]
    n_pos = pos.sum(axis=-1, keepdims=pos.ndim > 1)
    precision = tp / (tp + fp + eps)
    recall = tp / (n_pos + eps)
    f = (1.0 + beta2) * precision * recall / (beta2 * precision + recall + eps)
    return precision, recall, f


def _em_curves_from_hists(pos, neg, *, eps: float = 1e-12):
    """Per-image E-measure curves from class-split histograms [B,256].

    The enhanced-alignment map φ of a BINARISED prediction takes only
    four values per threshold — one per (pred, gt) ∈ {0,1}² cell —
    because the bias maps a_p = pb−mean(pb), a_g = g−mean(g) are
    two-valued.  Weighting those four φ values by TP/FP/FN/TN counts
    (reverse cumsums of the same histograms the Fβ curve uses) gives
    the exact 256-threshold Em curve in O(256) instead of O(256·H·W).
    Degenerate GT follows the PySODMetrics convention: all-fg → Em =
    fg-fraction of the prediction; all-bg → 1 − fg-fraction.
    """
    tp = jnp.cumsum(pos[..., ::-1], axis=-1)[..., ::-1]
    fp = jnp.cumsum(neg[..., ::-1], axis=-1)[..., ::-1]
    n_pos = pos.sum(axis=-1, keepdims=True)
    n_neg = neg.sum(axis=-1, keepdims=True)
    n = n_pos + n_neg
    fn = n_pos - tp
    tn = n_neg - fp
    p = (tp + fp) / n  # foreground fraction of the binarised pred
    q = n_pos / n      # foreground fraction of the gt (per image)

    def phi(ap, ag):
        align = 2.0 * ap * ag / (ap * ap + ag * ag + eps)
        return (align + 1.0) ** 2 / 4.0

    em = (tp * phi(1.0 - p, 1.0 - q) + fp * phi(1.0 - p, -q)
          + fn * phi(-p, 1.0 - q) + tn * phi(-p, -q)) / n
    em = jnp.where(q >= 1.0, p, em)        # all-foreground GT
    em = jnp.where(q <= 0.0, 1.0 - p, em)  # empty GT
    return em


def update_fbeta_state(
    state: FBetaState, pred, gt, *, beta2: float = BETA2, eps: float = 1e-8,
    valid=None,
) -> FBetaState:
    """Accumulate a batch.  pred ∈ [0,1] float, gt binary, both [B,H,W,1]
    (or [B,H,W]); static shapes, no host sync.  ``valid`` ([B], 0/1)
    masks out zero-padded tail images so fixed-size compiled eval
    batches accumulate exactly — a padded slot contributes nothing."""
    p = pred.astype(jnp.float32).reshape(pred.shape[0], -1)
    t = (gt.astype(jnp.float32) > 0.5).reshape(gt.shape[0], -1).astype(jnp.float32)
    v = (jnp.ones((p.shape[0],), jnp.float32) if valid is None
         else valid.astype(jnp.float32))
    # Histogramming strategy note (measured 2026-07-30): the tempting
    # scatter-free alternative — threshold comparisons reduced over
    # pixels (floor(x) >= k ⇔ x >= k for integer k) — is NOT shipped:
    # XLA materialises the [B,N,256] comparison operand (einsum → 1.7GB
    # temp at batch 16@320px; explicit mul+reduce → 3.4GB, ~100x slower
    # than scatter on XLA:CPU where the test suite and host fallbacks
    # run).  The 256-bin scatter-add below stays until a real-TPU
    # profile shows it hot in the compiled eval step; the right fix
    # then is a Pallas kernel, not fusion roulette.
    bins = jnp.clip((p * (NUM_BINS - 1)).astype(jnp.int32), 0, NUM_BINS - 1)

    def hists(b, tt):
        pos = jnp.zeros((NUM_BINS,), jnp.float32).at[b].add(tt)
        neg = jnp.zeros((NUM_BINS,), jnp.float32).at[b].add(1.0 - tt)
        return pos, neg

    pos_b, neg_b = jax.vmap(hists)(bins, t)  # [B,256] each
    _, _, f_b = _curves_from_hists(pos_b, neg_b, beta2=beta2, eps=eps)
    em_b = _em_curves_from_hists(pos_b, neg_b)
    mae_i = jnp.abs(p - t).mean(axis=-1)
    return FBetaState(
        f_curve_sum=state.f_curve_sum + (f_b * v[:, None]).sum(axis=0),
        e_curve_sum=state.e_curve_sum + (em_b * v[:, None]).sum(axis=0),
        pos_hist=state.pos_hist + (pos_b * v[:, None]).sum(axis=0),
        neg_hist=state.neg_hist + (neg_b * v[:, None]).sum(axis=0),
        mae_sum=state.mae_sum + (mae_i * v).sum(),
        count=state.count + v.sum(),
    )


def fbeta_curve(state: FBetaState, *, beta2: float = BETA2, eps: float = 1e-8):
    """Dataset-POOLED (micro) precision/recall/Fβ curves — diagnostics
    only; the headline number is the macro ``max_fbeta`` below."""
    return _curves_from_hists(
        state.pos_hist, state.neg_hist, beta2=beta2, eps=eps
    )


def mean_fbeta_curve(state: FBetaState) -> jnp.ndarray:
    """Macro (per-image-averaged) Fβ curve — PySODMetrics convention."""
    return state.f_curve_sum / jnp.maximum(state.count, 1.0)


def mean_emeasure_curve(state: FBetaState) -> jnp.ndarray:
    """Macro (per-image-averaged) 256-threshold E-measure curve."""
    return state.e_curve_sum / jnp.maximum(state.count, 1.0)


def max_fbeta(state: FBetaState):
    """(macro max-Fβ, mean MAE) from accumulated state."""
    f = mean_fbeta_curve(state)
    return f.max(), state.mae_sum / jnp.maximum(state.count, 1.0)
