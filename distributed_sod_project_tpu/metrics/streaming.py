"""Device-side streaming SOD metrics (SURVEY.md §2 C10, §5).

The governing quality metric is DUTS-TE max-Fβ + MAE (BASELINE.json:2).
TPU-first formulation: instead of looping 255 thresholds per image (the
classic evaluator), each image contributes two 256-bin histograms —
prediction values quantised to k=⌊p·255⌋ split by ground-truth class.
Cumulative sums from the top then give TP/FP at every threshold at
once: O(H·W + 256) per image, fully vectorised, accumulable across
images/hosts with a single psum.  maxFβ from the streamed state is
exact (bit-identical to the brute-force 256-threshold sweep — the
oracle test checks this).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

NUM_BINS = 256
BETA2 = 0.3  # β² for Fβ, the SOD-standard 0.3


class FBetaState(NamedTuple):
    """Accumulated sufficient statistics; a pytree → psum/checkpoint-able."""

    pos_hist: jnp.ndarray  # [256] prediction-bin counts where gt==1
    neg_hist: jnp.ndarray  # [256] prediction-bin counts where gt==0
    mae_sum: jnp.ndarray  # Σ per-image MAE
    count: jnp.ndarray  # number of images


def init_fbeta_state() -> FBetaState:
    return FBetaState(
        pos_hist=jnp.zeros((NUM_BINS,), jnp.float32),
        neg_hist=jnp.zeros((NUM_BINS,), jnp.float32),
        mae_sum=jnp.zeros((), jnp.float32),
        count=jnp.zeros((), jnp.float32),
    )


def update_fbeta_state(state: FBetaState, pred, gt) -> FBetaState:
    """Accumulate a batch.  pred ∈ [0,1] float, gt binary, both [B,H,W,1]
    (or [B,H,W]); static shapes, no host sync."""
    p = pred.astype(jnp.float32).reshape(pred.shape[0], -1)
    t = (gt.astype(jnp.float32) > 0.5).reshape(gt.shape[0], -1)
    bins = jnp.clip((p * (NUM_BINS - 1)).astype(jnp.int32), 0, NUM_BINS - 1)
    # Bincount via scatter-add, split by ground-truth class (histograms
    # are additive across images, so the whole batch merges into one).
    pos = jnp.zeros((NUM_BINS,), jnp.float32)
    neg = jnp.zeros((NUM_BINS,), jnp.float32)
    flat_bins = bins.reshape(-1)
    flat_t = t.reshape(-1)
    pos = pos.at[flat_bins].add(flat_t)
    neg = neg.at[flat_bins].add(1.0 - flat_t)
    mae = jnp.abs(p - t).mean(axis=-1).sum()
    return FBetaState(
        pos_hist=state.pos_hist + pos,
        neg_hist=state.neg_hist + neg,
        mae_sum=state.mae_sum + mae,
        count=state.count + p.shape[0],
    )


def fbeta_curve(state: FBetaState, *, beta2: float = BETA2, eps: float = 1e-8):
    """Precision/recall/Fβ at every threshold k/255 (prediction ≥ k/255
    counts as positive).  Returns (precision[256], recall[256], f[256])."""
    # TP at threshold k = # of positives with bin ≥ k  → reverse cumsum.
    tp = jnp.cumsum(state.pos_hist[::-1])[::-1]
    fp = jnp.cumsum(state.neg_hist[::-1])[::-1]
    n_pos = state.pos_hist.sum()
    precision = tp / (tp + fp + eps)
    recall = tp / (n_pos + eps)
    f = (1.0 + beta2) * precision * recall / (beta2 * precision + recall + eps)
    return precision, recall, f


def max_fbeta(state: FBetaState, *, beta2: float = BETA2):
    """(max-Fβ, mean MAE) from accumulated state."""
    _, _, f = fbeta_curve(state, beta2=beta2)
    return f.max(), state.mae_sum / jnp.maximum(state.count, 1.0)
