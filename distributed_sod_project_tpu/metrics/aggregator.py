"""PySODMetrics-style aggregator (SURVEY.md §2 C10).

Host-level API used by the eval path (test.py): feed per-image
(pred, gt) pairs at ORIGINAL resolution, read a dict of the standard
SOD numbers at the end.  Fβ/MAE accumulate through the jnp streaming
state (device-friendly); S/E-measure are host numpy per image.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .streaming import (
    FBetaState,
    init_fbeta_state,
    mean_emeasure_curve,
    mean_fbeta_curve,
    update_fbeta_state,
)
from .structure import e_measure, s_measure
from .weighted import adaptive_fbeta, weighted_fmeasure


class SODMetrics:
    def __init__(self, compute_structure: bool = True,
                 compute_fbeta: bool = True):
        """``compute_fbeta=False`` skips the threshold-curve/MAE state —
        used when those accumulate on-device (eval/inference.py
        ``device_metrics``) and this aggregator only owns the
        host-side per-image structure measures."""
        self._state: FBetaState = init_fbeta_state()
        self._compute_structure = compute_structure
        self._compute_fbeta = compute_fbeta
        self._sm: list = []
        self._em: list = []
        self._adp: list = []
        self._wfm: list = []

    def add(self, pred: np.ndarray, gt: np.ndarray) -> None:
        """pred in [0,1], gt binary; any of [H,W], [H,W,1]."""
        p = np.asarray(pred, np.float32).squeeze()
        g = np.asarray(gt).squeeze()
        if p.shape != g.shape:
            raise ValueError(f"pred {p.shape} vs gt {g.shape}")
        if self._compute_fbeta:
            self._state = update_fbeta_state(
                self._state, p[None, ..., None],
                g[None, ..., None].astype(np.float32)
            )
        if self._compute_structure:
            self._sm.append(s_measure(p, g))
            self._em.append(e_measure(p, g))
            self._adp.append(adaptive_fbeta(p, g))
            self._wfm.append(weighted_fmeasure(p, g))

    def curves(self) -> Dict[str, np.ndarray]:
        """256-threshold curves for plotting (PySODEvalToolkit-style):
        pooled (micro) precision/recall/Fβ plus the macro Fβ curve the
        headline max-Fβ comes from."""
        from .streaming import fbeta_curve

        prec, rec, f = fbeta_curve(self._state)
        return {
            "precision": np.asarray(prec),
            "recall": np.asarray(rec),
            "fbeta_pooled": np.asarray(f),
            "fbeta_macro": np.asarray(mean_fbeta_curve(self._state)),
            "emeasure_macro": np.asarray(mean_emeasure_curve(self._state)),
        }

    def results(self) -> Dict[str, float]:
        out = (results_from_state(self._state) if self._compute_fbeta
               else {"num_images": len(self._sm)})
        if self._compute_structure and self._sm:
            out["s_measure"] = float(np.mean(self._sm))
            out["e_measure"] = float(np.mean(self._em))
            out["adp_fbeta"] = float(np.mean(self._adp))
            out["weighted_fmeasure"] = float(np.mean(self._wfm))
        return out


def results_from_state(state: FBetaState) -> Dict[str, float]:
    """The standard result dict from accumulated threshold-curve state —
    shared by the host aggregator and the device-side eval path."""
    f = np.asarray(mean_fbeta_curve(state))  # macro, one finalise pass
    em = np.asarray(mean_emeasure_curve(state))
    n = max(float(state.count), 1.0)
    return {
        "max_fbeta": float(f.max()),
        "mean_fbeta": float(f.mean()),
        "max_emeasure": float(em.max()),
        "mean_emeasure": float(em.mean()),
        "mae": float(state.mae_sum) / n,
        "num_images": int(state.count),
    }
