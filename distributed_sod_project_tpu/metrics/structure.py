"""S-measure and E-measure (SURVEY.md §2 C10) — host-side per-image.

These run on the eval path only (once per image, not in the train hot
loop), so they are plain numpy for clarity and easy auditing against
the published formulations:

- S-measure (Fan et al., ICCV 2017): Sm = α·S_object + (1−α)·S_region,
  α = 0.5, with the standard degenerate-GT conventions.
- E-measure (Fan et al., IJCAI 2018): mean enhanced-alignment of the
  *binarised* (2×mean-pred adaptive threshold variant is NOT used here;
  this is the curve-free mean-φ over the continuous map convention used
  by PySODMetrics' `adp=False, curve=False` mean case is intricate —
  we implement the adaptive-threshold Em, the number usually reported).
"""

from __future__ import annotations

import numpy as np


def _ssim_region(pred: np.ndarray, gt: np.ndarray) -> float:
    """SSIM-style similarity of one region (means/vars/cov form)."""
    x, y = pred.astype(np.float64), gt.astype(np.float64)
    n = x.size
    if n <= 1:
        return 1.0
    mx, my = x.mean(), y.mean()
    vx = ((x - mx) ** 2).sum() / (n - 1)
    vy = ((y - my) ** 2).sum() / (n - 1)
    cxy = ((x - mx) * (y - my)).sum() / (n - 1)
    alpha = 4.0 * mx * my * cxy
    beta = (mx**2 + my**2) * (vx + vy)
    if alpha != 0:
        return alpha / (beta + 1e-20)
    return 1.0 if (alpha == 0 and beta == 0) else 0.0


def _object_score(x: np.ndarray) -> float:
    """Object-aware similarity of a (foreground or background) region."""
    if x.size == 0:
        return 0.0
    mean = x.mean()
    std = x.std()
    return 2.0 * mean / (mean * mean + 1.0 + std + 1e-20)


def s_measure(pred: np.ndarray, gt: np.ndarray, alpha: float = 0.5) -> float:
    """Structure measure of a single prediction in [0,1] vs binary gt."""
    pred = np.asarray(pred, np.float64).squeeze()
    gt = np.asarray(gt).squeeze() > 0.5
    mu = gt.mean()
    if mu == 0:  # empty GT → reward all-black prediction
        return 1.0 - pred.mean()
    if mu == 1:  # full GT → reward all-white prediction
        return pred.mean()

    # S_object: fg similarity weighted by μ, bg by (1-μ).
    s_obj = mu * _object_score(pred[gt]) + (1 - mu) * _object_score(
        1.0 - pred[~gt]
    )

    # S_region: split at the GT centroid into 4 quadrants; weighted SSIM.
    h, w = gt.shape
    ys, xs = np.nonzero(gt)
    cy = int(round(ys.mean())) + 1
    cx = int(round(xs.mean())) + 1
    cy = min(max(cy, 1), h - 1)
    cx = min(max(cx, 1), w - 1)
    quads = [
        (slice(0, cy), slice(0, cx)),
        (slice(0, cy), slice(cx, w)),
        (slice(cy, h), slice(0, cx)),
        (slice(cy, h), slice(cx, w)),
    ]
    total = float(h * w)
    s_reg = 0.0
    for sl in quads:
        g_q, p_q = gt[sl], pred[sl]
        weight = g_q.size / total
        s_reg += weight * _ssim_region(p_q, g_q.astype(np.float64))

    score = alpha * s_obj + (1 - alpha) * s_reg
    return float(max(score, 0.0))


def e_measure(pred: np.ndarray, gt: np.ndarray) -> float:
    """Adaptive-threshold E-measure of one prediction vs binary gt.

    Binarise at 2×mean(pred) (the standard adaptive rule), then compute
    the enhanced-alignment score φ = (2·a_p·a_g/(a_p²+a_g²)+1)²/4 where
    a_p/a_g are the bias-from-mean maps of the binarised pred and gt.
    """
    pred = np.asarray(pred, np.float64).squeeze()
    gt_b = np.asarray(gt).squeeze() > 0.5
    thr = min(2.0 * pred.mean(), 1.0)
    pb = (pred >= thr).astype(np.float64)
    g = gt_b.astype(np.float64)

    if gt_b.all():
        return float(pb.mean())
    if not gt_b.any():
        return float(1.0 - pb.mean())

    a_p = pb - pb.mean()
    a_g = g - g.mean()
    align = 2.0 * a_p * a_g / (a_p**2 + a_g**2 + 1e-20)
    phi = (align + 1.0) ** 2 / 4.0
    return float(phi.mean())
