from .streaming import (
    FBetaState,
    fbeta_curve,
    init_fbeta_state,
    max_fbeta,
    mean_fbeta_curve,
    update_fbeta_state,
)
from .structure import e_measure, s_measure
from .aggregator import SODMetrics

__all__ = [
    "FBetaState",
    "fbeta_curve",
    "init_fbeta_state",
    "max_fbeta",
    "mean_fbeta_curve",
    "update_fbeta_state",
    "e_measure",
    "s_measure",
    "SODMetrics",
]
