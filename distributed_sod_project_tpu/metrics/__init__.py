from .streaming import (
    FBetaState,
    fbeta_curve,
    init_fbeta_state,
    max_fbeta,
    mean_fbeta_curve,
    update_fbeta_state,
)
from .structure import e_measure, s_measure
from .weighted import adaptive_fbeta, weighted_fmeasure
from .aggregator import SODMetrics

__all__ = [
    "adaptive_fbeta",
    "weighted_fmeasure",
    "FBetaState",
    "fbeta_curve",
    "init_fbeta_state",
    "max_fbeta",
    "mean_fbeta_curve",
    "update_fbeta_state",
    "e_measure",
    "s_measure",
    "SODMetrics",
]
