"""Stdlib-only HTTP front end for the serving engine (docs/SERVING.md).

Endpoints:

- ``POST /predict`` — body: ``.npy`` bytes of an (H, W, 3) uint8 image
  (float32 in [0,1] accepted, quantized through uint8).  Optional
  ``X-SLO-MS`` header sets a per-request deadline; optional
  ``X-Precision`` selects a precision arm (must be enabled — 400 on an
  unknown arm; the degraded ladder may still step it down).  200
  responds with ``.npy`` float32 (H, W) saliency at the ORIGINAL
  resolution plus ``X-Model`` (the served model — the same header the
  fleet router echoes, so loadgen's per-model breakdown works against
  either front end) / ``X-Degraded`` (the ladder level, "0" when
  clean) / ``X-Precision`` (the arm actually served) /
  ``X-Res-Bucket`` / ``X-Batch-Bucket`` / ``X-Queue-MS`` /
  ``X-Device-MS`` / ``X-E2E-MS`` headers.  Overload sheds with 429, a
  missed SLO with 504, an unhealthy engine with 503.
- ``GET /healthz``  — 200 while the dispatch loop's resilience-watchdog
  heartbeat is live, 503 once it stalls (or the engine stopped).
- ``GET /metrics``  — Prometheus text (ServeStats: latency histograms,
  shed/expired counters, batch occupancy, degraded/health gauges; plus
  ``dsod_quality_*``/``dsod_alert_*`` when ``serve.quality_monitor``).
- ``GET /stats``    — the same telemetry as one JSON object.
- ``GET /alerts``   — the alert engine's rule states (utils/alerts.py;
  empty rule list when the quality monitors are off).
- ``GET /debug/traces?n=N`` — sampled request span timelines + the
  worst-N exemplars per (model, res bucket) (docs/OBSERVABILITY.md).

Every 200 also carries ``X-Request-ID`` (client-supplied or minted —
doubles as the trace id) and ``X-Timing`` (the server-side stage
split; ``trace=-`` when the request was not sampled).

No framework on purpose: the serving story must not add dependencies
the training image doesn't have (stdlib ``http.server`` + threads).
"""

from __future__ import annotations

import io
import json
import signal
import threading
import time
from concurrent.futures import TimeoutError as FutTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..resilience.inject import plan_from_env
from ..utils.logging import get_logger
from ..utils.tracing import format_timing, mint_trace_id
from .admission import DeadlineExpired, EngineStopped, QueueFull

MAX_BODY_BYTES = 64 * 1024 * 1024  # reject absurd uploads before np.load

_REQUEST_ID_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.")


def resolve_request_id(header_value) -> str:
    """Honor a client-supplied ``X-Request-ID`` (sanitized: the id is
    echoed into response headers and trace exports) or mint one.  The
    id doubles as the trace id, so a caller that supplies its own can
    correlate its logs with /debug/traces."""
    if header_value:
        rid = "".join(c for c in header_value.strip()
                      if c in _REQUEST_ID_SAFE)[:64]
        if rid:
            return rid
    return mint_trace_id()


def _query_int(query: str, key: str, default: int) -> int:
    """One int query field (``?n=20``), tolerant of garbage."""
    import urllib.parse

    try:
        return int(urllib.parse.parse_qs(query).get(key, [default])[0])
    except (ValueError, TypeError):
        return default


def timing_header(request_id, meta) -> str:
    """The ``X-Timing`` value for a served request: the server-side
    stage split (ms) from the request's own meta — the exact numbers
    the latency histograms observed, so client-side e2e reconciles
    against the server's split without a /debug/traces round trip."""
    return format_timing(
        request_id if meta.get("trace_id") else None,
        {"queue": meta.get("queue_ms", 0.0),
         "device": meta.get("device_ms", 0.0),
         "resize": meta.get("resize_ms", 0.0),
         "e2e": meta.get("e2e_ms", 0.0)})


def read_predict_body(handler) -> Optional[bytes]:
    """Read + bound a /predict body; on a bad Content-Length, answer
    400 (dropping the keep-alive connection — the unread image bytes
    would otherwise be parsed as the next request) and return None."""
    try:
        length = int(handler.headers.get("Content-Length", 0))
    except ValueError:
        length = -1  # non-numeric header: rejected below, body unread
    if not 0 < length <= MAX_BODY_BYTES:
        handler.close_connection = True
        handler._send_json(400, {
            "error": f"Content-Length {length} outside "
                     f"(0, {MAX_BODY_BYTES}]"})
        return None
    return handler.rfile.read(length)


_SLO_FROM_HEADER = object()  # sentinel: parse X-SLO-MS off the request


def run_predict(handler, engine, body: bytes, extra_headers=(),
                slo_ms=_SLO_FROM_HEADER, request_id=None,
                trace_parent=None, stream=None) -> str:
    """The whole /predict flow against one engine: decode the .npy
    body, validate the precision arm, submit, wait, respond — including
    the full error→status mapping.  Shared by the single-engine
    ``ServeHandler`` and the fleet router (serve/router.py), so the two
    front doors can never drift.  Returns the request's outcome for
    caller-side (e.g. per-tenant) accounting — ``rejected`` means a
    400 BEFORE submit (the engine never saw the request; the router
    must terminal-count it itself), every other outcome
    (``ok | bad_request | shed | expired | stopped | timeout | error``)
    was or will be terminal-counted by the engine.

    NEVER raises: every send is guarded, so a client that disconnects
    mid-response still yields a definite outcome — ``rejected`` when
    the engine never saw the request, ``error`` (engine-owned) after
    submit.  An escaping exception here would strand a router-counted
    submission with no terminal and break the fleet identity."""
    submitted = False

    def send(code, obj_or_bytes, content_type=None, headers=()):
        try:
            if content_type is None:
                handler._send_json(code, obj_or_bytes, headers=headers)
            else:
                handler._send(code, obj_or_bytes, content_type,
                              headers=headers)
        except Exception:  # noqa: BLE001 — client went away mid-response
            handler.close_connection = True

    try:
        try:
            image = np.load(io.BytesIO(body), allow_pickle=False)
        except Exception as e:  # noqa: BLE001 — client error surface
            send(400, {"error": f"body is not .npy: {e}",
                       "kind": "rejected"})
            return "rejected"
        # Channel contract BEFORE submit: an (H, W, 3) payload to an
        # RGB-D model — or (H, W, 4) to an RGB model — is a client
        # error the engine must never see (accounting untouched), the
        # same discipline as the malformed-header rejects below.  Other
        # malformed shapes keep the historical engine-counted 400 path.
        want_c = 4 if getattr(engine, "wants_depth", False) else 3
        if getattr(image, "ndim", 0) == 3 \
                and image.shape[2] in (3, 4) and image.shape[2] != want_c:
            kind = ("RGB-D: payloads must be (H, W, 4) RGBD"
                    if want_c == 4
                    else "RGB: payloads must be (H, W, 3)")
            send(400, {
                "error": f"model {engine.cfg.model.name!r} serves "
                         f"{kind}, got shape {tuple(image.shape)}",
                "kind": "rejected"})
            return "rejected"
        precision = handler.headers.get("X-Precision")
        if precision is not None:
            precision = precision.strip().lower()
            if precision not in engine.precision_arms:
                # Rejected before submit(): never entered the
                # engine's accounting (nothing was submitted).
                send(400, {
                    "error": f"unknown precision {precision!r}; "
                             "enabled arms: "
                             f"{list(engine.precision_arms)}",
                    "kind": "rejected"})
                return "rejected"
        if slo_ms is not _SLO_FROM_HEADER:
            # Caller-supplied deadline (the fleet router passes the
            # request's RESIDUAL budget so elapsed router time and
            # prior attempts are charged; None = no deadline).
            slo = slo_ms
        else:
            slo = handler.headers.get("X-SLO-MS")
            if slo is not None:
                try:
                    slo = float(slo)
                except ValueError:
                    # Parsed BEFORE submit on purpose: a malformed
                    # header must be a pre-submit reject (the engine
                    # never sees it), not an engine-counted ValueError.
                    send(400, {
                        "error": f"X-SLO-MS {slo!r} is not a number",
                        "kind": "rejected"})
                    return "rejected"
        fut = engine.submit(image, slo_ms=slo, precision=precision,
                            trace_id=request_id,
                            trace_parent=trace_parent, stream=stream)
        submitted = True
        pred, meta = fut.result(
            timeout=engine.cfg.serve.request_timeout_s)
        buf = io.BytesIO()
        np.save(buf, pred)
        timing = ([("X-Timing", timing_header(request_id, meta))]
                  if request_id else [])
        send(200, buf.getvalue(), "application/x-npy",
             headers=list(extra_headers) + timing + [
            # The ladder rung the request was admitted at ("0" stays
            # falsy for the historical binary readers).
            ("X-Degraded", str(meta.get("degraded_level",
                                        int(bool(meta.get("degraded")))))),
            # The arm actually served (ladder-adjusted) — loadgen
            # splits its latency curves on this.
            ("X-Precision", str(meta.get("precision"))),
            ("X-Res-Bucket", str(meta.get("res_bucket"))),
            ("X-Batch-Bucket", str(meta.get("batch_bucket"))),
            ("X-Queue-MS", f"{meta.get('queue_ms', 0):.3f}"),
            ("X-Device-MS", f"{meta.get('device_ms', 0):.3f}"),
            ("X-E2E-MS", f"{meta.get('e2e_ms', 0):.3f}"),
        ])
        return "ok"
    except QueueFull as e:
        send(429, {"error": str(e), "kind": "shed"})
        return "shed"
    except DeadlineExpired as e:
        send(504, {"error": str(e), "kind": "expired"})
        return "expired"
    except EngineStopped as e:
        send(503, {"error": str(e), "kind": "stopped"})
        return "stopped"
    except ValueError as e:
        # Raised by engine.submit (malformed image): the ENGINE counted
        # submitted+errors.  The "kind" lets a fronting router tell this
        # engine-counted 400 apart from the pre-submit "rejected" ones
        # when proxying a remote replica.
        send(400, {"error": str(e), "kind": "invalid_input"})
        return "bad_request"
    except FutTimeout:
        # The ENGINE owns the terminal counters; this request is
        # still live and will be counted (served/errors) when its
        # batch completes — counting it here too would terminate
        # one request in two counters.
        send(504, {
            "error": "response not ready within "
                     f"{engine.cfg.serve.request_timeout_s}s",
            "kind": "timeout"})
        return "timeout"
    except Exception as e:  # noqa: BLE001 — last-resort 500
        # No counter here either: every exception a future relays
        # was already terminal-counted by the engine when it failed
        # the request.
        get_logger().exception("predict handler failed")
        send(500, {"error": f"{type(e).__name__}: {e}"})
        # Post-submit the ENGINE owns the terminal (observational
        # "error"); pre-submit the engine never saw it — the caller
        # must terminal-count the reject.
        return "error" if submitted else "rejected"


class JsonHTTPHandler(BaseHTTPRequestHandler):
    """Shared stdlib-handler plumbing (response helpers + access-log
    routing) for the serving front ends — the single-engine
    ``ServeHandler`` here and the fleet ``RouterHandler``
    (serve/router.py)."""

    protocol_version = "HTTP/1.1"
    server_version = "dsod-serve/1.0"

    def log_message(self, fmt, *args):  # route access logs to our logger
        get_logger().debug("http: " + fmt, *args)

    def _send(self, code: int, body: bytes, content_type: str,
              headers=()) -> None:
        xform = getattr(self, "_send_transform", None)
        if xform is not None:
            # Response rewrite hook (serve/streams.py EMA mask blend):
            # applied BEFORE the capture tee so the client bytes and
            # whatever the tee feeds (cache, stream warm state) are the
            # SAME bytes.  None everywhere streaming is off.
            body = xform(code, body, content_type, headers)
        cap = getattr(self, "_send_capture", None)
        if cap is not None:
            # Router-cache tee (serve/cache.py): a coalescing LEADER
            # records what is about to go to the client — whoever
            # writes it (run_predict for engines, the remote relay) —
            # so followers can be served the same bytes and the LRU
            # can fill.  Captured BEFORE the write: a client gone
            # mid-response doesn't change what the backend answered.
            h = dict(headers)
            h["Content-Type"] = content_type
            cap.append((code, h, body))
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        drip_s = getattr(self, "_inject_drip_s", 0.0)
        if drip_s > 0 and len(body) > 1:
            # Injected slow-drip (resilience/inject.py serve_drip@R:SEC):
            # the sick-but-alive replica that accepts connections and
            # then starves the reader.  One response only; then clear.
            self._inject_drip_s = 0.0
            n = min(8, len(body))
            step = (len(body) + n - 1) // n
            for i in range(0, len(body), step):
                self.wfile.write(body[i:i + step])
                self.wfile.flush()
                time.sleep(drip_s / n)
            return
        self.wfile.write(body)

    def _send_json(self, code: int, obj, headers=()) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json",
                   headers=headers)

    def _apply_injected_fault(self, action) -> bool:
        """Apply a scheduled serve-tier fault (resilience/inject.py).
        True = the fault WAS the response (stop handling); False = the
        request proceeds (drip arms the send path)."""
        kind, arg = action
        if kind == "500":
            # Body unread: drop the connection so keep-alive can't
            # misparse the image bytes as the next request.
            self.close_connection = True
            self._send_json(500, {"error": "injected fault: 5xx burst",
                                  "kind": "injected_fault"})
            return True
        if kind == "reset":
            # Mid-body reset: claim the full length, write half, kill
            # the socket — the reader sees a short body / reset.
            payload = json.dumps(
                {"error": "injected fault: mid-body reset"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload) * 2))
            self.end_headers()
            self.wfile.write(payload[: len(payload) // 2])
            self.wfile.flush()
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
            return True
        if kind == "drip":
            self._inject_drip_s = float(arg)
        return False


class ServeHandler(JsonHTTPHandler):

    @property
    def engine(self):
        return self.server.engine

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 — http.server API
        import urllib.parse

        split = urllib.parse.urlsplit(self.path)
        path = split.path
        if path == "/healthz":
            stats = self.engine.stats
            if stats.healthy and self.engine._running:
                # Active model-health alerts DEGRADE the verdict (200
                # with the rules named: the engine still serves, the
                # MODEL may be drifting — a fronting LB must not drain
                # a replica over a quality worry, an operator must see
                # it).  docs/OBSERVABILITY.md "Model health".  Active
                # SLO burn/budget alerts join the same degraded list
                # ("Capacity & SLO").
                alerts = self.engine.alerts
                active = alerts.active_reasons() if alerts else []
                if self.engine.slo is not None:
                    active = active + self.engine.slo.active_reasons()
                if active:
                    self._send_json(200, {"status": "degraded",
                                          "alerts": active})
                else:
                    self._send_json(200, {"status": "ok"})
            else:
                self._send_json(503, {
                    "status": "unhealthy",
                    "reason": stats.health_reason or "engine stopped"})
        elif path == "/metrics":
            # The shared TelemetryRegistry render path — with the one
            # "serve" provider (quality monitors off) this is
            # byte-identical to stats.render_prometheus() (asserted in
            # tests).
            self._send(200, self.engine.telemetry.render().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/stats":
            self._send_json(200, self.engine.stats_snapshot())
        elif path == "/alerts":
            # Quality + SLO rule states merged into one payload (two
            # engines, disjoint rule names — utils/slo.py prefixes
            # slo_).
            snap = {"active": [], "rules": []}
            for eng in (self.engine.alerts,
                        self.engine.slo.alerts
                        if self.engine.slo is not None else None):
                if eng is not None:
                    s = eng.snapshot()
                    snap["active"] += s["active"]
                    snap["rules"] += s["rules"]
            self._send_json(200, snap)
        elif path == "/slo":
            # Error-budget accounting (utils/slo.py): empty objective
            # list when the knob is off, so scrapers need no probe.
            slo = self.engine.slo
            self._send_json(200, slo.snapshot() if slo is not None
                            else {"objectives": [], "active": []})
        elif path == "/debug/traces":
            self._send_json(200, self.engine.tracer.snapshot(
                n=_query_int(split.query, "n", 50)))
        elif path == "/incidents":
            # Flight-recorder state: ring segments + incident bundles
            # on disk (utils/flightrecorder.py; the bundles themselves
            # are files — tools/incident.py reads them offline).
            rec = self.engine.recorder
            self._send_json(200, rec.snapshot() if rec is not None
                            else {"enabled": False})
        else:
            self._send_json(404, {"error": f"no route {path}"})

    def _admin_reload(self) -> None:
        """POST /admin/reload ``{"step": N}`` — the rollout control
        plane's targeted reload (serve/rollout.py drives ONE canary
        replica to a candidate step; RemoteBackend.admin_reload is the
        client).  400 on a bad body; 409 when the engine has no
        checkpoint source or refuses the step (invalid/denylisted) —
        a refusal is an answer, not a server fault."""
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
            payload = json.loads(self.rfile.read(length).decode()
                                 if length else "{}")
            step = int(payload["step"])
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {
                "error": f'body must be {{"step": N}}: {e}'})
            return
        try:
            loaded = self.engine.reload_to(step)
        except (RuntimeError, ValueError) as e:
            self._send_json(409, {"error": str(e), "step": step})
            return
        except Exception as e:  # noqa: BLE001 — a torn checkpoint
            self._send_json(500, {"error": str(e), "step": step})
            return
        self._send_json(200, {"ok": True, "step": loaded})

    # -- POST ----------------------------------------------------------

    def do_POST(self):  # noqa: N802 — http.server API
        if self.path == "/admin/reload":
            self._admin_reload()
            return
        if self.path != "/predict":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        plan = plan_from_env()
        if plan is not None:
            action = plan.next_serve_request()
            if action is not None and self._apply_injected_fault(action):
                return
        body = read_predict_body(self)
        if body is None:
            return
        # X-Model on every 200: the single-engine server reports its
        # one model under the same header the fleet router echoes, so
        # loadgen's per-model breakdown works against either front end.
        # X-Request-ID (client-supplied or minted) doubles as the
        # trace id; X-Timing carries the stage split on every 200.
        rid = resolve_request_id(self.headers.get("X-Request-ID"))
        t0 = time.monotonic()
        outcome = run_predict(self, self.engine, body, request_id=rid,
                              extra_headers=[
                                  ("X-Model",
                                   str(self.engine.cfg.model.name)),
                                  ("X-Request-ID", rid)])
        if self.engine.slo is not None:
            # One SLO event per terminal outcome, at the same seam the
            # outcome was decided (client-fault terminals excluded
            # inside — utils/slo.py).
            self.engine.slo.observe_outcome(
                outcome, (time.monotonic() - t0) * 1000.0,
                model=str(self.engine.cfg.model.name))


class SODServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, engine):
        self.engine = engine
        super().__init__(addr, ServeHandler)


def make_server(engine, host: str, port: int) -> SODServer:
    """Bind (``port=0`` → ephemeral; read ``server_address[1]``)."""
    return SODServer((host, port), engine)


def publish_port(port_file: Optional[str], bound: int) -> None:
    """Atomic port-file publish: pollers watch for the file's existence
    and read immediately, so it must never be visible half-written."""
    if not port_file:
        return
    import os

    tmp = port_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(bound))
    os.replace(tmp, port_file)


def serve_forever(engine, host: str, port: int,
                  port_file: str = None) -> int:
    """Start the engine + HTTP server and block until SIGTERM/SIGINT;
    returns 0 on a clean drain (the contract tools/t1.sh smokes)."""
    log = get_logger()
    engine.start()
    srv = make_server(engine, host, port)
    bound = srv.server_address[1]
    publish_port(port_file, bound)
    stop = threading.Event()

    def _sig(signum, frame):
        log.info("serve: signal %s — draining", signum)
        if engine.recorder is not None and not stop.is_set():
            # The terminating signal IS an incident trigger: bundle the
            # last window of telemetry before the drain tears the
            # process down (debounced like every other trigger).
            engine.recorder.trigger("sigterm", f"signal {signum}")
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _sig)
        except ValueError:  # non-main thread (tests drive stop directly)
            pass
    t = threading.Thread(target=srv.serve_forever, name="serve-http",
                         daemon=True)
    t.start()
    log.info("serve: listening on http://%s:%d (buckets res=%s batch=%s)",
             host, bound, engine.res_buckets, engine.batch_buckets)
    try:
        while not stop.wait(0.2):
            pass
    finally:
        srv.shutdown()
        srv.server_close()
        engine.stop()
        log.info("serve: shut down cleanly")
    return 0
