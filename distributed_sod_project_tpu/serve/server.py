"""Stdlib-only HTTP front end for the serving engine (docs/SERVING.md).

Endpoints:

- ``POST /predict`` — body: ``.npy`` bytes of an (H, W, 3) uint8 image
  (float32 in [0,1] accepted, quantized through uint8).  Optional
  ``X-SLO-MS`` header sets a per-request deadline; optional
  ``X-Precision`` selects a precision arm (must be enabled — 400 on an
  unknown arm; the degraded ladder may still step it down).  200
  responds with ``.npy`` float32 (H, W) saliency at the ORIGINAL
  resolution plus ``X-Degraded`` (the ladder level, "0" when clean) /
  ``X-Precision`` (the arm actually served) / ``X-Res-Bucket`` /
  ``X-Batch-Bucket`` / ``X-Queue-MS`` / ``X-Device-MS`` / ``X-E2E-MS``
  headers.  Overload sheds with 429, a missed SLO with 504, an
  unhealthy engine with 503.
- ``GET /healthz``  — 200 while the dispatch loop's resilience-watchdog
  heartbeat is live, 503 once it stalls (or the engine stopped).
- ``GET /metrics``  — Prometheus text (ServeStats: latency histograms,
  shed/expired counters, batch occupancy, degraded/health gauges).
- ``GET /stats``    — the same telemetry as one JSON object.

No framework on purpose: the serving story must not add dependencies
the training image doesn't have (stdlib ``http.server`` + threads).
"""

from __future__ import annotations

import io
import json
import signal
import threading
from concurrent.futures import TimeoutError as FutTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..utils.logging import get_logger
from .admission import DeadlineExpired, EngineStopped, QueueFull

MAX_BODY_BYTES = 64 * 1024 * 1024  # reject absurd uploads before np.load


class ServeHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "dsod-serve/1.0"

    @property
    def engine(self):
        return self.server.engine

    def log_message(self, fmt, *args):  # route access logs to our logger
        get_logger().debug("http: " + fmt, *args)

    # -- helpers -------------------------------------------------------

    def _send(self, code: int, body: bytes, content_type: str,
              headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json")

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path == "/healthz":
            stats = self.engine.stats
            if stats.healthy and self.engine._running:
                self._send_json(200, {"status": "ok"})
            else:
                self._send_json(503, {
                    "status": "unhealthy",
                    "reason": stats.health_reason or "engine stopped"})
        elif self.path == "/metrics":
            self._send(200, self.engine.stats.render_prometheus().encode(),
                       "text/plain; version=0.0.4")
        elif self.path == "/stats":
            self._send_json(200, self.engine.stats.snapshot())
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    # -- POST ----------------------------------------------------------

    def do_POST(self):  # noqa: N802 — http.server API
        if self.path != "/predict":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            if not 0 < length <= MAX_BODY_BYTES:
                # The body was never read: a keep-alive client's next
                # request would otherwise be parsed out of the unread
                # image bytes.  Drop the connection with the rejection.
                self.close_connection = True
                self._send_json(400, {
                    "error": f"Content-Length {length} outside "
                             f"(0, {MAX_BODY_BYTES}]"})
                return
            body = self.rfile.read(length)
            try:
                image = np.load(io.BytesIO(body), allow_pickle=False)
            except Exception as e:  # noqa: BLE001 — client error surface
                self._send_json(400, {"error": f"body is not .npy: {e}"})
                return
            precision = self.headers.get("X-Precision")
            if precision is not None:
                precision = precision.strip().lower()
                if precision not in self.engine.precision_arms:
                    # Rejected before submit(): never entered the
                    # engine's accounting (nothing was submitted).
                    self._send_json(400, {
                        "error": f"unknown precision {precision!r}; "
                                 "enabled arms: "
                                 f"{list(self.engine.precision_arms)}"})
                    return
            slo = self.headers.get("X-SLO-MS")
            fut = self.engine.submit(
                image, slo_ms=float(slo) if slo is not None else None,
                precision=precision)
            pred, meta = fut.result(
                timeout=self.engine.cfg.serve.request_timeout_s)
            buf = io.BytesIO()
            np.save(buf, pred)
            self._send(200, buf.getvalue(), "application/x-npy", headers=[
                # The ladder rung the request was admitted at ("0" stays
                # falsy for the historical binary readers).
                ("X-Degraded", str(meta.get("degraded_level",
                                            int(bool(meta.get("degraded")))))),
                # The arm actually served (ladder-adjusted) — loadgen
                # splits its latency curves on this.
                ("X-Precision", str(meta.get("precision"))),
                ("X-Res-Bucket", str(meta.get("res_bucket"))),
                ("X-Batch-Bucket", str(meta.get("batch_bucket"))),
                ("X-Queue-MS", f"{meta.get('queue_ms', 0):.3f}"),
                ("X-Device-MS", f"{meta.get('device_ms', 0):.3f}"),
                ("X-E2E-MS", f"{meta.get('e2e_ms', 0):.3f}"),
            ])
        except QueueFull as e:
            self._send_json(429, {"error": str(e), "kind": "shed"})
        except DeadlineExpired as e:
            self._send_json(504, {"error": str(e), "kind": "expired"})
        except EngineStopped as e:
            self._send_json(503, {"error": str(e), "kind": "stopped"})
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
        except FutTimeout:
            # The ENGINE owns the terminal counters; this request is
            # still live and will be counted (served/errors) when its
            # batch completes — counting it here too would terminate
            # one request in two counters.
            self._send_json(504, {
                "error": "response not ready within "
                         f"{self.engine.cfg.serve.request_timeout_s}s",
                "kind": "timeout"})
        except Exception as e:  # noqa: BLE001 — last-resort 500
            # No counter here either: every exception a future relays
            # was already terminal-counted by the engine when it failed
            # the request.
            get_logger().exception("predict handler failed")
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})


class SODServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, engine):
        self.engine = engine
        super().__init__(addr, ServeHandler)


def make_server(engine, host: str, port: int) -> SODServer:
    """Bind (``port=0`` → ephemeral; read ``server_address[1]``)."""
    return SODServer((host, port), engine)


def serve_forever(engine, host: str, port: int,
                  port_file: str = None) -> int:
    """Start the engine + HTTP server and block until SIGTERM/SIGINT;
    returns 0 on a clean drain (the contract tools/t1.sh smokes)."""
    log = get_logger()
    engine.start()
    srv = make_server(engine, host, port)
    bound = srv.server_address[1]
    if port_file:
        # Atomic publish: pollers watch for the file's existence and
        # read immediately, so it must never be visible half-written.
        import os

        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(bound))
        os.replace(tmp, port_file)
    stop = threading.Event()

    def _sig(signum, frame):
        log.info("serve: signal %s — draining", signum)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _sig)
        except ValueError:  # non-main thread (tests drive stop directly)
            pass
    t = threading.Thread(target=srv.serve_forever, name="serve-http",
                         daemon=True)
    t.start()
    log.info("serve: listening on http://%s:%d (buckets res=%s batch=%s)",
             host, bound, engine.res_buckets, engine.batch_buckets)
    try:
        while not stop.wait(0.2):
            pass
    finally:
        srv.shutdown()
        srv.server_close()
        engine.stop()
        log.info("serve: shut down cleanly")
    return 0
