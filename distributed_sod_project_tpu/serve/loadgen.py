"""Open/closed-loop load generator for the serving engine
(docs/SERVING.md "Measuring throughput vs p99").

Stdlib-only (urllib + threads) so it runs anywhere the server does.
Two disciplines, because they answer different questions:

- **closed** loop — N workers, each sending back-to-back.  Measures
  capacity: the throughput the service sustains at a given concurrency
  and the latency it costs.  Latency under closed load is flattering
  (the generator slows down with the server — coordinated omission).
- **open** loop — requests fired on a fixed schedule at ``rps``
  regardless of completions, the arrival process real traffic has.
  Measures SLO behavior: p99 and shed rate at an offered rate, which is
  what the throughput-vs-p99 curve in tools/tpu_agenda_r7.sh sweeps.

Either discipline can offer **mixed traffic** against a fleet router
(``mix=``: weighted per-model/per-tenant request mix via X-Model /
X-Tenant headers), with per-SERVED-model p50/p95/p99 broken out in the
summary next to the per-arm breakdown — the fleet's mixed-model curve
(tools/tpu_agenda_r9.sh) is one command.

**Duplicate traffic** (``zipf=(s, catalog)``): instead of cycling a
small body pool, each request draws its payload from a ``catalog`` of
distinct pre-encoded images with Zipf popularity p(k) ∝ 1/k^s — the
skewed repeat distribution real image traffic has, and the workload
the router cache (serve/cache.py) is built for.  ``perturb`` sends
that fraction of draws as a resize-perturbed re-encode of their
catalog image (same content, different bytes/resolution — misses the
exact arm, hits the near-dup arm).  The summary gains hit-rate and a
per-terminal-class breakdown read from the X-Cache response header.
"""

from __future__ import annotations

import heapq
import http.client
import io
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.tracing import mint_trace_id, parse_timing


def encode_image(rng: np.random.RandomState, h: int, w: int) -> bytes:
    buf = io.BytesIO()
    np.save(buf, rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8))
    return buf.getvalue()


def _encode_arr(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def structured_image(rng: np.random.RandomState, h: int, w: int
                     ) -> np.ndarray:
    """A smooth low-frequency test image (8x8 noise upsampled
    bilinearly).  Pure uint8 noise is the WRONG payload for near-dup
    experiments — its perceptual hash is not resize-stable (every
    pixel is independent, so resampling rewrites the block means);
    natural images are dominated by low frequencies, which survive a
    resize, and this generator keeps that property on purpose."""
    from PIL import Image

    base = rng.randint(0, 256, size=(8, 8, 3)).astype(np.uint8)
    return np.asarray(Image.fromarray(base).resize((w, h), Image.BILINEAR))


def _zipf_bodies(rng: np.random.RandomState, zipf, perturb: float,
                 sizes, n_total: int) -> List[bytes]:
    """Per-request payloads for a duplicate-traffic run: a catalog of
    distinct structured images drawn with Zipf popularity
    p(k) ∝ 1/k^s, plus (with probability ``perturb``) a resize-
    perturbed re-encode of the drawn image — same content at a nearby
    resolution, so it misses the exact cache arm and exercises the
    near-dup arm.  All draws are seeded: two runs with the same seed
    offer the SAME request stream."""
    from PIL import Image

    s, catalog = float(zipf[0]), int(zipf[1])
    if catalog < 1:
        raise ValueError(f"zipf catalog must be >= 1, got {catalog}")
    if s < 0:
        raise ValueError(f"zipf exponent must be >= 0, got {s}")
    if not 0.0 <= float(perturb) <= 1.0:
        raise ValueError(f"perturb must be in [0, 1], got {perturb}")
    imgs = []
    for k in range(catalog):
        h, w = sizes[k % len(sizes)]
        imgs.append(structured_image(rng, h, w))
    bodies = [_encode_arr(a) for a in imgs]
    variants: Dict[int, List[bytes]] = {}
    if perturb > 0:
        # Pre-encode the perturbed variants up front — the hot loop
        # must never bottleneck on PIL while it is offering load.
        for k, a in enumerate(imgs):
            h, w = a.shape[:2]
            variants[k] = [
                _encode_arr(np.asarray(Image.fromarray(a).resize(
                    (max(int(w * f), 8), max(int(h * f), 8)),
                    Image.BILINEAR)))
                for f in (0.875, 1.125)]
    p = 1.0 / np.arange(1, catalog + 1, dtype=np.float64) ** s
    p /= p.sum()
    ks = rng.choice(catalog, size=n_total, p=p)
    flips = rng.random_sample(n_total) < float(perturb)
    out: List[bytes] = []
    for i in range(n_total):
        k = int(ks[i])
        if flips[i] and variants:
            out.append(variants[k][int(rng.randint(len(variants[k])))])
        else:
            out.append(bodies[k])
    return out


def wait_ready(base_url: str, timeout_s: float = 60.0,
               poll_s: float = 0.25) -> bool:
    """Poll /healthz until it answers 200 (engine warmed and serving)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(base_url + "/healthz",
                                        timeout=5.0) as r:
                if r.status == 200:
                    return True
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(poll_s)
    return False


def _one(base_url: str, body: bytes, slo_ms: Optional[float],
         timeout_s: float, precision: Optional[str] = None,
         model: Optional[str] = None, tenant: Optional[str] = None,
         request_id: Optional[str] = None,
         stream: Optional[str] = None
         ) -> Tuple[str, float, Dict[str, Optional[str]]]:
    """One /predict round-trip → (outcome, latency_ms, info).
    Outcomes: ok | shed | expired | unhealthy | error | transport —
    ``transport`` is a connection-level failure (refused, reset,
    timeout, short body) as opposed to an HTTP-status ``error``; the
    split is what makes failover/chaos experiments readable (a killed
    replica produces transports, a sick one produces 5xx errors).
    ``info`` holds the response's X-Precision / X-Model headers (what
    the server actually SERVED — the ladder may adjust the arm, the
    router names the model), None values on non-200s, plus the echoed
    X-Request-ID (``rid``) and raw X-Timing (``timing`` — the
    server-side stage split; docs/OBSERVABILITY.md).
    ``model``/``tenant`` ride as X-Model / X-Tenant request headers
    (fleet routing + tenancy); ``request_id`` rides as X-Request-ID so
    the client's latency record and the server's trace share an id."""
    headers = {"Content-Type": "application/x-npy"}
    if slo_ms:
        headers["X-SLO-MS"] = str(slo_ms)
    if precision:
        headers["X-Precision"] = str(precision)
    if model:
        headers["X-Model"] = str(model)
    if tenant:
        headers["X-Tenant"] = str(tenant)
    if request_id:
        headers["X-Request-ID"] = str(request_id)
    if stream:
        # Per-stream session key (serve/streams.py): frames of one
        # stream share it, so the router opens a session, pins the
        # stream to a replica, and may serve the reuse fast path.
        headers["X-Stream-ID"] = str(stream)
    req = urllib.request.Request(base_url + "/predict", data=body,
                                 headers=headers, method="POST")
    t0 = time.monotonic()
    info: Dict[str, Optional[str]] = {"arm": None, "model": None,
                                      "rid": None, "timing": None,
                                      "cache": None, "reuse": None}
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            r.read()
            out = "ok" if r.status == 200 else "error"
            if out == "ok":
                info["arm"] = r.headers.get("X-Precision")
                info["model"] = r.headers.get("X-Model")
                info["rid"] = r.headers.get("X-Request-ID")
                info["timing"] = r.headers.get("X-Timing")
                # exact | near | coalesced on a router-cache hit,
                # absent on a real forward (serve/cache.py).
                info["cache"] = r.headers.get("X-Cache")
                # "1" on a temporal-coherence replay (serve/streams.py),
                # absent on a full forward — the streaming summary
                # splits its latency curves on this.
                info["reuse"] = r.headers.get("X-Stream-Reuse")
    except urllib.error.HTTPError as e:
        e.read()
        out = {429: "shed", 504: "expired", 503: "unhealthy"}.get(
            e.code, "error")
    except (urllib.error.URLError, OSError, http.client.HTTPException):
        # Connection-level death (incl. IncompleteRead on a mid-body
        # reset): counted apart from HTTP-status errors.
        out = "transport"
    return out, (time.monotonic() - t0) * 1000.0, info


def _percentile(sorted_ms: List[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    i = min(int(p * len(sorted_ms)), len(sorted_ms) - 1)
    return sorted_ms[i]


def _normalize_mix(mix) -> List[Dict]:
    """Mixed-traffic spec → ``[{"model", "tenant", "weight"}, ...]``.
    Accepts dicts (``model`` required, ``tenant``/``weight`` optional)
    or ``(model, weight)`` tuples."""
    out = []
    for entry in mix:
        if isinstance(entry, dict):
            e = {"model": entry.get("model"),
                 "tenant": entry.get("tenant"),
                 "weight": float(entry.get("weight", 1.0))}
        else:
            model, weight = entry
            e = {"model": model, "tenant": None, "weight": float(weight)}
        if not e["model"]:
            raise ValueError(f"mix entry {entry!r} needs a model")
        if e["weight"] <= 0:
            raise ValueError(f"mix entry {entry!r} needs weight > 0")
        out.append(e)
    if not out:
        raise ValueError("mix must not be empty")
    return out


def _profile_offsets(rps: float, duration_s: float,
                     ramp: Optional[Tuple[float, float, float]],
                     bursts) -> Tuple[List[float], float]:
    """Arrival offsets (seconds from t0) for a shaped open-loop run →
    ``(offsets, duration)``.  ``ramp=(r0, r1, T)`` sweeps the base rate
    linearly from r0 to r1 over T seconds (holding r1 after); with no
    ramp the base rate is flat ``rps``.  Each ``(extra, start, dur)``
    burst adds ``extra`` rps inside its window on top of the base.  The
    run covers ``max(duration_s, T, last burst end)`` so a ramp or a
    late burst is never truncated by the default duration.  Offsets
    come from integrating rate(t) in 5 ms slices and emitting an
    arrival per accumulated unit — exact arrival COUNT under any shape
    (a 1/rate(t) stepper overshoots wildly when a ramp starts near
    zero), with arrival times quantized to the slice, which is noise
    next to network jitter at any rate worth sweeping."""
    bursts = tuple(bursts or ())
    dur = float(duration_s)
    if ramp is not None:
        dur = max(dur, float(ramp[2]))
    for _extra, b0, bdur in bursts:
        dur = max(dur, float(b0) + float(bdur))

    def rate(t: float) -> float:
        if ramp is not None:
            r0, r1, T = ramp
            r = (float(r1) if T <= 0
                 else float(r0) + (float(r1) - float(r0)) * min(t / T, 1.0))
        else:
            r = float(rps)
        for extra, b0, bdur in bursts:
            if float(b0) <= t < float(b0) + float(bdur):
                r += float(extra)
        return r

    offsets: List[float] = []
    t, credit, dt = 0.0, 0.0, 0.005
    while t < dur:
        credit += rate(t) * dt
        while credit >= 1.0:
            offsets.append(t)
            credit -= 1.0
        t += dt
    return (offsets or [0.0]), dur


def run_loadgen(
    base_url: str,
    mode: str = "closed",
    concurrency: int = 4,
    requests: int = 50,
    rps: float = 10.0,
    duration_s: float = 5.0,
    sizes: Tuple[Tuple[int, int], ...] = ((320, 320),),
    seed: int = 0,
    slo_ms: float = 0.0,
    timeout_s: float = 60.0,
    precision: Optional[str] = None,
    model: Optional[str] = None,
    tenant: Optional[str] = None,
    mix=None,
    slowest: int = 0,
    quality: bool = False,
    slo: bool = False,
    ramp: Optional[Tuple[float, float, float]] = None,
    bursts=None,
    zipf: Optional[Tuple[float, int]] = None,
    perturb: float = 0.0,
) -> Dict[str, float]:
    """Drive ``base_url`` and return a summary dict (see module doc for
    the open/closed semantics).  Closed loop sends exactly ``requests``
    total across ``concurrency`` workers; open loop offers ``rps`` for
    ``duration_s``.  ``precision`` rides every request as X-Precision;
    ``model``/``tenant`` ride as X-Model / X-Tenant (fleet routing).

    **Mixed traffic** (``mix``): a weighted list of
    ``{"model", "tenant", "weight"}`` entries — each request draws its
    (model, tenant) from the mix (deterministic under ``seed``), so ONE
    loadgen run produces the fleet's mixed-model curve.  Latency
    percentiles are exact over OK responses (client-side e2e, incl.
    HTTP); the summary additionally breaks p50/p95/p99 down per SERVED
    arm (X-Precision) and per SERVED model (X-Model — the router echo),
    mirroring the per-arm breakdown, so the mixed-model
    throughput-vs-p99 curve is one command.

    ``quality=True``: the summary ends with one /metrics scrape of the
    per-model shadow-disagreement and drift gauges
    (:func:`scrape_quality`) under ``"quality"`` — a chaos or agenda
    leg records model quality alongside its latency curve from the
    same command.  Omitted when the endpoint exports none (monitors
    off).

    ``slo=True``: the summary ends with one /slo scrape
    (:func:`scrape_slo`) under ``"slo"`` — per-objective (per-model/
    per-tenant scoped) budget-remaining and fast/slow burn rates next
    to the latency summary, the PR-10 ``--quality`` pattern for the
    error-budget surface.  Omitted when the endpoint has no objectives
    (knob off).

    ``slowest > 0``: every request carries a generated ``X-Request-ID``
    and the summary reports the N slowest OK responses with their
    request/trace ids and the SERVER-side stage breakdown parsed from
    ``X-Timing`` (queue/device/resize/e2e ms) — "which requests were
    slow and WHERE" without a server round trip; when a row's trace
    was sampled, its id keys straight into /debug/traces.

    **Shaped load** (open mode only): ``ramp=(r0, r1, seconds)`` sweeps
    the offered rate linearly from r0 to r1 rps over the window;
    ``bursts=[(extra_rps, start_s, dur_s), ...]`` adds step bursts on
    top of the base rate.  Shaped runs append a ``"curve"`` — per
    time-bucket offered/done/ok counts and p99 next to the overall
    latency summary — the response curve an autoscaler leg reads to see
    the controller catch up with (or shed) a moving offered rate, and
    ``offered_rps`` becomes the profile's true average.

    **Duplicate traffic** (``zipf=(s, catalog)``): payloads draw from
    a catalog of distinct structured images with Zipf popularity
    p(k) ∝ 1/k^s instead of cycling the body pool; ``perturb`` sends
    that fraction of draws as resize-perturbed re-encodes (near-dup
    arm fodder).  The summary gains ``"cache"`` — hit count/rate and
    per-kind (exact/near/coalesced) split from the X-Cache response
    header — and ``"terminals"``, the client-observed mirror of the
    router book's five terminal classes (docs/SERVING.md "Router
    cache")."""
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be open|closed, got {mode!r}")
    if mode == "closed" and (ramp is not None or bursts):
        raise ValueError("ramp/bursts are open-loop shapes (mode='open')")
    rng = np.random.RandomState(seed)
    # Pre-encode a body pool: the generator must never bottleneck on
    # numpy/npy encoding while it is supposed to be offering load.
    pool = [encode_image(rng, h, w)
            for h, w in (sizes * ((16 // max(len(sizes), 1)) + 1))[:16]]
    offsets: Optional[List[float]] = None
    profile_dur = float(duration_s)
    if mode == "open" and (ramp is not None or bursts):
        offsets, profile_dur = _profile_offsets(rps, duration_s, ramp,
                                                bursts)
        n_total = len(offsets)
    else:
        n_total = (int(requests) if mode == "closed"
                   else max(int(float(duration_s) * float(rps)), 1))
    if perturb and zipf is None:
        raise ValueError("perturb > 0 needs zipf duplicate traffic")
    body_of: Optional[List[bytes]] = None
    if zipf is not None:
        body_of = _zipf_bodies(rng, zipf, perturb, sizes, n_total)
    if mix is not None:
        entries = _normalize_mix(mix)
        w = np.asarray([e["weight"] for e in entries], np.float64)
        draws = rng.choice(len(entries), size=n_total, p=w / w.sum())
        assignment = [entries[int(j)] for j in draws]
    else:
        assignment = [{"model": model, "tenant": tenant}] * n_total
    lock = threading.Lock()
    outcomes: Dict[str, int] = {"ok": 0, "shed": 0, "expired": 0,
                                "unhealthy": 0, "error": 0,
                                "transport": 0}
    ok_ms: List[float] = []
    # OK responses per cache disposition ("forward" = no X-Cache
    # header, i.e. a real engine forward), plus hit-path latencies so
    # the summary can put the hit p50 next to the forward p50.
    cache_kinds: Dict[str, int] = {}
    cache_hit_ms: List[float] = []
    arm_ms: Dict[str, List[float]] = {}
    model_ms: Dict[str, List[float]] = {}
    model_sent: Dict[str, int] = {}
    # Failures per ASSIGNED model (the response names no model on a
    # failed request): the per-model half of a failover/chaos read.
    # "unhealthy" (503 — a dead replica set) belongs here too, or a
    # killed single-replica model's failures vanish from its row.
    _MODEL_FAIL_OUTCOMES = ("error", "transport", "unhealthy")
    model_fail: Dict[Tuple[str, str], int] = {}
    # slowest-N tracking: a min-heap bounded at N, so a long soak holds
    # N rows, not one per OK response.  Entries are (ms, seq, info);
    # seq breaks latency ties (dicts don't compare).
    slow_rows: List[Tuple[float, int, Dict]] = []
    slow_seq = [0]
    # Response-curve buckets for shaped runs: each request books into
    # the bucket of its SCHEDULED offset (offered time, not completion
    # time), so a bucket's offered count is exact even when responses
    # straggle past its edge.
    curve: Optional[List[Dict]] = None
    bucket_of: List[int] = []
    if offsets is not None:
        n_buckets = min(8, max(1, int(profile_dur)))
        width = profile_dur / n_buckets
        curve = [{"t0": round(k * width, 2),
                  "t1": round((k + 1) * width, 2),
                  "offered": 0, "done": 0, "ok": 0, "_ms": []}
                 for k in range(n_buckets)]
        for off in offsets:
            k = min(int(off / width), n_buckets - 1)
            bucket_of.append(k)
            curve[k]["offered"] += 1

    def record(out: str, ms: float, info=None, sent_model=None) -> None:
        info = info or {}
        with lock:
            outcomes[out] += 1
            if out == "ok":
                ok_ms.append(ms)
                ck = info.get("cache") or "forward"
                cache_kinds[ck] = cache_kinds.get(ck, 0) + 1
                if ck != "forward":
                    cache_hit_ms.append(ms)
                if info.get("arm"):
                    arm_ms.setdefault(info["arm"], []).append(ms)
                if info.get("model"):
                    model_ms.setdefault(info["model"], []).append(ms)
                if slowest > 0:
                    slow_seq[0] += 1
                    row = (ms, slow_seq[0], info)
                    if len(slow_rows) < slowest:
                        heapq.heappush(slow_rows, row)
                    elif ms > slow_rows[0][0]:
                        heapq.heapreplace(slow_rows, row)
            elif out in _MODEL_FAIL_OUTCOMES and sent_model:
                key = (sent_model, out)
                model_fail[key] = model_fail.get(key, 0) + 1

    def fire(i: int) -> None:
        a = assignment[i]
        if a["model"]:
            with lock:
                model_sent[a["model"]] = model_sent.get(a["model"], 0) + 1
        # A request id per request (the X-Request-ID header) so the
        # slowest-N rows key into the server's /debug/traces; ids do
        # not perturb the seeded (model, tenant) draws above.
        rid = mint_trace_id() if slowest > 0 else None
        body = body_of[i] if body_of is not None else pool[i % len(pool)]
        res = _one(base_url, body, slo_ms or None,
                   timeout_s, precision=precision, model=a["model"],
                   tenant=a.get("tenant") or tenant, request_id=rid)
        record(*res, sent_model=a["model"])
        if curve is not None:
            b = curve[bucket_of[i]]
            with lock:
                b["done"] += 1
                if res[0] == "ok":
                    b["ok"] += 1
                    b["_ms"].append(res[1])

    t_start = time.monotonic()
    if mode == "closed":
        remaining = [n_total]

        def worker() -> None:
            while True:
                with lock:
                    if remaining[0] <= 0:
                        return
                    remaining[0] -= 1
                    i = n_total - remaining[0] - 1
                fire(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(int(concurrency), 1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sent = n_total
    else:
        # Fixed worker pool, not thread-per-request: at a few hundred
        # rps the spawn cost inflates the very p99 the sweep measures,
        # and thread exhaustion kills the leg.  The pool bounds
        # client-side concurrency; a scheduled arrival that finds every
        # worker blocked queues in the executor and its lateness shows
        # up in latency — the open-loop signal, not a generator stall.
        from concurrent.futures import ThreadPoolExecutor

        if offsets is None:
            interval = 1.0 / max(float(rps), 1e-6)
            offsets = [i * interval for i in range(n_total)]
            peak_rps = float(rps)
        else:
            # Size the pool for the PEAK of the shaped profile, not the
            # flat rps knob — a burst that outruns the pool would queue
            # in the generator and smear the very step it measures.
            peak_rps = ((max(float(ramp[0]), float(ramp[1]))
                         if ramp is not None else float(rps))
                        + max((float(b[0]) for b in (bursts or ())),
                              default=0.0))
        workers = min(256, max(8, int(peak_rps * min(timeout_s, 10.0))))
        futures = []
        with ThreadPoolExecutor(max_workers=workers) as ex:
            for i, off in enumerate(offsets):
                delay = (t_start + off) - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                futures.append(ex.submit(fire, i))
            for f in futures:
                f.result()
        sent = n_total
    elapsed = time.monotonic() - t_start

    ok_ms.sort()
    done = sum(outcomes.values())
    out = {
        "mode": mode,
        "sent": sent,
        "done": done,
        "elapsed_s": round(elapsed, 3),
        "throughput_rps": round(outcomes["ok"] / elapsed, 2) if elapsed
        else 0.0,
        "p50_ms": round(_percentile(ok_ms, 0.50), 2),
        "p95_ms": round(_percentile(ok_ms, 0.95), 2),
        "p99_ms": round(_percentile(ok_ms, 0.99), 2),
        "mean_ms": round(sum(ok_ms) / len(ok_ms), 2) if ok_ms else 0.0,
        **outcomes,
    }
    if precision:
        out["precision"] = precision
    if model:
        out["model"] = model
    if tenant:
        out["tenant"] = tenant
    if mix is not None:
        out["mix"] = [{k: v for k, v in e.items() if v is not None}
                      for e in _normalize_mix(mix)]
    hits = sum(v for k, v in cache_kinds.items() if k != "forward")
    if zipf is not None or hits:
        # Cache disposition of the OK responses (X-Cache header) plus
        # the client-observed mirror of the router book's terminal
        # classes — served+shed+expired+errors+cache_hit is the
        # identity /stats asserts server-side (docs/SERVING.md).
        if zipf is not None:
            out["zipf"] = {"s": float(zipf[0]), "catalog": int(zipf[1]),
                           "perturb": round(float(perturb), 4)}
        cache_hit_ms.sort()
        out["cache"] = {
            "hits": hits,
            "hit_rate": (round(hits / outcomes["ok"], 4)
                         if outcomes["ok"] else 0.0),
            "hit_p50_ms": round(_percentile(cache_hit_ms, 0.50), 2),
            "hit_p99_ms": round(_percentile(cache_hit_ms, 0.99), 2),
            "kinds": {k: cache_kinds[k] for k in sorted(cache_kinds)},
        }
        out["terminals"] = {
            "served": outcomes["ok"] - hits,
            "cache_hit": hits,
            "shed": outcomes["shed"],
            "expired": outcomes["expired"],
            "errors": (outcomes["error"] + outcomes["unhealthy"]
                       + outcomes["transport"]),
        }
    if arm_ms:
        # Per-SERVED-arm latency breakdown: under the degraded ladder a
        # single offered arm can come back as several served arms, and
        # the curve per arm is the number the r8 agenda sweeps.
        out["arms"] = {}
        for arm in sorted(arm_ms):
            ms = sorted(arm_ms[arm])
            out["arms"][arm] = {
                "ok": len(ms),
                "p50_ms": round(_percentile(ms, 0.50), 2),
                "p95_ms": round(_percentile(ms, 0.95), 2),
                "p99_ms": round(_percentile(ms, 0.99), 2),
            }
    if model_ms or model_sent:
        # Per-SERVED-model latency breakdown (the response's X-Model —
        # the router's echo), mirroring the per-arm breakdown: under a
        # mixed-model run this is the per-model half of the fleet's
        # throughput-vs-p99 curve, from ONE command.
        out["models"] = {}
        for name in sorted(set(model_ms) | set(model_sent)):
            ms = sorted(model_ms.get(name, []))
            out["models"][name] = {
                "sent": model_sent.get(name, 0),
                "ok": len(ms),
                "error": model_fail.get((name, "error"), 0),
                "transport": model_fail.get((name, "transport"), 0),
                "unhealthy": model_fail.get((name, "unhealthy"), 0),
                "p50_ms": round(_percentile(ms, 0.50), 2),
                "p95_ms": round(_percentile(ms, 0.95), 2),
                "p99_ms": round(_percentile(ms, 0.99), 2),
            }
    if slowest > 0 and slow_rows:
        # The N slowest OK responses, server-side stage split attached:
        # client e2e minus the X-Timing e2e is the network + front-door
        # share, and a sampled row's trace id keys into /debug/traces.
        slow_rows.sort(key=lambda e: -e[0])
        rows = []
        for ms, _seq, info in slow_rows[:slowest]:
            trace_id, stages = parse_timing(info.get("timing"))
            rows.append({
                "ms": round(ms, 2),
                "request_id": info.get("rid"),
                "trace": trace_id,  # None = not sampled server-side
                "model": info.get("model"),
                "arm": info.get("arm"),
                "stages": {k: round(v, 3) for k, v in stages.items()},
            })
        out["slowest"] = rows
    if mode == "open":
        if curve is not None:
            out["offered_rps"] = (round(n_total / profile_dur, 2)
                                  if profile_dur else 0.0)
            rendered = []
            for b in curve:
                ms = sorted(b.pop("_ms"))
                b["p99_ms"] = round(_percentile(ms, 0.99), 2)
                rendered.append(b)
            # The response curve: offered vs completed vs ok per time
            # bucket with the bucket's p99 — "did the fleet keep up as
            # the rate moved", readable without replaying the run.
            out["curve"] = rendered
        else:
            out["offered_rps"] = round(float(rps), 2)
    if quality:
        q = scrape_quality(base_url)
        if q:
            out["quality"] = q
    if slo:
        s = scrape_slo(base_url)
        if s:
            out["slo"] = s
    return out


def stream_frames(rng: np.random.RandomState, h: int, w: int,
                  n_frames: int, perturb: float = 0.0) -> List[bytes]:
    """A temporally-coherent pre-encoded frame train for ONE stream:
    frame i+1 is frame i's scene under a small uniform brightness
    jitter (bytes differ, the perceptual hash barely moves — the
    workload the temporal-coherence fast path is built for), and with
    probability ``perturb`` a SCENE CUT replaces the base image (a cut
    must miss the reuse gate and force a full forward).  Fully seeded:
    the same (seed, h, w, n, perturb) always yields the same bytes —
    the determinism tests/test_streams.py asserts."""
    if not 0.0 <= float(perturb) <= 1.0:
        raise ValueError(f"perturb must be in [0, 1], got {perturb}")
    frames: List[bytes] = []
    base = structured_image(rng, h, w).astype(np.int16)
    for i in range(int(n_frames)):
        if i > 0 and perturb > 0 \
                and rng.random_sample() < float(perturb):
            base = structured_image(rng, h, w).astype(np.int16)
        arr = np.clip(base + int(rng.randint(-2, 3)), 0, 255)
        frames.append(_encode_arr(arr.astype(np.uint8)))
    return frames


def run_stream_loadgen(
    base_url: str,
    streams: int = 4,
    fps: float = 10.0,
    duration_s: float = 5.0,
    sizes: Tuple[Tuple[int, int], ...] = ((320, 320),),
    seed: int = 0,
    perturb: float = 0.0,
    slo_ms: float = 0.0,
    timeout_s: float = 60.0,
    precision: Optional[str] = None,
    model: Optional[str] = None,
    tenant: Optional[str] = None,
) -> Dict:
    """Streaming-video mode (docs/SERVING.md "Streaming"): ``streams``
    concurrent clients, each pushing a temporally-coherent frame train
    at a fixed ``fps`` under its own ``X-Stream-ID``.  Frames within a
    stream are SEQUENTIAL (a video client never races its own frames):
    each client sends frame i at its scheduled instant ``t0 + i/fps``,
    waits for the answer, and sleeps until the next slot — a late
    answer makes the next frame fire immediately, which is exactly the
    freshness pressure a real stream applies.

    ``perturb`` is the per-frame SCENE-CUT probability (a cut forces a
    full forward past the reuse gate); between cuts frames carry only
    a small brightness jitter, the reuse-arm fodder.  Deterministic
    under ``seed``: payload bytes and schedule are identical across
    runs (latencies, of course, are not).

    The summary reports the streaming triple the r19 agenda records:
    **per-stream p99** (each stream's own tail, plus the fleet-worst
    under ``per_stream_p99_ms``), **inter-frame jitter** (stddev of
    completion-to-completion intervals per stream, ms), and **reuse
    rate** (X-Stream-Reuse answers / OK), with the reuse-vs-forward
    p50 split alongside."""
    if int(streams) < 1:
        raise ValueError(f"streams must be >= 1, got {streams}")
    if float(fps) <= 0:
        raise ValueError(f"fps must be > 0, got {fps}")
    n_frames = max(int(float(duration_s) * float(fps)), 1)
    interval = 1.0 / float(fps)
    specs = []
    for si in range(int(streams)):
        srng = np.random.RandomState((int(seed) * 9973 + si) % (2**31))
        h, w = sizes[si % len(sizes)]
        specs.append({
            "sid": f"lg{int(seed)}-{si}",
            "frames": stream_frames(srng, h, w, n_frames, perturb)})
    lock = threading.Lock()
    outcomes: Dict[str, int] = {"ok": 0, "shed": 0, "expired": 0,
                                "unhealthy": 0, "error": 0,
                                "transport": 0}
    reuse_ms: List[float] = []
    fwd_ms: List[float] = []
    rows: List[Dict] = []

    def client(spec: Dict) -> None:
        lats: List[float] = []
        done_t: List[float] = []
        reused = 0
        t0 = time.monotonic()
        for i, body in enumerate(spec["frames"]):
            delay = (t0 + i * interval) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            out, ms, info = _one(base_url, body, slo_ms or None,
                                 timeout_s, precision=precision,
                                 model=model, tenant=tenant,
                                 stream=spec["sid"])
            with lock:
                outcomes[out] += 1
                if out == "ok":
                    if info.get("reuse") == "1":
                        reused += 1
                        reuse_ms.append(ms)
                    else:
                        fwd_ms.append(ms)
            if out == "ok":
                lats.append(ms)
                done_t.append(time.monotonic())
        lats.sort()
        gaps = [(done_t[k] - done_t[k - 1]) * 1000.0
                for k in range(1, len(done_t))]
        jitter = float(np.std(gaps)) if len(gaps) >= 2 else 0.0
        with lock:
            rows.append({
                "stream": spec["sid"],
                "sent": len(spec["frames"]),
                "ok": len(lats),
                "reused": reused,
                "reuse_rate": (round(reused / len(lats), 4)
                               if lats else 0.0),
                "p50_ms": round(_percentile(lats, 0.50), 2),
                "p99_ms": round(_percentile(lats, 0.99), 2),
                "jitter_ms": round(jitter, 2),
            })

    t_start = time.monotonic()
    threads = [threading.Thread(target=client, args=(s,), daemon=True)
               for s in specs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t_start
    all_ms = sorted(reuse_ms + fwd_ms)
    reuse_ms.sort()
    fwd_ms.sort()
    rows.sort(key=lambda r: r["stream"])
    hits = len(reuse_ms)
    return {
        "mode": "streaming",
        "streams": int(streams),
        "fps": float(fps),
        "frames_per_stream": n_frames,
        "perturb": round(float(perturb), 4),
        "sent": int(streams) * n_frames,
        "done": sum(outcomes.values()),
        "elapsed_s": round(elapsed, 3),
        "p50_ms": round(_percentile(all_ms, 0.50), 2),
        "p95_ms": round(_percentile(all_ms, 0.95), 2),
        "p99_ms": round(_percentile(all_ms, 0.99), 2),
        "mean_ms": (round(sum(all_ms) / len(all_ms), 2)
                    if all_ms else 0.0),
        **outcomes,
        "reuse": {
            "hits": hits,
            "rate": (round(hits / outcomes["ok"], 4)
                     if outcomes["ok"] else 0.0),
            "reuse_p50_ms": round(_percentile(reuse_ms, 0.50), 2),
            "forward_p50_ms": round(_percentile(fwd_ms, 0.50), 2),
        },
        "per_stream": rows,
        "per_stream_p99_ms": (max(r["p99_ms"] for r in rows)
                              if rows else 0.0),
        "jitter_ms": (round(sum(r["jitter_ms"] for r in rows)
                            / len(rows), 2) if rows else 0.0),
    }


def fetch_stats(base_url: str, timeout_s: float = 10.0) -> Dict[str, float]:
    with urllib.request.urlopen(base_url + "/stats", timeout=timeout_s) as r:
        return json.loads(r.read().decode())


def scrape_slo(base_url: str, timeout_s: float = 10.0) -> Dict:
    """End-of-run /slo scrape, condensed per objective (the objective's
    scope IS the per-model/per-tenant key — the router tracks one book,
    so unlike the quality gauges there are no replica-labeled series to
    disambiguate):

        {name: {"scope", "kind", "budget_remaining",
                "burn_fast", "burn_slow", "good", "bad", "active"}}

    Empty when the endpoint is unreachable or exports no objectives —
    an agenda leg records error-budget state exactly when there is an
    SLO to record."""
    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/slo",
                                    timeout=timeout_s) as r:
            snap = json.loads(r.read().decode())
    except (urllib.error.URLError, OSError, ValueError):
        return {}
    active = set(snap.get("active", []))
    out = {}
    for o in snap.get("objectives", []):
        burns = o.get("burn_rate", {})
        out[o["name"]] = {
            "scope": o.get("scope"),
            "kind": o.get("kind"),
            "budget_remaining": o.get("budget_remaining"),
            "burn_fast": burns.get("fast"),
            "burn_slow": burns.get("slow"),
            "good": o.get("good"),
            "bad": o.get("bad"),
            # Exact rule-name membership (utils/slo.py names them
            # slo_<name>_burn / slo_<name>_budget): a prefix match
            # would cross-attribute when one objective's name prefixes
            # another's.
            "active": sorted(active & {f"slo_{o['name']}_burn",
                                       f"slo_{o['name']}_budget"}),
        }
    return out


# Quality gauges worth carrying into a load summary (serve/quality.py;
# docs/OBSERVABILITY.md "Model health").
_QUALITY_FAMILIES = ("dsod_quality_psi", "dsod_quality_shadow_mae_avg",
                     "dsod_quality_shadow_flip_avg",
                     "dsod_quality_shadow_total",
                     "dsod_quality_shadow_dropped_total",
                     "dsod_quality_scored_total")


def _parse_labels(frag: str) -> Dict[str, str]:
    """Label fragment → dict.  Split-on-comma is sufficient for the
    quality families: every label value here (model/arm/signal/replica
    names) comes from validated identifier-like config fields — none
    may contain a comma or an escaped quote."""
    out = {}
    for part in frag.split(","):
        k, sep, v = part.partition("=")
        if sep:
            out[k.strip()] = v.strip().strip('"')
    return out


def scrape_quality(base_url: str, timeout_s: float = 10.0) -> Dict:
    """End-of-run /metrics scrape of the model-health quality gauges,
    grouped per model label (the single-engine server exports no
    ``model=`` label — those series land under ``""``; a multi-member
    replica set's series carry ``replica=`` and land under
    ``model[replica]`` so replicas never overwrite each other):

        {model: {"psi": {signal: v}, "shadow": {arm: {...}},
                 "scored": n, "shadow_dropped": n}}

    Empty when the endpoint is unreachable or the quality monitors are
    off — a chaos/agenda leg records quality alongside latency exactly
    when there is quality telemetry to record."""
    from ..utils.observability import parse_prom_text

    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/metrics",
                                    timeout=timeout_s) as r:
            text = r.read().decode()
    except (urllib.error.URLError, OSError):
        return {}
    out: Dict[str, Dict] = {}

    def model_entry(labels):
        key = labels.get("model", "")
        if "replica" in labels:
            key = f'{key}[{labels["replica"]}]'
        return out.setdefault(key, {})

    samples = []
    for fam_name, _typ, fam_samples in parse_prom_text(text):
        if fam_name in _QUALITY_FAMILIES:
            samples.extend(fam_samples)
    for line in samples:
        head, _, rest = line.partition(" ")
        name, _, frag = head.partition("{")
        labels = _parse_labels(frag.rstrip("}"))
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        entry = model_entry(labels)
        if name == "dsod_quality_psi":
            entry.setdefault("psi", {})[labels.get("signal", "")] = value
        elif name == "dsod_quality_scored_total":
            entry["scored"] = value
        elif name == "dsod_quality_shadow_dropped_total":
            entry["shadow_dropped"] = value
        else:
            arm = labels.get("arm", "")
            key = {"dsod_quality_shadow_mae_avg": "mae_avg",
                   "dsod_quality_shadow_flip_avg": "flip_avg",
                   "dsod_quality_shadow_total": "n"}[name]
            entry.setdefault("shadow", {}).setdefault(arm, {})[key] = value
    return {m: v for m, v in out.items() if v}
