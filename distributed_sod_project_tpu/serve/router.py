"""Router tier for the multi-model serving fleet (docs/SERVING.md
"Fleet"): model-aware routing, multi-tenant admission, and the fleet
HTTP front end.

The routing/tenancy POLICY lives here (``TokenBucket``,
``TenantAdmission``, ``RouterStats``); the fleet ASSEMBLY — backends,
the interleaved dispatch loop, metric aggregation — lives in
``serve/fleet.py``.  The philosophy extends PR 5's admission story one
tier up: the cheapest place to reject work the fleet cannot (or will
not) do is the router door, BEFORE a request ever reaches an engine
queue — an exhausted tenant budget costs one token-bucket read, not an
engine slot.

Request contract (``POST /predict``):

- ``X-Model: <name>`` (or a ``model=`` query field) names the replica
  set.  Unknown → 404, and the request never touches a counter — a
  typo'd model name must not pollute the fleet accounting.  The served
  model is echoed back as ``X-Model``.
- ``X-Tenant: <name>`` names the tenant class (``default_tenant`` when
  absent; unknown tenants ride the default class unless
  ``strict_tenants``, then 403 uncounted).  The tenant's token-bucket
  budget and priority class are enforced here: budget exhaustion and
  priority shed answer 429 (``kind: tenant_budget | priority_shed``)
  with the engine queues untouched.
- Everything after admission is the single-engine contract verbatim
  (``serve/server.py::run_predict``) — same headers, same status
  mapping, bitwise-identical responses.

Fleet-wide accounting identity (the PR-5 invariant, one tier up):

    served + shed + expired + errors == submitted

where ``submitted`` counts every routed-and-tenant-resolved request at
the router door and every other term is computed from the ROUTER'S OWN
terminal book: each counted submission ends in exactly one
``inc_shed`` or ``inc_response`` call, whatever mix of retries,
hedges, failovers, or replica deaths the request lived through.  The
engines' local books remain exposed per replica (each one's own
identity holds over the attempts it saw), but the fleet identity no
longer depends on scraping them — a SIGKILLed replica cannot lose the
fleet history (serve/fleet.py ``Fleet.stats`` classifies the
outcomes).

Failure semantics (docs/SERVING.md "Failure semantics"): transport
failures and remote 5xx re-dispatch to the next healthy replica under
the per-replica circuit breaker, retries are charged against the
residual ``X-SLO-MS`` (the router forwards the RESIDUAL budget, never
the original, on every attempt), and an optional tail-latency hedge
races a second replica at the observed p95 — first answer wins, the
loser is abandoned and its breaker outcome still recorded
(serve/failover.py owns the policy math).
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple

from ..configs.base import FleetTenantConfig
from ..utils.logging import get_logger
from .failover import pick_hedge_delay
from .server import (JsonHTTPHandler, ThreadingHTTPServer, _query_int,
                     publish_port, read_predict_body, resolve_request_id,
                     run_predict)
from .streams import sanitize_stream_id


class TokenBucket:
    """Thread-safe token bucket: ``rate_per_s`` sustained, ``burst``
    capacity.  ``clock`` is injectable for deterministic tests."""

    def __init__(self, rate_per_s: float, burst: float = 0.0,
                 clock=time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst > 0 else self.rate
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available (refilling lazily); False
        when the budget is exhausted."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class TenantAdmission:
    """Resolve a request's tenant class and enforce its budget +
    priority BEFORE the engine queue.

    Budgets: each tenant with ``rate_rps > 0`` owns a
    :class:`TokenBucket`; an exhausted bucket sheds at the router.

    Priorities: the distinct configured priorities form shed classes —
    a class of rank ``r`` (0 = lowest) among ``n`` classes may use the
    target replica's queue only while its depth is below
    ``(r+1)/n * max_queue``.  The top class never priority-sheds (the
    engine's own bound is its limit), so with a single class the
    mechanism is inert.  Burst-proof by construction: under a one-hot
    overload the low classes lose admission first, which is the
    documented contract, not an emergent accident.
    """

    def __init__(self, tenants: Tuple[FleetTenantConfig, ...],
                 default_tenant: str = "default",
                 strict: bool = False, clock=time.monotonic):
        tenants = tuple(tenants)
        if default_tenant not in {t.name for t in tenants}:
            low = min((t.priority for t in tenants), default=0)
            tenants += (FleetTenantConfig(name=default_tenant,
                                          priority=low),)
        self.tenants: Dict[str, FleetTenantConfig] = {
            t.name: t for t in tenants}
        self.default_tenant = default_tenant
        self.strict = strict
        self._buckets: Dict[str, Optional[TokenBucket]] = {
            t.name: (TokenBucket(t.rate_rps, t.burst, clock=clock)
                     if t.rate_rps > 0 else None)
            for t in tenants}
        classes = sorted({t.priority for t in tenants})
        n = len(classes)
        self._frac = {p: (classes.index(p) + 1) / n for p in classes}

    def resolve(self, name: Optional[str]) -> Optional[FleetTenantConfig]:
        """Header value → tenant class.  None when ``strict`` and the
        name is unknown (the caller 403s without counting)."""
        if not name:
            return self.tenants[self.default_tenant]
        t = self.tenants.get(name)
        if t is None and not self.strict:
            return self.tenants[self.default_tenant]
        return t

    def backlog_frac(self, priority: int) -> float:
        """The fraction of a replica's queue this priority class may
        fill before it sheds (1.0 = never priority-sheds)."""
        return self._frac[priority]

    def try_admit(self, tenant: FleetTenantConfig,
                  queue_depth: Optional[int],
                  max_queue: Optional[int]) -> Optional[str]:
        """None = admitted; otherwise the shed reason
        (``budget`` | ``priority``).  Priority is checked FIRST so a
        priority-shed request never burns a budget token — a tenant
        must not exit a backlog spike budget-broke for requests the
        router refused to route.  ``queue_depth=None`` (remote replica
        — depth unknown here) skips the priority check; the remote
        engine's own admission still bounds it."""
        frac = self.backlog_frac(tenant.priority)
        if (queue_depth is not None and max_queue and frac < 1.0
                and queue_depth >= frac * max_queue):
            return "priority"
        bucket = self._buckets.get(tenant.name)
        if bucket is not None and not bucket.try_take():
            return "budget"
        return None


class RouterStats:
    """Router-door accounting under ``tenant=`` / ``model=`` labels.

    Terminal book: every counted submission ends in exactly ONE
    ``inc_shed`` or ``inc_response`` call — ``outcomes`` (per-outcome
    totals) plus ``tenant_shed`` ARE the fleet identity's terms
    (serve/fleet.py classifies them into served/shed/expired/errors).
    ``rejected``/``transport_errors`` remain as convenience rollups.
    Fault-tolerance counters (per model): ``retries`` (re-dispatched
    attempts beyond the first), ``hedges`` (tail-latency second
    attempts fired), ``failovers`` (re-dispatches that switched
    replica) — attempt accounting, deliberately OUTSIDE the identity
    (one request, however many attempts, is one terminal).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tenant_submitted: Dict[str, int] = {}
        self._tenant_shed: Dict[Tuple[str, str], int] = {}
        self._responses: Dict[Tuple[str, str], int] = {}
        self._outcomes: Dict[str, int] = {}
        self._routed: Dict[str, int] = {}
        self._retries: Dict[str, int] = {}
        self._hedges: Dict[str, int] = {}
        self._failovers: Dict[str, int] = {}
        self._rejected = 0
        self._transport_errors = 0

    def inc_submitted(self, tenant: str) -> None:
        with self._lock:
            self._tenant_submitted[tenant] = \
                self._tenant_submitted.get(tenant, 0) + 1

    def inc_shed(self, tenant: str, reason: str) -> None:
        with self._lock:
            key = (tenant, reason)
            self._tenant_shed[key] = self._tenant_shed.get(key, 0) + 1

    def inc_routed(self, model: str) -> None:
        with self._lock:
            self._routed[model] = self._routed.get(model, 0) + 1

    def inc_retry(self, model: str) -> None:
        with self._lock:
            self._retries[model] = self._retries.get(model, 0) + 1

    def inc_hedge(self, model: str) -> None:
        with self._lock:
            self._hedges[model] = self._hedges.get(model, 0) + 1

    def inc_failover(self, model: str) -> None:
        with self._lock:
            self._failovers[model] = self._failovers.get(model, 0) + 1

    def inc_response(self, tenant: str, outcome: str) -> None:
        with self._lock:
            key = (tenant, outcome)
            self._responses[key] = self._responses.get(key, 0) + 1
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            if outcome == "rejected":
                self._rejected += 1
            elif outcome in ("transport_error", "no_healthy_replica"):
                self._transport_errors += 1

    def snapshot(self) -> Dict:
        with self._lock:
            shed_total = sum(self._tenant_shed.values())
            return {
                "submitted_total": sum(self._tenant_submitted.values()),
                "shed_total": shed_total,
                "rejected_total": self._rejected,
                "transport_errors_total": self._transport_errors,
                "retries_total": sum(self._retries.values()),
                "hedges_total": sum(self._hedges.values()),
                "failovers_total": sum(self._failovers.values()),
                "outcomes": dict(sorted(self._outcomes.items())),
                "retries": dict(sorted(self._retries.items())),
                "hedges": dict(sorted(self._hedges.items())),
                "failovers": dict(sorted(self._failovers.items())),
                "tenants": {
                    t: {
                        "submitted": n,
                        "shed": {r: v for (tt, r), v
                                 in sorted(self._tenant_shed.items())
                                 if tt == t},
                        "responses": {o: v for (tt, o), v
                                      in sorted(self._responses.items())
                                      if tt == t},
                    }
                    for t, n in sorted(self._tenant_submitted.items())},
                "routed": dict(sorted(self._routed.items())),
            }

    def prom_families(self):
        """Router families for the fleet /metrics (tenant=/model=
        labels; one TYPE per family by construction)."""
        with self._lock:
            submitted = sorted(self._tenant_submitted.items())
            shed = sorted(self._tenant_shed.items())
            responses = sorted(self._responses.items())
            routed = sorted(self._routed.items())
        fams = []
        if submitted:
            fams.append(("dsod_fleet_tenant_submitted_total", "counter", [
                'dsod_fleet_tenant_submitted_total{tenant="%s"} %d'
                % (t, n) for t, n in submitted]))
        if shed:
            fams.append(("dsod_fleet_tenant_shed_total", "counter", [
                'dsod_fleet_tenant_shed_total{tenant="%s",reason="%s"} %d'
                % (t, r, n) for (t, r), n in shed]))
        if responses:
            fams.append(("dsod_fleet_tenant_responses_total", "counter", [
                'dsod_fleet_tenant_responses_total'
                '{tenant="%s",outcome="%s"} %d'
                % (t, o, n) for (t, o), n in responses]))
        if routed:
            fams.append(("dsod_fleet_routed_total", "counter", [
                'dsod_fleet_routed_total{model="%s"} %d'
                % (m, n) for m, n in routed]))
        with self._lock:
            fault = (("dsod_fleet_retries_total", sorted(
                self._retries.items())),
                ("dsod_fleet_hedges_total", sorted(self._hedges.items())),
                ("dsod_fleet_failovers_total", sorted(
                    self._failovers.items())))
        for fam, items in fault:
            if items:
                fams.append((fam, "counter", [
                    '%s{model="%s"} %d' % (fam, m, n) for m, n in items]))
        return fams


# -- HTTP front end ----------------------------------------------------

# Request headers the router forwards to a remote replica verbatim.
# X-SLO-MS is NOT here: the router forwards the RESIDUAL budget (the
# original minus elapsed router time and prior attempts) per attempt.
# X-Stream-ID rides so a remote that is itself a streaming-armed
# router keeps the session key (a plain single-engine remote ignores
# it — streaming is a router-tier concern).
_FORWARD_HEADERS = ("Content-Type", "X-Precision", "X-Stream-ID")
# Response headers relayed back from a remote replica's answer.
# X-Timing rides so the stage split (and sampled trace id) a remote
# computed reaches the client through the router unchanged; the
# router's own X-Request-ID echo is authoritative for the request id.
_RELAY_HEADERS = ("X-Degraded", "X-Precision", "X-Res-Bucket",
                  "X-Batch-Bucket", "X-Queue-MS", "X-Device-MS",
                  "X-E2E-MS", "X-Timing")
# Remote answers that trigger failover/retry: the replica itself is
# broken (500 crash, 502 its own upstream, 503 stopped/unhealthy).
# 429/504 are POLICY answers (shed/deadline) — retrying those would
# amplify the very overload they signal; 4xx are the client's fault.
_RETRYABLE_STATUSES = frozenset((500, 502, 503))
# Transport failures: the connection itself broke (refused, reset,
# timeout, short body).  http.client errors (IncompleteRead on a
# mid-body reset) are transport too — the injected chaos mode.
_TRANSPORT_ERRORS = (urllib.error.URLError, OSError,
                     http.client.HTTPException)


class RouterHandler(JsonHTTPHandler):
    """The fleet front door: /predict (routed), /healthz (degrading),
    /metrics (aggregated), /stats, /models."""

    @property
    def fleet(self):
        return self.server.fleet

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 — http.server API
        path = urllib.parse.urlsplit(self.path).path
        if path == "/healthz":
            code, body = self.fleet.health()
            self._send_json(code, body)
        elif path == "/metrics":
            self._send(200, self.fleet.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/stats":
            self._send_json(200, self.fleet.stats())
        elif path == "/models":
            self._send_json(200, {"models": self.fleet.describe_models()})
        elif path == "/alerts":
            # Aggregated model-health alerts (docs/OBSERVABILITY.md
            # "Model health"): per-replica rule states + the fleet-wide
            # active union.
            self._send_json(200, self.fleet.alerts())
        elif path == "/slo":
            # Router-tier error-budget accounting (utils/slo.py;
            # "Capacity & SLO"): fed by the router's own terminal book,
            # empty objective list when the knob is off.
            slo = self.fleet.slo
            self._send_json(200, slo.snapshot() if slo is not None
                            else {"objectives": [], "active": []})
        elif path == "/debug/traces":
            q = urllib.parse.urlsplit(self.path).query
            self._send_json(200, self.fleet.debug_traces(
                n=_query_int(q, "n", 50)))
        elif path == "/incidents":
            # Flight-recorder aggregation (utils/flightrecorder.py):
            # the router's own ring + every reachable replica's.
            self._send_json(200, self.fleet.incidents())
        else:
            self._send_json(404, {"error": f"no route {path}"})

    # -- POST ----------------------------------------------------------

    def _guarded_send(self, *a, **kw) -> None:
        """Send, tolerating a client that went away mid-response: the
        request's outcome was decided by the BACKEND's answer (or the
        router's policy), and a dead client must never turn one
        terminal into two."""
        try:
            self._send(*a, **kw)
        except Exception:  # noqa: BLE001 — client gone
            self.close_connection = True

    def _guarded_send_json(self, code: int, obj, headers=()) -> None:
        self._guarded_send(code, json.dumps(obj).encode(),
                           "application/json", headers=headers)

    def do_POST(self):  # noqa: N802 — http.server API
        split = urllib.parse.urlsplit(self.path)
        if split.path != "/predict":
            self._send_json(404, {"error": f"no route {split.path}"})
            return
        fleet = self.fleet
        query = urllib.parse.parse_qs(split.query)
        model = self.headers.get("X-Model") \
            or (query.get("model") or [None])[0]
        group = fleet.resolve(model)
        if group is None:
            # Unknown model: NO counter anywhere — a typo must not
            # pollute the fleet accounting.  The body was never read;
            # drop the connection so keep-alive can't misparse it.
            self.close_connection = True
            self._send_json(404, {
                "error": f"unknown model {model!r}",
                "models": sorted(fleet.groups)})
            return
        tenant = fleet.admission.resolve(self.headers.get("X-Tenant"))
        if tenant is None:  # strict_tenants: unknown tenant, uncounted
            self.close_connection = True
            self._send_json(403, {
                "error": "unknown tenant "
                         f"{self.headers.get('X-Tenant')!r}",
                "tenants": sorted(fleet.admission.tenants)})
            return
        # The request id doubles as the END-TO-END trace id: minted
        # here (or honored from the client), forwarded to every
        # replica attempt, echoed back — retries and hedges all share
        # it, so one slow request reads as ONE timeline.
        req_id = resolve_request_id(self.headers.get("X-Request-ID"))
        echo = [("X-Model", group.name), ("X-Tenant", tenant.name),
                ("X-Request-ID", req_id)]
        # The deadline budget is stamped at the DOOR: every retry,
        # hedge, and backoff below is charged against it.
        t_door = fleet._clock()
        slo_hdr = self.headers.get("X-SLO-MS")
        # Stream session key (serve/streams.py; docs/SERVING.md
        # "Streaming"): parsed only while the table is armed — with
        # streaming off the header is INERT and everything below is
        # byte-identical to the independent-request path.
        streams = fleet.streams
        sid = (sanitize_stream_id(self.headers.get("X-Stream-ID"))
               if streams is not None else None)
        # From here the request is IN the fleet accounting: every path
        # below terminates it in exactly one router outcome — including
        # a client that disconnects mid-request (the final except
        # records the pre-dispatch abort as a router reject).
        fleet.rstats.inc_submitted(tenant.name)

        # Terminal booking rides with its SLO event: every counted
        # submission feeds the tracker exactly once, at the instant its
        # outcome is decided, so /slo and the router book reconcile
        # (utils/slo.py excludes the client-fault terminals itself).
        def book_response(outcome: str) -> None:
            fleet.rstats.inc_response(tenant.name, outcome)
            fleet.observe_slo(group.name, tenant.name, outcome,
                              (fleet._clock() - t_door) * 1000.0)

        def book_shed(reason: str) -> None:
            fleet.rstats.inc_shed(tenant.name, reason)
            fleet.observe_slo(group.name, tenant.name, "shed",
                              (fleet._clock() - t_door) * 1000.0)
        root_attrs = {"model": group.name, "tenant": tenant.name}
        if sid is not None:
            # Stream-tagged trace: every frame of one stream shares
            # this attr, so /debug/traces can follow a stream's
            # timeline across its requests.
            root_attrs["stream"] = sid
        root = fleet.tracer.begin(
            "request", req_id, t0=t_door, root=True, attrs=root_attrs)

        def end_root(outcome: str) -> None:
            if root is not None:
                root.end(key=(group.name,), outcome=outcome)

        terminal = False
        picked = None
        dispatched = False
        try:
            slo_ms = None
            if slo_hdr is not None:
                try:
                    slo_ms = float(slo_hdr)
                except ValueError:
                    # Malformed deadline: pre-dispatch reject at the
                    # ROUTER (the budget math below needs the number).
                    book_response("rejected")
                    end_root("rejected")
                    terminal = True
                    self.close_connection = True
                    self._guarded_send_json(400, {
                        "error": f"X-SLO-MS {slo_hdr!r} is not a number",
                        "kind": "rejected"}, headers=echo)
                    return
            # Stream session open/refresh BEFORE the pick: a table
            # full of LIVE sessions sheds a NEW stream at the door
            # (same pre-body posture as tenant admission — no body
            # read, no probe slot claimed, no engine queue touched).
            sess = None
            req_phash = None
            if sid is not None:
                verdict, sess = streams.touch(sid)
                if verdict == "budget":
                    book_shed("stream")
                    end_root("shed_stream")
                    terminal = True
                    self.close_connection = True
                    self._guarded_send_json(429, {
                        "error": f"stream table full "
                                 f"({streams.max_sessions} live "
                                 "sessions); retry after a stream "
                                 "goes idle",
                        "kind": "stream_budget"}, headers=echo)
                    return
            # Replica affinity: frames of a homed stream pin to the
            # replica holding the session's warm state; a dead home
            # falls through to the normal rotation and the session
            # RE-HOMES (counted) once the new replica serves it.
            picked = group.pick(
                prefer=sess.home_rid if sess is not None else None)
            if picked is None:
                # Every replica is dead, probe-flagged, or breaker-
                # open: terminal at the router, no timeout paid.
                book_response("no_healthy_replica")
                end_root("no_healthy_replica")
                terminal = True
                self.close_connection = True
                self._guarded_send_json(503, {
                    "error": f"model {group.name!r}: no healthy replica",
                    "kind": "no_healthy_replica"}, headers=echo)
                return
            # Admission BEFORE the body read: an exhausted budget (or a
            # priority shed) must cost one bucket read, not a 64 MB
            # upload.  The unread body forces dropping the connection.
            reason = fleet.admission.try_admit(
                tenant, picked[1].queue_depth(), picked[1].max_queue)
            if reason is not None:
                # The pick may have claimed the replica's single
                # half-open probe slot; this request will never
                # dispatch, so hand the probe back — a shed-destined
                # request must not stall a recovered replica's
                # re-admission.
                picked[2].release_probe()
                book_shed(reason)
                end_root(f"shed_{reason}")
                terminal = True
                self.close_connection = True
                self._guarded_send_json(429, {
                    "error": f"tenant {tenant.name!r} shed at the router "
                             f"({reason})",
                    "kind": {"budget": "tenant_budget",
                             "priority": "priority_shed"}[reason]},
                    headers=echo)
                return
            body = read_predict_body(self)
            if body is None:  # bad Content-Length, 400 already sent
                picked[2].release_probe()  # never dispatched
                book_response("rejected")
                end_root("rejected")
                terminal = True
                return
            # Temporal-coherence fast path (serve/streams.py): a frame
            # within the configured Hamming budget of the stream's
            # previous frame replays the previous mask WITHOUT a
            # forward — checked BEFORE the cache (cheaper: one
            # per-session compare vs an LRU walk) and booked as its
            # own sixth terminal class ``stream_reuse``.
            if sess is not None and streams.reuse_hamming > 0:
                from .cache import payload_fingerprint

                fp = payload_fingerprint(body)
                req_phash = fp[0] if fp is not None else None
                reuse = streams.reuse_body(sess, req_phash)
                if reuse is not None:
                    self._serve_stream_reuse(group, tenant, sess,
                                             reuse, echo, t_door,
                                             end_root)
                    terminal = True
                    picked[2].release_probe()  # never dispatched
                    return
            # Router cache (serve/cache.py; docs/SERVING.md "Router
            # cache").  Engine backends only: a remote replica's loaded
            # step is unknown at the router, and a stale mask is worse
            # than a miss — remotes BYPASS.  Hits/coalesced responses
            # never reach a backend, so they are booked ``cache_hit``
            # (not routed); a follower whose leader failed falls
            # through to its own normal dispatch below.
            cache_handle = None
            cache = fleet.cache
            if cache is not None and picked[1].kind == "engine":
                step = picked[1].engine.loaded_step
                prec = (self.headers.get("X-Precision") or "")
                prec = prec.strip().lower() or None
                verdict, obj = cache.begin(
                    group.name, body, prec,
                    -1 if step is None else int(step))
                if verdict in ("exact", "near") \
                        and self._serve_cache_hit(group, tenant, verdict,
                                                  obj, body, picked,
                                                  echo, t_door, end_root):
                    terminal = True
                    picked[2].release_probe()  # never dispatched
                    return
                if verdict == "follower":
                    entry = self._await_leader(obj, slo_ms, t_door)
                    if entry is not None and self._serve_cache_hit(
                            group, tenant, "coalesced", entry, body,
                            picked, echo, t_door, end_root):
                        terminal = True
                        picked[2].release_probe()
                        return
                elif verdict == "leader":
                    cache_handle = obj
            fleet.rstats.inc_routed(group.name)
            dispatched = True
            self._served_rid = None
            cap = None
            if cache_handle is not None or sess is not None:
                # Tee the response (whoever writes it): a coalescing
                # LEADER feeds the cache so followers wake with the
                # same bytes, and a stream session stores the served
                # mask as its new warm state — both read ONE capture.
                # Any no-capture path abandons the cache token so
                # followers can never hang on a dead leader.
                cap = []
                self._send_capture = cap
            if sess is not None and streams.ema_blend > 0.0:
                # EMA flicker damping: rewrite the 200 mask body
                # in-flight (serve/server.py applies this before the
                # tee, so the client, the cache, and the session all
                # see the SAME blended bytes).  Off (the default) the
                # hook stays None and full forwards are bitwise the
                # engine's own answer.
                self._send_transform = (
                    lambda code, b, ctype, hdrs:
                    streams.blend_body(sess, b)[0]
                    if code == 200 and ctype == "application/x-npy"
                    and dict(hdrs).get("X-Degraded", "0") in ("", "0")
                    else b)
            try:
                outcome = self._dispatch(group, picked, body, echo,
                                         slo_ms, slo_hdr is not None,
                                         t_door, req_id, root,
                                         stream=sid)
            finally:
                self._send_transform = None
                if cap is not None:
                    self._send_capture = None
                if cache_handle is not None:
                    if cap:
                        code, rh, rbody = cap[0]
                        cache.complete(cache_handle, code=code,
                                       headers=rh, body=rbody,
                                       model=group.name)
                    else:
                        cache.abandon(cache_handle)
                if sess is not None and cap:
                    # Full-forward epilogue: store the served mask +
                    # the REQUEST frame's fingerprint as the stream's
                    # warm state (cacheability rule shared with
                    # RouterCache: non-degraded 200 x-npy only).
                    self._stream_note(sess, cap[0], req_phash, t_door)
            if outcome == "ok" and sess is not None:
                # Pin (or re-home, counted) the session to the replica
                # that actually served the frame — under failover that
                # may not be the original pick.
                streams.pin(sess, self._served_rid or picked[0])
            book_response(outcome)
            end_root(outcome)
            terminal = True
        except Exception:  # noqa: BLE001 — dead client / broken pipe
            get_logger().exception("router: predict handler failed")
            self.close_connection = True
            if picked is not None and not dispatched:
                picked[2].release_probe()  # claimed but never used
            if not terminal:
                # No backend outcome was booked (every dispatch path
                # books through the single book_response above): close
                # the book as a router reject, not a silent leak.
                book_response("rejected")
                end_root("rejected")

    # -- router cache --------------------------------------------------

    def _serve_cache_hit(self, group, tenant, kind: str, obj, body,
                         picked, echo, t_door: float, end_root) -> bool:
        """Serve a stored mask for an ``exact`` / ``near`` /
        ``coalesced`` hit and book the ``cache_hit`` terminal — the ONE
        seam where a cache hit enters the router book (registered in
        dsodlint's BOOKING_SEAMS; serve/fleet.py extends the identity
        to served+shed+expired+errors+cache_hit == submitted).

        Returns False (nothing booked, nothing sent) only when a
        near-dup hit could not be resize-normalized — the caller falls
        through to a normal dispatch, so a cache bug can only cost the
        hit, never the request."""
        fleet = self.fleet
        cache = fleet.cache
        if kind == "near":
            ent, hw = obj
            try:
                from .cache import resize_mask_body

                out_body = resize_mask_body(ent.body, hw)
            except Exception:  # noqa: BLE001 — fall back to a forward
                get_logger().exception(
                    "router: near-dup resize failed — dispatching")
                return False
        else:
            ent = obj
            out_body = ent.body
        if kind == "coalesced":
            cache.stats.inc_coalesced(group.name)
        # Terminal booking first, send guarded after — the same
        # book-then-send order as every other router terminal, so an
        # exception can never book twice or strand the submission.
        fleet.rstats.inc_response(tenant.name, "cache_hit")
        fleet.observe_slo(group.name, tenant.name, "cache_hit",
                          (fleet._clock() - t_door) * 1000.0)
        end_root("cache_hit")
        self._guarded_send(200, out_body, ent.content_type,
                           headers=list(echo) + [
                               ("X-Cache", kind),
                               ("X-Degraded", "0"),
                               ("X-Precision", ent.precision),
                               ("X-Res-Bucket", ent.res_bucket)])
        if kind == "near" and cache.should_shadow():
            # Online near-dup quality gate (PR 10 discipline): every
            # Nth near hit re-forwards the ACTUAL request off the
            # request path and records served-vs-fresh MAE.  The
            # shadow forward books in the ENGINE's own book like any
            # direct submit — never the router book.
            cache.submit_shadow(body, out_body, picked[1].engine.predict)
        return True

    def _await_leader(self, tok, slo_ms: Optional[float],
                      t_door: float):
        """Follower side of in-flight coalescing: wait for the leader's
        response, bounded by this request's OWN residual deadline (or
        the fleet request timeout when it carries none).  ``None`` —
        leader failed, timed out, or answered uncacheably — means the
        caller dispatches normally."""
        fleet = self.fleet
        bound = fleet.cfg.request_timeout_s
        residual = fleet.retry_policy.residual_ms(slo_ms, t_door)
        if residual is not None:
            bound = min(bound, max(residual, 0.0) / 1000.0)
        if tok.event.wait(timeout=bound):
            return tok.entry
        return None

    # -- streaming (serve/streams.py) ----------------------------------

    def _serve_stream_reuse(self, group, tenant, sess, out_body: bytes,
                            echo, t_door: float, end_root) -> None:
        """Replay the stream's previous mask for a temporally-coherent
        frame and book the ``stream_reuse`` terminal — the ONE seam
        where the fast path enters the router book (registered in
        dsodlint's BOOKING_SEAMS; serve/fleet.py extends the identity
        to served+shed+expired+errors+cache_hit+stream_reuse ==
        submitted).

        Terminal booking first, send guarded after — the same
        book-then-send order as every other router terminal, so an
        exception can never book twice or strand the submission."""
        fleet = self.fleet
        ms = (fleet._clock() - t_door) * 1000.0
        fleet.rstats.inc_response(tenant.name, "stream_reuse")
        fleet.observe_slo(group.name, tenant.name, "stream_reuse", ms)
        end_root("stream_reuse")
        fleet.streams.note_reuse(sess, ms)
        # Replay the stored response surface: the arm/bucket headers
        # the ORIGINAL forward answered with, plus the reuse marker
        # loadgen's streaming mode splits its latency curves on.
        self._guarded_send(200, out_body, sess.content_type,
                           headers=list(echo) + [
                               ("X-Stream-Reuse", "1"),
                               ("X-Degraded", "0"),
                               ("X-Precision", sess.precision),
                               ("X-Res-Bucket", sess.res_bucket)])

    def _stream_note(self, sess, captured, req_phash,
                     t_door: float) -> None:
        """Store a full forward's captured response as the stream's
        new warm state — same cacheability rule as RouterCache (a
        non-degraded 200 x-npy body; anything else leaves the previous
        warm state in place)."""
        code, rh, rbody = captured
        if code != 200 or not rbody:
            return
        if rh.get("X-Degraded", "0") not in ("", "0"):
            return
        ctype = rh.get("Content-Type", "")
        if ctype != "application/x-npy":
            return
        fleet = self.fleet
        fleet.streams.note_result(
            sess, body=rbody, content_type=ctype,
            precision=rh.get("X-Precision", ""),
            res_bucket=rh.get("X-Res-Bucket", ""),
            phash=req_phash,
            latency_ms=(fleet._clock() - t_door) * 1000.0)

    # -- failover dispatch ---------------------------------------------

    def _dispatch(self, group, picked, body: bytes, echo,
                  slo_ms: Optional[float], has_slo: bool,
                  t_door: float, req_id: Optional[str] = None,
                  root=None, stream: Optional[str] = None) -> str:
        """Run one request against a replica set under the fleet's
        retry/hedge/breaker policy and write exactly one response.
        Returns the request's single terminal outcome.  NEVER raises
        (sends are guarded; attempt failures are data).  Every attempt
        below — first dispatch, retries, hedges — records a child span
        under ``root`` tagged with its replica and breaker state, all
        sharing the ``req_id`` trace."""
        fleet = self.fleet
        policy = fleet.retry_policy
        rid, backend, breaker = picked
        root_sid = root.span_id if root is not None else None
        attempts = 0
        excluded = set()
        last = None
        while True:
            residual = policy.residual_ms(slo_ms, t_door)
            if residual is not None and residual <= 0:
                # The budget died in router hands (backoffs, prior
                # attempts): expired, same as an engine would answer.
                # The current pick never dispatches — hand back any
                # half-open probe slot it claimed.
                breaker.release_probe()
                self._guarded_send_json(504, {
                    "error": "deadline exhausted at the router after "
                             f"{attempts} attempt(s)",
                    "kind": "expired"}, headers=echo)
                return "expired"
            if backend.kind == "engine":
                # An engaged engine writes its own response — its
                # outcome is terminal (no retry after bytes moved).
                # Dead/wedged engines were routed around by pick().
                return self._engine_attempt(group, rid, backend, breaker,
                                            body, echo, slo_ms, has_slo,
                                            t_door, req_id, root_sid,
                                            attempt_n=attempts,
                                            stream=stream)
            result = self._remote_attempt_maybe_hedged(
                group, rid, backend, breaker, body, slo_ms, t_door,
                hedge_allowed=(attempts == 0), excluded=excluded,
                req_id=req_id, root_sid=root_sid, attempt_n=attempts)
            attempts += 1
            if result[0] == "http" \
                    and result[1] not in _RETRYABLE_STATUSES:
                return self._relay_remote(result, echo, group, t_door)
            last = result
            # The failing result names the replica that ACTUALLY
            # produced it — under a hedge that may be the secondary,
            # not the loop's primary.  Exclude both: the failed member
            # for obvious reasons, the slow primary because hedging
            # already judged it past its window.
            failed_rid = result[2] if result[0] == "transport" \
                else result[4]
            excluded.update((rid, failed_rid))
            if len(excluded) >= len(group):
                # Every member has failed once this request: allow
                # re-tries of failed members (their breakers may
                # already block them — that is the breaker's call).
                excluded.clear()
            if not policy.may_retry(attempts, slo_ms, t_door):
                break
            policy.wait_before_retry(attempts, slo_ms, t_door)
            nxt = group.pick(exclude=excluded) or group.pick()
            if nxt is None and breaker.allow():
                # Nothing else is routable and the failed member's own
                # fast health flip blocks a fresh pick — but its
                # breaker still grants attempts: a single-replica
                # transient fault (reset mid-body) deserves its retry;
                # a persistent one trips the breaker and stops here.
                nxt = (rid, backend, breaker)
            if nxt is None:
                break
            fleet.rstats.inc_retry(group.name)
            if nxt[0] != failed_rid:
                fleet.rstats.inc_failover(group.name)
            rid, backend, breaker = nxt
        # The loop ended without an answer.  If the DEADLINE is what
        # ran out (the attempt burned the residual), the honest answer
        # is expired — the client's budget died, whatever the last
        # transport symptom was.
        residual = policy.residual_ms(slo_ms, t_door)
        if residual is not None and residual <= 0:
            self._guarded_send_json(504, {
                "error": "deadline exhausted after "
                         f"{attempts} attempt(s)",
                "kind": "expired"}, headers=echo)
            return "expired"
        # Otherwise attempts ran out: relay the last failure as the
        # request's one terminal answer.
        if last is not None and last[0] == "http":
            return self._relay_remote(last, echo, group, t_door,
                                      final_failure=True)
        reason = last[1] if last is not None else "no replica available"
        self._guarded_send(502, json.dumps({
            "error": f"model {group.name!r} unreachable after "
                     f"{attempts} attempt(s): {reason}",
            "kind": "replica_unreachable"}).encode(),
            "application/json", headers=echo)
        return "transport_error"

    def _engine_attempt(self, group, rid: str, backend, breaker,
                        body: bytes, echo, slo_ms: Optional[float],
                        has_slo: bool, t_door: float,
                        req_id: Optional[str] = None,
                        root_sid: Optional[str] = None,
                        attempt_n: int = 0,
                        stream: Optional[str] = None) -> str:
        fleet = self.fleet
        extra = list(echo) + [("X-Replica", rid)]
        span = None
        if req_id is not None and fleet.tracer.sampled(req_id):
            # breaker.snapshot() only on the sampled path — unsampled
            # requests pay one crc32, nothing else.
            span = fleet.tracer.begin(
                "attempt", req_id, parent_id=root_sid,
                attrs={"replica": rid, "kind": "engine", "n": attempt_n,
                       "breaker": breaker.snapshot()["state"]})
        kw = {}
        if has_slo:
            # Charge elapsed router time against the engine's deadline
            # too — the residual-budget contract is backend-agnostic.
            kw["slo_ms"] = fleet.retry_policy.residual_ms(slo_ms, t_door)
        outcome = run_predict(self, backend.engine, body,
                              extra_headers=extra, request_id=req_id,
                              trace_parent=span.span_id if span else None,
                              stream=stream, **kw)
        if span is not None:
            span.end(outcome=outcome)
        if outcome == "ok":
            # Stream affinity reads which replica ACTUALLY served the
            # frame (under failover, not necessarily the first pick).
            self._served_rid = rid
        if outcome in ("stopped", "error"):
            breaker.record_failure()
        else:
            breaker.record_success()
        # Engine attempts deliberately do NOT feed the group's hedge
        # tail estimate: hedging only ever targets remotes, and a
        # door-to-done engine time (queueing included) would inflate
        # the per-ATTEMPT p95 the hedge trigger needs.
        return outcome

    def _one_remote_call(self, group, rid: str, backend, breaker,
                         body: bytes, slo_ms: Optional[float],
                         t_door: float, req_id: Optional[str] = None,
                         root_sid: Optional[str] = None,
                         attempt_n: int = 0, hedge: bool = False):
        """One POST to one remote replica.  Returns
        ``("http", status, headers, body, rid)`` for ANY HTTP answer or
        ``("transport", reason, rid)`` when the connection itself broke
        — recording the breaker outcome and the health fast-flip, and
        touching NOTHING client-facing (hedge losers run this exact
        path and must stay invisible — their attempt SPAN is recorded,
        the one trace-visible mark a loser leaves)."""
        fleet = self.fleet
        headers = {k: v for k in _FORWARD_HEADERS
                   if (v := self.headers.get(k)) is not None}
        span = None
        if req_id is not None:
            # The trace id rides to the replica: a remote tracing at
            # the same rate records the in-engine half of THIS trace
            # under the same id (deterministic sampling).
            headers["X-Request-ID"] = req_id
            if fleet.tracer.sampled(req_id):
                span = fleet.tracer.begin(
                    "attempt", req_id, parent_id=root_sid,
                    attrs={"replica": rid, "kind": "remote",
                           "n": attempt_n, "hedge": hedge,
                           "breaker": breaker.snapshot()["state"]})
        residual = fleet.retry_policy.residual_ms(slo_ms, t_door)
        timeout_s = None
        if residual is not None:
            # Forward the RESIDUAL budget — the remote must judge its
            # own expiry against what is actually left, and a retry
            # paid for its predecessors.  Cap the transport wait just
            # past it so a stalled remote cannot hold the slot hostage.
            headers["X-SLO-MS"] = "%.3f" % max(residual, 0.0)
            timeout_s = max(residual, 0.0) / 1000.0 + 0.5
        t0 = fleet._clock()
        try:
            status, rheaders, rbody = backend.predict_raw(
                body, headers, timeout_s=timeout_s)
        except _TRANSPORT_ERRORS as e:
            breaker.record_failure()
            if span is not None:
                span.end(result="transport", error=f"{type(e).__name__}")
            note = getattr(backend, "note_transport_failure", None)
            if note is not None:
                note(str(e))
            # Flight recorder: a replica death under load is exactly
            # the incident the router-tier bundle exists for (event
            # per failure, bundle debounced).
            fleet.note_replica_failure(rid, group.name,
                                       f"{type(e).__name__}: {e}")
            get_logger().warning(
                "router: replica %s transport failure: %s", rid, e)
            return ("transport", f"{type(e).__name__}: {e}", rid)
        if span is not None:
            span.end(status=status)
        if status in _RETRYABLE_STATUSES:
            breaker.record_failure()
        else:
            breaker.record_success()
            if status == 200:
                # Only SERVED attempts feed the hedge-trigger tail
                # estimate (per-attempt time, remote attempts only):
                # fast 429/400 answers under overload would collapse
                # the p95 and make auto-hedging amplify the very
                # overload that sheds.
                fleet.observe_latency(group.name,
                                      (fleet._clock() - t0) * 1000.0)
        return ("http", status, rheaders, rbody, rid)

    def _remote_attempt_maybe_hedged(self, group, rid: str, backend,
                                     breaker, body: bytes,
                                     slo_ms: Optional[float],
                                     t_door: float, hedge_allowed: bool,
                                     excluded,
                                     req_id: Optional[str] = None,
                                     root_sid: Optional[str] = None,
                                     attempt_n: int = 0) -> tuple:
        """The FIRST dispatch may race a tail-latency hedge: if the
        primary hasn't answered within the hedge delay (fixed, or the
        router's observed per-model p95), fire the same request at a
        second healthy replica and take whichever answers first.  The
        loser is abandoned — its thread still records its breaker
        outcome but can never touch the response or the book."""
        fleet = self.fleet
        delay_ms = None
        if hedge_allowed and len(group) > 1:
            delay_ms = pick_hedge_delay(fleet.cfg.hedge_ms,
                                        group.tail.percentile(0.95))
        if delay_ms is None:
            return self._one_remote_call(group, rid, backend, breaker,
                                         body, slo_ms, t_door, req_id,
                                         root_sid, attempt_n)
        residual = fleet.retry_policy.residual_ms(slo_ms, t_door)
        if residual is not None and residual <= delay_ms:
            # No budget left to wait out a hedge window — plain call.
            return self._one_remote_call(group, rid, backend, breaker,
                                         body, slo_ms, t_door, req_id,
                                         root_sid, attempt_n)
        results: "queue.Queue" = queue.Queue()
        # Every results.get() below is bounded by this: the attempts'
        # own transport timeouts are tighter, so the bound only bites
        # when a worker thread died without enqueueing (in which case
        # the synthetic transport failure keeps the request terminal).
        worker_bound_s = fleet.cfg.request_timeout_s + 5.0

        def attempt(rid_, backend_, breaker_, hedge_=False):
            try:
                results.put(self._one_remote_call(
                    group, rid_, backend_, breaker_, body, slo_ms,
                    t_door, req_id, root_sid, attempt_n, hedge=hedge_))
            except Exception as e:  # noqa: BLE001 — keep the handler fed
                get_logger().exception(
                    "router: hedge attempt worker failed")
                results.put(("transport",
                             f"attempt worker died: {e}", rid_))

        def bounded_get(fallback_rid):
            try:
                return results.get(timeout=worker_bound_s)
            except queue.Empty:
                return ("transport", "attempt worker lost", fallback_rid)

        threading.Thread(target=attempt, args=(rid, backend, breaker),
                         name="router-hedge-primary",
                         daemon=True).start()
        try:
            return results.get(timeout=delay_ms / 1000.0)
        except queue.Empty:
            pass
        hedge_pick = group.pick(exclude=set(excluded) | {rid})
        if hedge_pick is not None and hedge_pick[1].kind != "remote":
            # Never hedge onto an in-process engine: it shares the
            # device with its siblings (a hedge there queues behind
            # itself) and has no predict_raw.  Hand back any probe
            # slot the pick claimed.
            hedge_pick[2].release_probe()
            hedge_pick = None
        if hedge_pick is None:  # no second healthy replica: wait it out
            return bounded_get(rid)
        fleet.rstats.inc_hedge(group.name)
        threading.Thread(target=attempt, args=tuple(hedge_pick) + (True,),
                         name="router-hedge-secondary",
                         daemon=True).start()
        first = bounded_get(rid)
        if first[0] == "http" and first[1] not in _RETRYABLE_STATUSES:
            return first
        # The faster answer was a failure; the slower attempt may still
        # succeed — waiting for it beats surfacing a known failure.
        second = bounded_get(hedge_pick[0])
        if second[0] == "http" and second[1] not in _RETRYABLE_STATUSES:
            return second
        return first

    def _relay_remote(self, result, echo, group, t_door: float,
                      final_failure: bool = False) -> str:
        """Relay a remote's HTTP answer (status, selected headers,
        body) to the client verbatim and classify the outcome."""
        _, status, rheaders, rbody, rid = result
        rh = {k: v for k, v in rheaders}
        if status == 200:
            self._served_rid = rid  # stream affinity pins to this
        relay = echo + [("X-Replica", rid)] \
            + [(k, rh[k]) for k in _RELAY_HEADERS if k in rh]
        ctype = rh.get("Content-Type", "application/octet-stream")
        self._guarded_send(status, rbody, ctype, headers=relay)
        if status == 400:
            # The remote's 400 body says who counted it: a pre-submit
            # "rejected" never entered the remote's accounting, an
            # "invalid_input" was counted by the remote's engine — the
            # router book classifies both as errors either way, the
            # split is kept for the per-replica reconciliation.
            try:
                kind = json.loads(rbody.decode()).get("kind")
            except (ValueError, UnicodeDecodeError):
                kind = None
            return "bad_request" if kind == "invalid_input" else "rejected"
        return {200: "ok", 429: "shed", 504: "expired",
                503: "stopped"}.get(status, "error")


class FleetServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, fleet):
        self.fleet = fleet
        super().__init__(addr, RouterHandler)


def make_fleet_server(fleet, host: str, port: int) -> FleetServer:
    """Bind (``port=0`` → ephemeral; read ``server_address[1]``)."""
    return FleetServer((host, port), fleet)


def serve_fleet_forever(fleet, host: str, port: int,
                        port_file: Optional[str] = None) -> int:
    """Start the fleet (engines + interleaved dispatcher) and the
    router HTTP server; block until SIGTERM/SIGINT, then drain cleanly
    (exit 0 — the same contract tools/t1.sh smokes for the
    single-engine server)."""
    import signal

    log = get_logger()
    fleet.start()
    srv = make_fleet_server(fleet, host, port)
    bound = srv.server_address[1]
    publish_port(port_file, bound)
    prober = None
    if fleet.cfg.prober_interval_s > 0:
        # Synthetic canary prober (serve/prober.py): probes loop back
        # through the router's OWN bound address, so they traverse the
        # full front door — tenancy, routing, failover, accounting —
        # exactly like a client request.
        from .prober import SyntheticProber

        probe_host = host if host not in ("", "0.0.0.0") else "127.0.0.1"
        prober = SyntheticProber(
            f"http://{probe_host}:{bound}", sorted(fleet.groups),
            stats=fleet.probe_stats,
            interval_s=fleet.cfg.prober_interval_s,
            tenant=fleet.cfg.prober_tenant, px=fleet.cfg.prober_px,
            timeout_s=fleet.cfg.prober_timeout_s).start()
    stop = threading.Event()

    def _sig(signum, frame):
        log.info("fleet: signal %s — draining", signum)
        if fleet.recorder is not None and not stop.is_set():
            # Bundle the router's last telemetry window before the
            # drain (debounced; the replicas bundle their own SIGTERMs).
            fleet.recorder.trigger("sigterm", f"signal {signum}")
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _sig)
        except ValueError:  # non-main thread (tests drive stop directly)
            pass
    t = threading.Thread(target=srv.serve_forever, name="fleet-http",
                         daemon=True)
    t.start()
    log.info("fleet: listening on http://%s:%d (models=%s tenants=%s)",
             host, bound, sorted(fleet.backends),
             sorted(fleet.admission.tenants))
    try:
        while not stop.wait(0.2):
            pass
    finally:
        if prober is not None:
            prober.stop()  # before the server: a probe mid-flight may
            #   hold a connection the shutdown would otherwise wait on
        srv.shutdown()
        srv.server_close()
        fleet.stop()
        log.info("fleet: shut down cleanly")
    return 0
