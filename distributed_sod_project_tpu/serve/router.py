"""Router tier for the multi-model serving fleet (docs/SERVING.md
"Fleet"): model-aware routing, multi-tenant admission, and the fleet
HTTP front end.

The routing/tenancy POLICY lives here (``TokenBucket``,
``TenantAdmission``, ``RouterStats``); the fleet ASSEMBLY — backends,
the interleaved dispatch loop, metric aggregation — lives in
``serve/fleet.py``.  The philosophy extends PR 5's admission story one
tier up: the cheapest place to reject work the fleet cannot (or will
not) do is the router door, BEFORE a request ever reaches an engine
queue — an exhausted tenant budget costs one token-bucket read, not an
engine slot.

Request contract (``POST /predict``):

- ``X-Model: <name>`` (or a ``model=`` query field) names the replica
  set.  Unknown → 404, and the request never touches a counter — a
  typo'd model name must not pollute the fleet accounting.  The served
  model is echoed back as ``X-Model``.
- ``X-Tenant: <name>`` names the tenant class (``default_tenant`` when
  absent; unknown tenants ride the default class unless
  ``strict_tenants``, then 403 uncounted).  The tenant's token-bucket
  budget and priority class are enforced here: budget exhaustion and
  priority shed answer 429 (``kind: tenant_budget | priority_shed``)
  with the engine queues untouched.
- Everything after admission is the single-engine contract verbatim
  (``serve/server.py::run_predict``) — same headers, same status
  mapping, bitwise-identical responses.

Fleet-wide accounting identity (the PR-5 invariant, one tier up):

    served + shed + expired + errors == submitted

where ``submitted`` counts every routed-and-tenant-resolved request at
the router door, ``shed`` adds router sheds (budget/priority) to the
engines' queue sheds, and ``errors`` adds router-side terminal rejects
(pre-submit 400s, remote transport failures) to the engines' error
counts.  Each engine's own identity is preserved exactly — the router
only ever adds terminals for requests the engines never saw.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple

from ..configs.base import FleetTenantConfig
from ..utils.logging import get_logger
from .server import (JsonHTTPHandler, ThreadingHTTPServer, publish_port,
                     read_predict_body, run_predict)


class TokenBucket:
    """Thread-safe token bucket: ``rate_per_s`` sustained, ``burst``
    capacity.  ``clock`` is injectable for deterministic tests."""

    def __init__(self, rate_per_s: float, burst: float = 0.0,
                 clock=time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst > 0 else self.rate
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available (refilling lazily); False
        when the budget is exhausted."""
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class TenantAdmission:
    """Resolve a request's tenant class and enforce its budget +
    priority BEFORE the engine queue.

    Budgets: each tenant with ``rate_rps > 0`` owns a
    :class:`TokenBucket`; an exhausted bucket sheds at the router.

    Priorities: the distinct configured priorities form shed classes —
    a class of rank ``r`` (0 = lowest) among ``n`` classes may use the
    target replica's queue only while its depth is below
    ``(r+1)/n * max_queue``.  The top class never priority-sheds (the
    engine's own bound is its limit), so with a single class the
    mechanism is inert.  Burst-proof by construction: under a one-hot
    overload the low classes lose admission first, which is the
    documented contract, not an emergent accident.
    """

    def __init__(self, tenants: Tuple[FleetTenantConfig, ...],
                 default_tenant: str = "default",
                 strict: bool = False, clock=time.monotonic):
        tenants = tuple(tenants)
        if default_tenant not in {t.name for t in tenants}:
            low = min((t.priority for t in tenants), default=0)
            tenants += (FleetTenantConfig(name=default_tenant,
                                          priority=low),)
        self.tenants: Dict[str, FleetTenantConfig] = {
            t.name: t for t in tenants}
        self.default_tenant = default_tenant
        self.strict = strict
        self._buckets: Dict[str, Optional[TokenBucket]] = {
            t.name: (TokenBucket(t.rate_rps, t.burst, clock=clock)
                     if t.rate_rps > 0 else None)
            for t in tenants}
        classes = sorted({t.priority for t in tenants})
        n = len(classes)
        self._frac = {p: (classes.index(p) + 1) / n for p in classes}

    def resolve(self, name: Optional[str]) -> Optional[FleetTenantConfig]:
        """Header value → tenant class.  None when ``strict`` and the
        name is unknown (the caller 403s without counting)."""
        if not name:
            return self.tenants[self.default_tenant]
        t = self.tenants.get(name)
        if t is None and not self.strict:
            return self.tenants[self.default_tenant]
        return t

    def backlog_frac(self, priority: int) -> float:
        """The fraction of a replica's queue this priority class may
        fill before it sheds (1.0 = never priority-sheds)."""
        return self._frac[priority]

    def try_admit(self, tenant: FleetTenantConfig,
                  queue_depth: Optional[int],
                  max_queue: Optional[int]) -> Optional[str]:
        """None = admitted; otherwise the shed reason
        (``budget`` | ``priority``).  Priority is checked FIRST so a
        priority-shed request never burns a budget token — a tenant
        must not exit a backlog spike budget-broke for requests the
        router refused to route.  ``queue_depth=None`` (remote replica
        — depth unknown here) skips the priority check; the remote
        engine's own admission still bounds it."""
        frac = self.backlog_frac(tenant.priority)
        if (queue_depth is not None and max_queue and frac < 1.0
                and queue_depth >= frac * max_queue):
            return "priority"
        bucket = self._buckets.get(tenant.name)
        if bucket is not None and not bucket.try_take():
            return "budget"
        return None


class RouterStats:
    """Router-door accounting under ``tenant=`` / ``model=`` labels.

    Terminal counters (requests the ENGINES never saw — the router's
    contribution to the fleet identity): ``tenant_shed`` (budget /
    priority, per reason), ``rejected`` (pre-submit 400s), and
    ``transport_errors`` (remote replica unreachable).  ``responses``
    is the observational per-tenant outcome tally (includes
    engine-owned outcomes; NOT part of the identity — dashboards only).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tenant_submitted: Dict[str, int] = {}
        self._tenant_shed: Dict[Tuple[str, str], int] = {}
        self._responses: Dict[Tuple[str, str], int] = {}
        self._routed: Dict[str, int] = {}
        self._rejected = 0
        self._transport_errors = 0

    def inc_submitted(self, tenant: str) -> None:
        with self._lock:
            self._tenant_submitted[tenant] = \
                self._tenant_submitted.get(tenant, 0) + 1

    def inc_shed(self, tenant: str, reason: str) -> None:
        with self._lock:
            key = (tenant, reason)
            self._tenant_shed[key] = self._tenant_shed.get(key, 0) + 1

    def inc_routed(self, model: str) -> None:
        with self._lock:
            self._routed[model] = self._routed.get(model, 0) + 1

    def inc_response(self, tenant: str, outcome: str) -> None:
        with self._lock:
            key = (tenant, outcome)
            self._responses[key] = self._responses.get(key, 0) + 1
            if outcome == "rejected":
                self._rejected += 1
            elif outcome == "transport_error":
                self._transport_errors += 1

    def snapshot(self) -> Dict:
        with self._lock:
            shed_total = sum(self._tenant_shed.values())
            return {
                "submitted_total": sum(self._tenant_submitted.values()),
                "shed_total": shed_total,
                "rejected_total": self._rejected,
                "transport_errors_total": self._transport_errors,
                "tenants": {
                    t: {
                        "submitted": n,
                        "shed": {r: v for (tt, r), v
                                 in sorted(self._tenant_shed.items())
                                 if tt == t},
                        "responses": {o: v for (tt, o), v
                                      in sorted(self._responses.items())
                                      if tt == t},
                    }
                    for t, n in sorted(self._tenant_submitted.items())},
                "routed": dict(sorted(self._routed.items())),
            }

    def prom_families(self):
        """Router families for the fleet /metrics (tenant=/model=
        labels; one TYPE per family by construction)."""
        with self._lock:
            submitted = sorted(self._tenant_submitted.items())
            shed = sorted(self._tenant_shed.items())
            responses = sorted(self._responses.items())
            routed = sorted(self._routed.items())
        fams = []
        if submitted:
            fams.append(("dsod_fleet_tenant_submitted_total", "counter", [
                'dsod_fleet_tenant_submitted_total{tenant="%s"} %d'
                % (t, n) for t, n in submitted]))
        if shed:
            fams.append(("dsod_fleet_tenant_shed_total", "counter", [
                'dsod_fleet_tenant_shed_total{tenant="%s",reason="%s"} %d'
                % (t, r, n) for (t, r), n in shed]))
        if responses:
            fams.append(("dsod_fleet_tenant_responses_total", "counter", [
                'dsod_fleet_tenant_responses_total'
                '{tenant="%s",outcome="%s"} %d'
                % (t, o, n) for (t, o), n in responses]))
        if routed:
            fams.append(("dsod_fleet_routed_total", "counter", [
                'dsod_fleet_routed_total{model="%s"} %d'
                % (m, n) for m, n in routed]))
        return fams


# -- HTTP front end ----------------------------------------------------

# Request headers the router forwards to a remote replica verbatim.
_FORWARD_HEADERS = ("Content-Type", "X-SLO-MS", "X-Precision")
# Response headers relayed back from a remote replica's answer.
_RELAY_HEADERS = ("X-Degraded", "X-Precision", "X-Res-Bucket",
                  "X-Batch-Bucket", "X-Queue-MS", "X-Device-MS",
                  "X-E2E-MS")


class RouterHandler(JsonHTTPHandler):
    """The fleet front door: /predict (routed), /healthz (degrading),
    /metrics (aggregated), /stats, /models."""

    @property
    def fleet(self):
        return self.server.fleet

    # -- GET -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 — http.server API
        path = urllib.parse.urlsplit(self.path).path
        if path == "/healthz":
            code, body = self.fleet.health()
            self._send_json(code, body)
        elif path == "/metrics":
            self._send(200, self.fleet.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/stats":
            self._send_json(200, self.fleet.stats())
        elif path == "/models":
            self._send_json(200, {"models": self.fleet.describe_models()})
        else:
            self._send_json(404, {"error": f"no route {path}"})

    # -- POST ----------------------------------------------------------

    def do_POST(self):  # noqa: N802 — http.server API
        split = urllib.parse.urlsplit(self.path)
        if split.path != "/predict":
            self._send_json(404, {"error": f"no route {split.path}"})
            return
        fleet = self.fleet
        query = urllib.parse.parse_qs(split.query)
        model = self.headers.get("X-Model") \
            or (query.get("model") or [None])[0]
        backend = fleet.resolve(model)
        if backend is None:
            # Unknown model: NO counter anywhere — a typo must not
            # pollute the fleet accounting.  The body was never read;
            # drop the connection so keep-alive can't misparse it.
            self.close_connection = True
            self._send_json(404, {
                "error": f"unknown model {model!r}",
                "models": sorted(fleet.backends)})
            return
        tenant = fleet.admission.resolve(self.headers.get("X-Tenant"))
        if tenant is None:  # strict_tenants: unknown tenant, uncounted
            self.close_connection = True
            self._send_json(403, {
                "error": "unknown tenant "
                         f"{self.headers.get('X-Tenant')!r}",
                "tenants": sorted(fleet.admission.tenants)})
            return
        echo = [("X-Model", backend.name), ("X-Tenant", tenant.name)]
        # From here the request is IN the fleet accounting: every path
        # below terminates it in exactly one router or engine counter —
        # including a client that disconnects mid-request (the final
        # except records the pre-engine abort as a router reject).
        fleet.rstats.inc_submitted(tenant.name)
        terminal = False
        try:
            # Admission BEFORE the body read: an exhausted budget (or a
            # priority shed) must cost one bucket read, not a 64 MB
            # upload.  The unread body forces dropping the connection.
            reason = fleet.admission.try_admit(
                tenant, backend.queue_depth(), backend.max_queue)
            if reason is not None:
                fleet.rstats.inc_shed(tenant.name, reason)
                terminal = True
                self.close_connection = True
                self._send_json(429, {
                    "error": f"tenant {tenant.name!r} shed at the router "
                             f"({reason})",
                    "kind": {"budget": "tenant_budget",
                             "priority": "priority_shed"}[reason]},
                    headers=echo)
                return
            body = read_predict_body(self)
            if body is None:  # bad Content-Length, 400 already sent
                fleet.rstats.inc_response(tenant.name, "rejected")
                terminal = True
                return
            fleet.rstats.inc_routed(backend.name)
            if backend.kind == "engine":
                outcome = run_predict(self, backend.engine, body,
                                      extra_headers=echo)
            else:
                outcome = self._proxy(backend, body, echo)
            fleet.rstats.inc_response(tenant.name, outcome)
            terminal = True
        except Exception:  # noqa: BLE001 — dead client / broken pipe
            get_logger().exception("router: predict handler failed")
            self.close_connection = True
            if not terminal:
                # The engine never saw it (run_predict/_proxy never
                # raise once a backend is engaged): close the book as
                # a router reject, not a silent leak.
                fleet.rstats.inc_response(tenant.name, "rejected")

    def _proxy(self, backend, body: bytes, echo) -> str:
        """Forward /predict to a remote replica and relay its answer
        (status, selected headers, body) verbatim.  Sends are guarded:
        the outcome is decided by the REMOTE's answer, and a client
        that died mid-relay must not turn an already-counted remote
        terminal into a second router terminal."""
        headers = {k: v for k in _FORWARD_HEADERS
                   if (v := self.headers.get(k)) is not None}

        def send(*a, **kw):
            try:
                self._send(*a, **kw)
            except Exception:  # noqa: BLE001 — client went away
                self.close_connection = True

        try:
            status, rheaders, rbody = backend.predict_raw(body, headers)
        except (urllib.error.URLError, OSError) as e:
            get_logger().warning("router: replica %s unreachable: %s",
                                 backend.name, e)
            send(502, json.dumps({
                "error": f"replica {backend.name!r} unreachable: {e}",
                "kind": "replica_unreachable"}).encode(),
                "application/json", headers=echo)
            return "transport_error"
        rh = {k: v for k, v in rheaders}
        relay = echo + [(k, rh[k]) for k in _RELAY_HEADERS if k in rh]
        ctype = rh.get("Content-Type", "application/octet-stream")
        send(status, rbody, ctype, headers=relay)
        if status == 400:
            # The remote's 400 body says who counted it: a pre-submit
            # "rejected" never entered the remote's accounting (this
            # router must terminal-count it), an "invalid_input" was
            # counted by the remote's engine (submitted+errors — no
            # router terminal, or one request lands in two books).
            try:
                kind = json.loads(rbody.decode()).get("kind")
            except (ValueError, UnicodeDecodeError):
                kind = None
            return "bad_request" if kind == "invalid_input" else "rejected"
        return {200: "ok", 429: "shed", 504: "expired",
                503: "stopped"}.get(status, "error")


class FleetServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, fleet):
        self.fleet = fleet
        super().__init__(addr, RouterHandler)


def make_fleet_server(fleet, host: str, port: int) -> FleetServer:
    """Bind (``port=0`` → ephemeral; read ``server_address[1]``)."""
    return FleetServer((host, port), fleet)


def serve_fleet_forever(fleet, host: str, port: int,
                        port_file: Optional[str] = None) -> int:
    """Start the fleet (engines + interleaved dispatcher) and the
    router HTTP server; block until SIGTERM/SIGINT, then drain cleanly
    (exit 0 — the same contract tools/t1.sh smokes for the
    single-engine server)."""
    import signal

    log = get_logger()
    fleet.start()
    srv = make_fleet_server(fleet, host, port)
    bound = srv.server_address[1]
    publish_port(port_file, bound)
    stop = threading.Event()

    def _sig(signum, frame):
        log.info("fleet: signal %s — draining", signum)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _sig)
        except ValueError:  # non-main thread (tests drive stop directly)
            pass
    t = threading.Thread(target=srv.serve_forever, name="fleet-http",
                         daemon=True)
    t.start()
    log.info("fleet: listening on http://%s:%d (models=%s tenants=%s)",
             host, bound, sorted(fleet.backends),
             sorted(fleet.admission.tenants))
    try:
        while not stop.wait(0.2):
            pass
    finally:
        srv.shutdown()
        srv.server_close()
        fleet.stop()
        log.info("fleet: shut down cleanly")
    return 0
