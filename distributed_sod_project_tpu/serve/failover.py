"""Fault-tolerance policy primitives for the serving fleet
(docs/SERVING.md "Failure semantics").

TF-Replicator's thesis one more time: the distributed-execution layer
owns worker failure so user code never sees it.  For serving that
means the ROUTER owns replica failure — a request that hits a dead,
wedged, or resetting backend is re-dispatched, hedged, or terminally
counted, and the client sees exactly one answer either way.  Three
pure-policy pieces live here, each injectable-clock testable without a
single socket:

- :class:`CircuitBreaker` — per-replica closed → open → half-open
  gate.  ``breaker_failures`` consecutive failures open it; an open
  breaker swallows the dispatch attempt entirely (the wedged remote is
  routed AROUND, costing a dict read instead of a connect timeout);
  after ``breaker_reset_s`` ONE half-open probe is allowed through and
  its outcome decides re-admission vs re-open.
- :class:`RetryPolicy` — capped exponential backoff charged against
  the request's residual deadline budget: a retry is only granted
  while attempts remain AND the residual ``X-SLO-MS`` can still cover
  the backoff, so retried attempts can never exceed the original
  budget (asserted with a fake clock in tests/test_failover.py).
- :func:`pick_hedge_delay` — the tail-latency hedge trigger: a fixed
  delay, or the router's observed per-model p95 when configured to
  auto (``hedge_ms = -1``).

``serve/fleet.py`` owns replica GROUPING (which breaker guards which
backend); ``serve/router.py`` owns the dispatch loop that consults
these policies.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# Breaker states, in escalation order (also the value of the
# dsod_fleet_breaker_state gauge: 0 closed, 1 half-open, 2 open).
CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-replica failure gate: closed → open after ``failures``
    CONSECUTIVE failures → half-open single probe after ``reset_s``.

    Thread-safe; every router worker thread records outcomes into the
    same breaker.  ``allow()`` is the dispatch gate: True from closed,
    True exactly ONCE per reset window from open (the transition to
    half-open — that caller is the probe), False while the probe is in
    flight.  The probe's ``record_success`` re-admits the replica;
    its ``record_failure`` re-opens for another full window.
    """

    def __init__(self, failures: int = 3, reset_s: float = 5.0,
                 clock=time.monotonic):
        if failures < 1:
            raise ValueError(f"breaker failures must be >= 1, got {failures}")
        if reset_s <= 0:
            raise ValueError(f"breaker reset_s must be > 0, got {reset_s}")
        self._failures = int(failures)
        self._reset_s = float(reset_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self._opened_total = 0  # closed/half-open → open transitions

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def opened_total(self) -> int:
        """How many times this breaker has tripped open (the
        ``dsod_fleet_breaker_open_total`` counter)."""
        with self._lock:
            return self._opened_total

    def would_allow(self) -> bool:
        """Non-mutating routability read for health surfaces: could a
        dispatch reach this replica now-or-imminently?  True for
        closed, for half-open (a probe is assessing it), and for open
        once the reset window has elapsed (the next pick IS the
        probe); False only while open-and-cooling.  Never claims the
        probe slot — /healthz must observe, not consume."""
        with self._lock:
            if self._state == OPEN:
                return self._clock() - self._opened_at >= self._reset_s
            return True

    def allow(self) -> bool:
        """May the caller dispatch to this replica right now?  An open
        breaker answers True exactly once per ``reset_s`` window — that
        caller IS the half-open probe and must report its outcome."""
        with self._lock:
            now = self._clock()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at >= self._reset_s:
                    self._state = HALF_OPEN
                    self._half_open_at = now
                    return True  # the single probe
                return False
            # HALF_OPEN: a probe is in flight — unless it evaporated
            # (caller died before recording an outcome); after a full
            # reset window with no verdict, grant a replacement probe
            # so a lost one cannot wedge the breaker half-open forever.
            if now - self._half_open_at >= self._reset_s:
                self._half_open_at = now
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._consecutive = 0

    def release_probe(self) -> None:
        """Return an UNUSED half-open probe slot: the caller won
        ``allow()``'s single probe but never dispatched (the request
        was shed or rejected before reaching the replica).  Reverts to
        OPEN with the original window intact, so the very next caller
        can claim the probe instead of waiting out another reset."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = OPEN

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            tripped = (self._state == HALF_OPEN
                       or self._consecutive >= self._failures)
            if tripped and self._state != OPEN:
                self._opened_total += 1
            if tripped:
                self._state = OPEN
                self._opened_at = self._clock()

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive,
                    "opened_total": self._opened_total}


class RetryPolicy:
    """Retry/backoff under a deadline budget.

    ``max_attempts`` is the TOTAL dispatch attempts a request may make
    (1 = no retry).  Backoff between attempt k and k+1 is
    ``backoff_ms * 2**(k-1)`` capped at ``backoff_max_ms`` — and a
    retry is granted only while the residual budget can still cover
    that backoff, so the sum of waits and attempts never exceeds the
    request's original ``X-SLO-MS``.  ``clock``/``sleep`` are
    injectable so the budget math is provable with a fake clock.
    """

    def __init__(self, max_attempts: int = 2, backoff_ms: float = 10.0,
                 backoff_max_ms: float = 250.0, clock=time.monotonic,
                 sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError(
                f"retry max_attempts must be >= 1, got {max_attempts}")
        if backoff_ms < 0 or backoff_max_ms < 0:
            raise ValueError("retry backoff must be >= 0")
        self.max_attempts = int(max_attempts)
        self.backoff_ms = float(backoff_ms)
        self.backoff_max_ms = float(max(backoff_max_ms, backoff_ms))
        self._clock = clock
        self._sleep = sleep

    def backoff_for(self, retry_index: int) -> float:
        """Backoff in ms before the ``retry_index``-th RETRY (1-based:
        the wait between attempt k and attempt k+1 has index k)."""
        if retry_index < 1 or self.backoff_ms <= 0:
            return 0.0
        return min(self.backoff_ms * (2.0 ** (retry_index - 1)),
                   self.backoff_max_ms)

    def residual_ms(self, slo_ms: Optional[float], t0: float) -> Optional[float]:
        """What is left of the request's original budget, charged
        against everything since it crossed the router door at ``t0``
        (router time, prior attempts, backoffs).  None = no deadline."""
        if slo_ms is None:
            return None
        return float(slo_ms) - (self._clock() - t0) * 1000.0

    def may_retry(self, attempts_done: int, slo_ms: Optional[float],
                  t0: float) -> bool:
        """Grant attempt ``attempts_done + 1``?  Requires an attempt
        slot AND enough residual budget to cover the pre-retry backoff
        with something left to actually dispatch."""
        if attempts_done >= self.max_attempts:
            return False
        residual = self.residual_ms(slo_ms, t0)
        if residual is None:
            return True
        return residual > self.backoff_for(attempts_done)

    def wait_before_retry(self, retry_index: int, slo_ms: Optional[float],
                          t0: float) -> None:
        """Sleep the capped-exponential backoff, never past the
        residual budget (the next residual_ms() check still gates the
        dispatch itself)."""
        wait_ms = self.backoff_for(retry_index)
        residual = self.residual_ms(slo_ms, t0)
        if residual is not None:
            wait_ms = min(wait_ms, max(residual, 0.0))
        if wait_ms > 0:
            self._sleep(wait_ms / 1000.0)


def pick_hedge_delay(hedge_ms: float, p95_ms: Optional[float]
                     ) -> Optional[float]:
    """The tail-latency hedge trigger delay in ms, or None when
    hedging is off for this request.  ``hedge_ms > 0`` is a fixed
    delay; ``hedge_ms == -1`` hedges at the router's observed p95 for
    the model (no observations yet → no hedge — never guess a tail);
    ``0`` disables."""
    if hedge_ms > 0:
        return float(hedge_ms)
    if hedge_ms == -1:
        return float(p95_ms) if p95_ms and p95_ms > 0 else None
    return None
