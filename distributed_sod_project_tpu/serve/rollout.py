"""Progressive checkpoint delivery — canary-gated rollout with
auto-rollback (docs/SERVING.md "Fleet control plane").

The engine's hot reload (serve/engine.py) is all-replicas-at-once: a
new VALID step lands and every replica's next poll swaps to it.  That
is exactly the deployment posture the prober was built to distrust — a
checkpoint can be bit-exact on disk and still predict garbage, and
with simultaneous swap the first scorer to notice is a user.  The
:class:`RolloutManager` replaces the swap with a state machine::

    idle ──new candidate step──▶ canary ──verdict──▶ promoting ─▶ idle
                                   │
                                   └──verdict fails──▶ rolled_back
                                        (step denylisted, canary
                                         reloaded to last-good)

ONE replica (the canary) reloads the candidate, bakes, and is scored
with the prober's ground-truth probe set (serve/prober.py) sent
DIRECTLY to it — plus the same probes against a stable baseline
replica, so the verdict is relative (a hard input set degrades both)
— and, when the quality monitors are armed, the canary's drift PSI.
Pass → every other replica reloads (promote).  Fail → the step is
pinned in the on-disk **denylist** (``reload_denylist.json`` next to
the checkpoints, honored by the engine's own reload poll and
``reload_to`` — the rollback cannot undo itself one poll later), the
canary reloads back to the last-good step, and the flight recorder
cuts an incident bundle.

Every verdict is booked through :meth:`RolloutManager._record` — THE
rollout accounting seam (tools/dsodlint.py ``BOOKING_SEAMS``) — and
surfaces as ``dsod_ctrl_rollout_*`` families on the router's /metrics
(rendered only while armed: ``rollout_ckpt_dir`` empty keeps /metrics
byte-identical).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

# Rollout state gauge encoding (the breaker STATE_GAUGE idiom:
# documented enum, stable across releases).
ROLLOUT_STATE_GAUGE = {"idle": 0, "canary": 1, "promoting": 2,
                       "rolled_back": 3}

_DENYLIST_NAME = "reload_denylist.json"


def _denylist_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, _DENYLIST_NAME)


def read_step_denylist(ckpt_dir: str) -> Dict[int, Dict]:
    """``{step: verdict_record}`` of steps pinned bad for ``ckpt_dir``
    (empty on a missing/empty/corrupt file — a torn denylist must not
    stop serving; the rollout rewrites it on the next verdict)."""
    if not ckpt_dir:
        return {}
    try:
        with open(_denylist_path(ckpt_dir)) as f:
            raw = json.load(f)
        return {int(k): dict(v) for k, v in raw.get("steps", {}).items()}
    except (OSError, ValueError, AttributeError):
        return {}


def deny_step(ckpt_dir: str, step: int, reason: str, **extra) -> None:
    """Pin ``step`` in the denylist (atomic tmp+rename, the
    publish_port idiom — a reader never sees a torn file)."""
    steps = {str(k): v for k, v in read_step_denylist(ckpt_dir).items()}
    steps[str(int(step))] = dict(extra, reason=reason,
                                 denied_at=time.time())
    path = _denylist_path(ckpt_dir)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"steps": steps}, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


class RolloutStats:
    """Thread-safe rollout telemetry: per-model state gauge, verdict
    counters, denylist depth, last canary score.  Owned by the
    :class:`RolloutManager`; rendered into the router's /metrics by
    ``Fleet._router_families`` while the rollout is armed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state: Dict[str, str] = {}
        self._verdicts: Dict[Tuple[str, str], int] = {}
        self._denylisted: Dict[str, int] = {}
        self._canary_mae: Dict[str, float] = {}

    def set_state(self, model: str, state: str) -> None:
        if state not in ROLLOUT_STATE_GAUGE:
            raise ValueError(f"unknown rollout state {state!r}")
        with self._lock:
            self._state[model] = state

    def set_denylisted(self, model: str, n: int) -> None:
        with self._lock:
            self._denylisted[model] = int(n)

    def set_canary_mae(self, model: str, mae: float) -> None:
        with self._lock:
            self._canary_mae[model] = float(mae)

    def inc_verdict(self, model: str, verdict: str) -> None:
        with self._lock:
            k = (model, verdict)
            self._verdicts[k] = self._verdicts.get(k, 0) + 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "state": dict(self._state),
                "verdicts": {f"{m}:{v}": n for (m, v), n
                             in sorted(self._verdicts.items())},
                "denylisted": dict(self._denylisted),
                "canary_mae": {m: round(v, 6) for m, v
                               in self._canary_mae.items()},
            }

    def prom_families(self):
        """``dsod_ctrl_rollout_*`` + denylist/canary families (state
        gauge always while armed; counters only once non-empty — the
        conditional-render idiom of RouterStats)."""
        with self._lock:
            state = sorted(self._state.items())
            verdicts = sorted(self._verdicts.items())
            deny = sorted(self._denylisted.items())
            mae = sorted(self._canary_mae.items())
        fams = [("dsod_ctrl_rollout_state", "gauge",
                 ['dsod_ctrl_rollout_state{model="%s"} %d'
                  % (m, ROLLOUT_STATE_GAUGE[s]) for m, s in state])]
        if verdicts:
            fams.append((
                "dsod_ctrl_rollout_verdicts_total", "counter",
                ['dsod_ctrl_rollout_verdicts_total'
                 '{model="%s",verdict="%s"} %d' % (m, v, n)
                 for (m, v), n in verdicts]))
        fams.append((
            "dsod_ctrl_denylisted_steps", "gauge",
            ['dsod_ctrl_denylisted_steps{model="%s"} %d' % (m, n)
             for m, n in deny]))
        if mae:
            fams.append((
                "dsod_ctrl_canary_mae", "gauge",
                ['dsod_ctrl_canary_mae{model="%s"} %g' % (m, v)
                 for m, v in mae]))
        return fams


class RolloutManager:
    """The checkpoint-delivery actuator for ONE replica set.

    Construction is side-effect free (no threads, no disk) so the
    Fleet can build it whenever ``rollout_ckpt_dir`` is set and the
    metrics surface is renderable without a running loop;
    :meth:`start` arms the poll thread, :meth:`tick` is one complete
    state-machine evaluation (tests drive it directly with
    ``rollout_bake_s=0``).

    Replicas under rollout management should serve with their OWN
    reload poll off (``serve.reload_poll_s=0``) — two actuators moving
    the same weights is the race this class exists to end — but even a
    replica that keeps polling cannot resurrect a rolled-back step:
    the denylist gates its poll too.
    """

    def __init__(self, fleet, cfg=None, clock=time.monotonic):
        cfg = cfg if cfg is not None else fleet.cfg
        if not cfg.rollout_ckpt_dir:
            raise ValueError("RolloutManager needs rollout_ckpt_dir")
        self.fleet = fleet
        self.cfg = cfg
        self.ckpt_dir = cfg.rollout_ckpt_dir
        self.model = cfg.rollout_model or next(iter(fleet.groups))
        self._clock = clock
        self.stats = RolloutStats()
        self.stats.set_state(self.model, "idle")
        self._lock = threading.Lock()
        self._state = "idle"
        self._last_good: Optional[int] = None
        self._adopted = False  # bootstrapped last_good from the fleet?
        # A canary that ERRORED (reload refused/transport died) is not
        # evidence against the STEP — no denylist, but back off before
        # retrying so a permanently unloadable replica set does not
        # hot-loop the canary dance every poll.
        self._error_step: Optional[int] = None
        self._error_at = 0.0
        self._mgr = None
        self._probes: Optional[List[Tuple[bytes, np.ndarray]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = get_logger()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "RolloutManager":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-rollout", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.rollout_bake_s + 30.0)
            self._thread = None
        with self._lock:
            mgr, self._mgr = self._mgr, None
        if mgr is not None:
            try:
                mgr.close()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.rollout_poll_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — keep delivering
                self._log.exception(
                    "rollout: tick failed; retrying next poll")

    # -- state ---------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def last_good(self) -> Optional[int]:
        with self._lock:
            return self._last_good

    def _set_state(self, state: str) -> None:
        with self._lock:
            self._state = state
        self.stats.set_state(self.model, state)

    def snapshot(self) -> Dict:
        with self._lock:
            out = {"state": self._state, "model": self.model,
                   "last_good": self._last_good,
                   "ckpt_dir": self.ckpt_dir}
        out["denylist"] = {str(k): v.get("reason", "")
                           for k, v in sorted(
                               read_step_denylist(self.ckpt_dir).items())}
        out.update(self.stats.snapshot())
        return out

    # -- booking seam --------------------------------------------------

    def _record(self, action: str, **attrs) -> None:
        """THE rollout booking seam (tools/dsodlint.py
        ``BOOKING_SEAMS``): every verdict counter increments here, and
        every decision leaves a typed flight-recorder event."""
        if action == "verdict":
            self.stats.inc_verdict(self.model, attrs.get("verdict", ""))
        rec = self.fleet.recorder
        if rec is not None:
            rec.event("rollout_" + action, model=self.model, **attrs)

    # -- the machine ---------------------------------------------------

    def tick(self) -> Optional[str]:
        """One complete state-machine evaluation; returns the action
        taken ("canary_promote" / "canary_rollback" / "canary_error")
        or None when there was nothing to deliver."""
        group = self.fleet.groups.get(self.model)
        if group is None:
            return None
        with self._lock:
            if self._mgr is None:
                from ..ckpt import CheckpointManager

                self._mgr = CheckpointManager(self.ckpt_dir,
                                              async_save=False)
            mgr = self._mgr
        mgr.reload()  # steps/denials land between polls
        deny = read_step_denylist(self.ckpt_dir)
        self.stats.set_denylisted(self.model, len(deny))
        steps = [s for s in mgr.valid_steps() if s not in deny]
        if not steps:
            return None
        cand = max(steps)
        if not self._adopted:
            # Bootstrap: replicas restored the newest step at startup —
            # adopt it as last-good instead of canarying what is
            # already serving fleet-wide.
            loaded = [self._member_step(b) for _rid, b in group.members]
            known = [s for s in loaded if s is not None]
            if known and all(s == cand for s in known):
                with self._lock:
                    self._last_good = cand
            self._adopted = True
        with self._lock:
            last_good = self._last_good
        if cand == last_good:
            if self.state not in ("idle",):
                self._set_state("idle")
            return None
        if cand == self._error_step and (
                self._clock() - self._error_at
                < 10.0 * self.cfg.rollout_poll_s):
            return None
        return self._run_canary(group, cand, steps)

    def _run_canary(self, group, step: int,
                    steps: List[int]) -> Optional[str]:
        cfg = self.cfg
        canary = None
        for rid, b in group.members:
            if b.healthy():
                canary = (rid, b)
                break
        if canary is None:
            return None  # nothing routable to canary on; next poll
        rid, backend = canary
        prev = self._member_step(backend)
        self._set_state("canary")
        self._record("canary", step=step, replica=rid)
        try:
            self.fleet.reload_replica(rid, step)
        except Exception as e:  # noqa: BLE001 — replica fault, not step
            self._error_step, self._error_at = step, self._clock()
            self._record("verdict", verdict="canary_error", step=step,
                         replica=rid, error=str(e)[:200])
            self._set_state("idle")
            return "canary_error"
        self._stop.wait(cfg.rollout_bake_s)
        mae, avail = self._probe_member(backend)
        self.stats.set_canary_mae(
            self.model, mae if math.isfinite(mae) else -1.0)
        base_mae = None
        for orid, ob in group.members:
            if orid != rid and ob.healthy():
                base_mae, _base_avail = self._probe_member(ob)
                break
        psi = self._canary_psi(backend)
        reasons = []
        if avail < cfg.rollout_min_avail:
            reasons.append(f"availability {avail:.2f} < "
                           f"{cfg.rollout_min_avail:.2f}")
        if not math.isfinite(mae):
            reasons.append("unscorable predictions")
        elif cfg.rollout_mae_max > 0 and mae > cfg.rollout_mae_max:
            reasons.append(f"mae {mae:.4f} > ceiling "
                           f"{cfg.rollout_mae_max:.4f}")
        if (base_mae is not None and math.isfinite(base_mae)
                and math.isfinite(mae)
                and mae - base_mae > cfg.rollout_mae_degrade):
            reasons.append(f"mae {mae:.4f} degrades baseline "
                           f"{base_mae:.4f} by more than "
                           f"{cfg.rollout_mae_degrade:.4f}")
        if (cfg.rollout_psi_max > 0 and psi is not None
                and psi > cfg.rollout_psi_max):
            reasons.append(f"psi {psi:.4f} > {cfg.rollout_psi_max:.4f}")
        if reasons:
            return self._rollback(group, step, rid, prev, steps,
                                  reasons, mae, base_mae)
        return self._promote(group, step, rid, mae, base_mae)

    def _promote(self, group, step: int, canary_rid: str,
                 mae: float, base_mae: Optional[float]) -> str:
        self._set_state("promoting")
        self._record("verdict", verdict="promote", step=step,
                     replica=canary_rid, mae=round(mae, 6),
                     baseline_mae=(round(base_mae, 6)
                                   if base_mae is not None else -1.0))
        failed = []
        for orid, ob in group.members:
            if orid == canary_rid:
                continue
            try:
                self.fleet.reload_replica(orid, step)
            except Exception as e:  # noqa: BLE001 — promote the rest
                failed.append(orid)
                self._record("promote_error", step=step, replica=orid,
                             error=str(e)[:200])
        with self._lock:
            self._last_good = step
        self._record("promote", step=step,
                     failed_replicas=",".join(failed))
        self._set_state("idle")
        return "canary_promote"

    def _rollback(self, group, step: int, canary_rid: str,
                  prev: Optional[int], steps: List[int],
                  reasons: List[str], mae: float,
                  base_mae: Optional[float]) -> str:
        reason = "; ".join(reasons)
        self._record("verdict", verdict="rollback", step=step,
                     replica=canary_rid, reason=reason,
                     mae=(round(mae, 6) if math.isfinite(mae) else -1.0),
                     baseline_mae=(round(base_mae, 6)
                                   if base_mae is not None else -1.0))
        deny_step(self.ckpt_dir, step, reason,
                  mae=(mae if math.isfinite(mae) else None),
                  replica=canary_rid)
        self.stats.set_denylisted(
            self.model, len(read_step_denylist(self.ckpt_dir)))
        with self._lock:
            last_good = self._last_good
        others = [s for s in steps if s != step]
        target = last_good if last_good is not None else prev
        if target is None and others:
            target = max(others)
        if target is not None:
            try:
                self.fleet.reload_replica(canary_rid, target)
            except Exception as e:  # noqa: BLE001 — evidence anyway
                self._record("rollback_error", step=step, target=target,
                             replica=canary_rid, error=str(e)[:200])
        self._record("rollback", step=step, replica=canary_rid,
                     target=(target if target is not None else -1),
                     reason=reason)
        rec = self.fleet.recorder
        if rec is not None:
            # The incident bundle: the ring around the verdict plus
            # every section snapshot — the rollback's evidence package.
            rec.trigger(f"rollout:{self.model}",
                        f"step {step} rolled back: {reason}"[:200],
                        background=True)
        self._set_state("rolled_back")
        return "canary_rollback"

    # -- replica IO ----------------------------------------------------

    def _member_step(self, backend) -> Optional[int]:
        """Which checkpoint step a replica is serving (None when
        unknown: random-init engine, unreachable remote, old remote)."""
        try:
            if backend.kind == "engine":
                return backend.engine._loaded_step
            step = backend.stats_snapshot().get("loaded_step")
            return int(step) if step is not None else None
        except Exception:  # noqa: BLE001 — unknown, not fatal
            return None

    def _probe_set(self) -> List[Tuple[bytes, np.ndarray]]:
        if self._probes is None:
            from .prober import make_probe_set

            self._probes = make_probe_set(self.cfg.rollout_probes,
                                          px=self.cfg.rollout_probe_px)
        return self._probes

    def _probe_member(self, backend) -> Tuple[float, float]:
        """Score ONE replica directly against the ground-truth probe
        set: ``(mean mae over answered probes, availability)``.
        Direct-to-replica on purpose — the router would round-robin
        the probes over the whole set and the verdict must isolate the
        canary."""
        import io

        from .prober import score_probe

        probes = self._probe_set()
        maes: List[float] = []
        answered = 0
        for body, gt in probes:
            try:
                if backend.kind == "engine":
                    img = np.load(io.BytesIO(body), allow_pickle=False)
                    pred, _meta = backend.engine.predict(
                        img, timeout=self.cfg.prober_timeout_s)
                else:
                    status, _hdrs, payload = backend.predict_raw(
                        body, {"Content-Type": "application/x-npy"},
                        timeout_s=self.cfg.prober_timeout_s)
                    if status != 200:
                        continue
                    pred = np.load(io.BytesIO(payload),
                                   allow_pickle=False)
                m, _iou = score_probe(np.asarray(pred, np.float32), gt)
            except Exception:  # noqa: BLE001 — an unanswered probe
                continue
            answered += 1
            if math.isfinite(m):
                maes.append(m)
            else:
                # A non-finite score is an answered-but-garbage probe:
                # it must sink the MAE verdict, not vanish from it.
                maes.append(float("inf"))
        avail = answered / len(probes) if probes else 0.0
        mae = (sum(maes) / len(maes)) if maes else float("inf")
        return mae, avail

    def _canary_psi(self, backend) -> Optional[float]:
        """Worst drift PSI on the canary (best-effort; None when the
        quality monitors are off or the remote predates them)."""
        try:
            if backend.kind == "engine":
                q = backend.engine.quality
                vals = q.psi_values() if q is not None else {}
            else:
                snap = backend.stats_snapshot().get("quality") or {}
                vals = snap.get("psi") or {}
            nums = [float(v) for v in vals.values()
                    if isinstance(v, (int, float))]
            return max(nums) if nums else None
        except Exception:  # noqa: BLE001 — telemetry, not policy
            return None
