"""Admission control: bounded-queue backpressure, SLO deadline expiry,
and hysteretic degraded mode (docs/SERVING.md "SLO semantics").

Philosophy — shed early, shed loudly: a request the service cannot
finish in time is cheapest to reject at the door (QueueFull, before any
host work) and second-cheapest to drop at the dispatch gate (expired,
before a device forward is wasted on an answer nobody is waiting for).
An overloaded service that queues unboundedly fails *every* request
late; one that sheds keeps its p99 for the requests it accepts.
"""

from __future__ import annotations

import time
from typing import Optional


class QueueFull(Exception):
    """Admission rejected the request: the bounded queue is at capacity.
    HTTP surface: 429."""


class DeadlineExpired(Exception):
    """The request could no longer meet its SLO deadline and was shed
    before the forward.  HTTP surface: 504."""


class EngineStopped(Exception):
    """The engine is not accepting work (stopped or unhealthy).
    HTTP surface: 503."""


class AdmissionController:
    """Queue-bound + degraded-mode policy for the serving engine.

    Degraded mode is a hysteretic **ladder** over observed queue depth:
    ``level`` runs 0 (full quality) .. ``max_level``, and each step —
    up or down — must EARN itself: the depth has to stay at or above
    ``high * max_queue`` for ``engage_s`` seconds to climb one level,
    and at or below ``low * max_queue`` for ``disengage_s`` seconds to
    descend one.  The timers reset at every transition, so a sustained
    overload walks the ladder one rung per ``engage_s`` (precision
    steps first, resolution last — the engine maps levels to actions)
    and recovery unwinds in reverse order, one rung per
    ``disengage_s``.  In between (the dead band) the current level
    holds.  ``max_level=1`` is the historical binary degraded mode.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        max_queue: int,
        *,
        high: float = 0.75,
        low: float = 0.25,
        engage_s: float = 2.0,
        disengage_s: float = 5.0,
        max_level: int = 1,
        clock=time.monotonic,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if not 0.0 <= low <= high <= 1.0:
            raise ValueError(
                f"need 0 <= low <= high <= 1, got low={low} high={high}")
        if max_level < 1:
            raise ValueError(f"max_level must be >= 1, got {max_level}")
        self.max_queue = int(max_queue)
        self.max_level = int(max_level)
        self._high = float(high) * self.max_queue
        self._low = float(low) * self.max_queue
        self._engage_s = float(engage_s)
        self._disengage_s = float(disengage_s)
        self._clock = clock
        self._level = 0
        # Time the depth first crossed into the (high / low) region it
        # is currently in; None = not in that region.  Reset on every
        # ladder transition: each further rung needs its own dwell.
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    # -- backpressure --------------------------------------------------

    def try_admit(self, queue_depth: int) -> None:
        """Raise :class:`QueueFull` when the bounded queue is full.
        Called at submit time, before any per-request host work."""
        if queue_depth >= self.max_queue:
            raise QueueFull(
                f"queue at capacity ({queue_depth}/{self.max_queue})")

    # -- SLO expiry ----------------------------------------------------

    @staticmethod
    def expired(deadline: Optional[float], est_device_s: float,
                now: float) -> bool:
        """True when a request with monotonic ``deadline`` can no longer
        meet it: even dispatching right now, the res bucket's estimated
        device time lands past the deadline.  ``deadline=None`` never
        expires."""
        if deadline is None:
            return False
        return now + max(est_device_s, 0.0) > deadline

    # -- degraded mode -------------------------------------------------

    def observe(self, queue_depth: int, now: Optional[float] = None) -> bool:
        """Feed one queue-depth observation; returns the (possibly
        updated) degraded flag (``level > 0`` — read :attr:`level` for
        the ladder rung).  Call periodically — the engine's dispatch
        loop does, including when idle."""
        now = self._clock() if now is None else now
        if queue_depth >= self._high:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (self._level < self.max_level
                    and now - self._above_since >= self._engage_s):
                self._level += 1
                self._above_since = now  # the next rung needs its own dwell
        elif queue_depth <= self._low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (self._level > 0
                    and now - self._below_since >= self._disengage_s):
                self._level -= 1
                self._below_since = now
        else:  # dead band: hold state, reset both region timers
            self._above_since = None
            self._below_since = None
        return self._level > 0

    @property
    def level(self) -> int:
        """Current ladder rung: 0 = full quality .. ``max_level``."""
        return self._level

    @property
    def degraded(self) -> bool:
        return self._level > 0
