"""Router-tier content-addressed response cache + in-flight request
coalescing + quality-gated near-dup serving (docs/SERVING.md "Router
cache"; ROADMAP item 3).

Real image traffic at millions-of-users scale is highly redundant —
reposted, resized, re-encoded images — yet without this layer every
request pays a full device forward.  The cache sits at the ROUTER door
(`serve/router.py`), in front of every engine:

- **exact arm** — a content-addressed, bounded-byte-budget LRU keyed
  on ``(sha256(payload), model, requested precision arm, loaded
  checkpoint step)``.  A hit returns the stored mask bytes without
  touching any engine.  The res bucket is a pure function of the
  payload for a fixed model config and the stored entry carries the
  bucket the response was actually served at, so the full ISSUE key
  (payload hash, model, res bucket, precision arm, step) is faithful.
  The **loaded checkpoint step is part of the key**: hot reload,
  rollout promotion, and denylist rollback all change the step, which
  makes every old entry unreachable instantly — there is no
  invalidation hook to forget, stale entries simply age out of the
  LRU.  Requests routed to a remote backend (step unknown at the
  router) BYPASS the cache entirely — staleness safety over hit rate.

- **in-flight coalescing** — concurrent identical payloads fold into
  ONE engine submit: the first becomes the *leader* and dispatches
  normally; the rest become *followers* that wait (bounded by their
  own residual deadline) for the leader's response and are each
  terminal-booked as ``cache_hit``.  A follower whose leader fails,
  times out, or produces a non-cacheable response FALLS THROUGH to its
  own normal dispatch — coalescing can only save work, never lose a
  request.

- **optional near-dup arm** — a 16×16 block-mean luminance perceptual
  hash (256-bit) indexes entries per (model, arm, step); a hit within
  the configured Hamming budget serves the stored mask
  resize-normalized (PIL bilinear) to the requester's dimensions.
  Quality is gated the precision-arm way: offline budget via
  ``tools/cache_gate.py`` (checked-in ``tools/cache_baseline.json``),
  online via shadow scoring — every Nth near-dup hit re-forwards
  through the engine off the request path (bounded in-flight, drops
  counted) and records the MAE between the served and fresh masks.

Only NON-DEGRADED 200s served at the requested arm are inserted: a
degraded response is a load artifact, not the model's answer for that
(payload, arm, step), and must never be replayed once the engine
recovers.

A cache hit is a **new terminal class**: ``serve/fleet.py`` extends
the router accounting identity to
``served + shed + expired + errors + cache_hit == submitted`` and the
booking seam (`RouterHandler._serve_cache_hit`) is registered in
dsodlint's BOOKING_SEAMS.  Everything here is off by default
(``fleet.cache_bytes = 0``): when disabled the fleet never constructs
a RouterCache, `/metrics` is byte-identical, and zero threads exist.

No jax import — this module runs on the router's request threads.
"""

from __future__ import annotations

import hashlib
import io
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

# Entries competing for the byte budget are body bytes plus real
# bookkeeping (key tuple, header strings, LRU node, phash index row) —
# charge a flat overhead so a flood of tiny payloads cannot blow the
# budget through bookkeeping alone.
ENTRY_OVERHEAD_BYTES = 512

# A near-dup Hamming scan is O(candidates); bound it so a huge cache
# cannot turn the miss path into a linear walk.  The exact-phash dict
# hit (Hamming 0) is O(1) and unaffected.
NEAR_SCAN_CAP = 512

PHASH_SIDE = 16
PHASH_BITS = PHASH_SIDE * PHASH_SIDE


def payload_cache_key(body: bytes, model: str, precision: Optional[str],
                      step: int) -> Tuple[str, str, str, int]:
    """The exact-arm lookup key.  ``precision`` is the REQUESTED arm
    ("" when the request left it to the server default); the degraded
    ladder never pollutes the key because degraded responses are never
    inserted."""
    return (hashlib.sha256(body).hexdigest(), str(model),
            str(precision or ""), int(step))


def payload_fingerprint(body: bytes):
    """``(phash, (h, w))`` of an x-npy request payload, or ``None``
    when the body does not decode to a 2-D/3-D image.

    The phash is a 256-bit block-mean luminance average-hash: the
    image's channel-mean is reduced to a 16×16 grid of true block
    means (integral-free ``np.add.reduceat`` with per-block area
    normalization, robust across resizes), thresholded at the grid
    mean.  Pure numpy, a few hundred microseconds at request sizes.
    """
    try:
        arr = np.load(io.BytesIO(body), allow_pickle=False)
    except Exception:  # noqa: BLE001 — malformed body: no fingerprint
        return None
    a = np.asarray(arr)
    if a.ndim == 3:
        a = a.mean(axis=2)
    if a.ndim != 2:
        return None
    h, w = int(a.shape[0]), int(a.shape[1])
    if h < PHASH_SIDE or w < PHASH_SIDE:
        return None
    a = a.astype(np.float32, copy=False)
    yb = (np.arange(PHASH_SIDE) * h) // PHASH_SIDE
    xb = (np.arange(PHASH_SIDE) * w) // PHASH_SIDE
    sums = np.add.reduceat(np.add.reduceat(a, yb, axis=0), xb, axis=1)
    ylen = np.diff(np.append(yb, h)).astype(np.float32)
    xlen = np.diff(np.append(xb, w)).astype(np.float32)
    means = sums / (ylen[:, None] * xlen[None, :])
    bits = (means > means.mean()).ravel()
    v = 0
    for b in bits:
        v = (v << 1) | int(b)
    return v, (h, w)


def hamming(a: int, b: int) -> int:
    return bin(a ^ b).count("1")


def _decode_mask(body: bytes) -> np.ndarray:
    return np.asarray(np.load(io.BytesIO(body), allow_pickle=False),
                      np.float32)


def _encode_mask(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr, np.float32))
    return buf.getvalue()


def resize_mask_body(body: bytes, hw: Tuple[int, int]) -> bytes:
    """Resize a stored x-npy mask body to the requester's ``(h, w)``
    (PIL bilinear, the same resampler eval uses) — the near-dup arm's
    resize normalization.  Returns ``body`` unchanged when the
    dimensions already match."""
    mask = _decode_mask(body)
    if mask.shape == tuple(hw):
        return body
    from PIL import Image

    im = Image.fromarray((np.clip(mask, 0.0, 1.0) * 255.0)
                         .astype(np.uint8))
    im = im.resize((int(hw[1]), int(hw[0])), Image.BILINEAR)
    return _encode_mask(np.asarray(im, np.float32) / 255.0)


@dataclass
class CacheEntry:
    """One cached 200: the mask bytes plus the response headers a hit
    must reproduce.  ``step`` / ``phash`` ride along for the index
    bookkeeping (eviction must drop the phash row it owns)."""

    body: bytes
    content_type: str
    precision: str
    res_bucket: str
    model: str
    step: int
    phash: Optional[int] = None

    @property
    def cost(self) -> int:
        return len(self.body) + ENTRY_OVERHEAD_BYTES


class _Inflight:
    """Coalescing token: the leader resolves it with its CacheEntry
    (or ``None`` — failure / non-cacheable response) and every
    follower wakes."""

    __slots__ = ("event", "entry", "followers")

    def __init__(self):
        self.event = threading.Event()
        self.entry: Optional[CacheEntry] = None
        self.followers = 0


@dataclass
class CacheStats:
    """Lock-guarded cache counters → /stats snapshot + dsod_cache_*
    prom families (rendered by :meth:`RouterCache.prom_families` so
    the gauges can read the LRU's live totals)."""

    _lock: threading.Lock = field(default_factory=threading.Lock)
    hits: Dict[Tuple[str, str], int] = field(default_factory=dict)
    misses: Dict[str, int] = field(default_factory=dict)
    coalesced: Dict[str, int] = field(default_factory=dict)
    inserts: Dict[str, int] = field(default_factory=dict)
    evictions: int = 0
    shadow_total: int = 0
    shadow_dropped: int = 0
    shadow_mae_sum: float = 0.0

    def inc_hit(self, model: str, kind: str) -> None:
        with self._lock:
            k = (model, kind)
            self.hits[k] = self.hits.get(k, 0) + 1

    def inc_miss(self, model: str) -> None:
        with self._lock:
            self.misses[model] = self.misses.get(model, 0) + 1

    def inc_coalesced(self, model: str) -> None:
        with self._lock:
            self.coalesced[model] = self.coalesced.get(model, 0) + 1

    def inc_insert(self, model: str) -> None:
        with self._lock:
            self.inserts[model] = self.inserts.get(model, 0) + 1

    def inc_evictions(self, n: int = 1) -> None:
        with self._lock:
            self.evictions += n

    def record_shadow(self, mae: float) -> None:
        with self._lock:
            self.shadow_total += 1
            self.shadow_mae_sum += float(mae)

    def record_shadow_dropped(self) -> None:
        with self._lock:
            self.shadow_dropped += 1

    def snapshot(self) -> Dict:
        with self._lock:
            hits = {}
            for (model, kind), n in self.hits.items():
                hits.setdefault(model, {})[kind] = n
            out = {
                "hits": hits,
                "misses": dict(self.misses),
                "coalesced": dict(self.coalesced),
                "inserts": dict(self.inserts),
                "evictions": self.evictions,
                "hits_total": sum(self.hits.values()),
                "misses_total": sum(self.misses.values()),
            }
            if self.shadow_total or self.shadow_dropped:
                out["shadow"] = {
                    "total": self.shadow_total,
                    "dropped": self.shadow_dropped,
                    "mae_avg": (self.shadow_mae_sum / self.shadow_total
                                if self.shadow_total else 0.0),
                }
            return out

    def raw(self) -> Dict:
        """One consistent copy of every counter (the prom render reads
        this instead of reaching into the lock)."""
        with self._lock:
            return {
                "hits": dict(self.hits), "misses": dict(self.misses),
                "coalesced": dict(self.coalesced),
                "inserts": dict(self.inserts),
                "evictions": self.evictions,
                "shadow_total": self.shadow_total,
                "shadow_dropped": self.shadow_dropped,
                "shadow_mae_sum": self.shadow_mae_sum,
            }


class RouterCache:
    """The router-door cache.  Thread-safe; all request-path work is a
    hash + dict ops under one lock (the near-dup fingerprint is pure
    numpy computed OUTSIDE the lock).

    Request-path protocol (`RouterHandler.do_POST`):

    ``begin(model, body, precision, step)`` → ``(verdict, obj)``:

    - ``("exact", entry)`` / ``("near", (entry, hw))`` — serve the
      stored bytes (near: resize-normalize to ``hw`` first), book
      ``cache_hit``, done.  No engine is touched.
    - ``("follower", token)`` — an identical payload is already in
      flight; wait on ``token.event`` up to the residual deadline,
      then ``token.entry`` is the leader's cacheable response (serve
      it, book ``cache_hit``) or ``None`` (fall through to a normal
      dispatch).
    - ``("leader", handle)`` — dispatch normally, then call
      ``complete(handle, code=..., headers=..., body=...)`` with
      whatever was sent to the client (or ``abandon(handle)`` on any
      non-response path) so followers wake and the LRU fills.
    """

    def __init__(self, max_bytes: int, *, coalesce: bool = True,
                 near_dup: bool = False, near_hamming: int = 0,
                 shadow_sample: int = 0, shadow_inflight: int = 2):
        self.max_bytes = int(max_bytes)
        self.coalesce = bool(coalesce)
        self.near_dup = bool(near_dup)
        self.near_hamming = int(near_hamming)
        self.shadow_sample = int(shadow_sample)
        self.stats = CacheStats()
        self._lock = threading.Lock()
        self._lru: "OrderedDict[Tuple, CacheEntry]" = OrderedDict()
        self._bytes = 0
        # (model, precision, step, phash) -> exact key, for O(1)
        # Hamming-0 near hits; the Hamming>0 scan walks its values.
        self._phash: Dict[Tuple, Tuple] = {}
        self._inflight: Dict[Tuple, _Inflight] = {}
        self._near_seen = 0
        self._shadow_sem = threading.BoundedSemaphore(
            max(1, int(shadow_inflight)))

    # -- request path --------------------------------------------------

    def begin(self, model: str, body: bytes, precision: Optional[str],
              step: int):
        key = payload_cache_key(body, model, precision, step)
        with self._lock:
            ent = self._lru.get(key)
            if ent is not None:
                self._lru.move_to_end(key)
                self.stats.inc_hit(model, "exact")
                return "exact", ent
        ph = None
        if self.near_dup:
            fp = payload_fingerprint(body)
            if fp is not None:
                ph, hw = fp
                ent = self._near_lookup(model, precision, step, ph)
                if ent is not None:
                    self.stats.inc_hit(model, "near")
                    return "near", (ent, hw)
        self.stats.inc_miss(model)
        if not self.coalesce:
            return "leader", (key, None, ph)
        with self._lock:
            tok = self._inflight.get(key)
            if tok is not None:
                tok.followers += 1
                return "follower", tok
            tok = _Inflight()
            self._inflight[key] = tok
            return "leader", (key, tok, ph)

    def _near_lookup(self, model: str, precision: Optional[str],
                     step: int, ph: int) -> Optional[CacheEntry]:
        prefix = (str(model), str(precision or ""), int(step))
        with self._lock:
            key = self._phash.get(prefix + (ph,))
            if key is not None:
                ent = self._lru.get(key)
                if ent is not None:
                    self._lru.move_to_end(key)
                    return ent
            if self.near_hamming > 0:
                for pk, key in list(self._phash.items())[:NEAR_SCAN_CAP]:
                    if pk[:3] != prefix:
                        continue
                    if hamming(pk[3], ph) <= self.near_hamming:
                        ent = self._lru.get(key)
                        if ent is not None:
                            self._lru.move_to_end(key)
                            return ent
        return None

    def complete(self, handle, *, code: int, headers: Dict[str, str],
                 body: Optional[bytes], model: str) -> None:
        """Leader epilogue: insert the response if cacheable, then wake
        followers.  ``headers`` are the response headers actually sent
        (the `_send_capture` tee in serve/server.py)."""
        key, tok, ph = handle
        entry = None
        if (code == 200 and body
                and str(headers.get("X-Degraded", "0")) in ("", "0")
                and headers.get("Content-Type") == "application/x-npy"):
            entry = CacheEntry(
                body=bytes(body),
                content_type="application/x-npy",
                precision=str(headers.get("X-Precision", "")),
                res_bucket=str(headers.get("X-Res-Bucket", "")),
                model=str(model), step=key[3], phash=ph)
            self._insert(key, entry)
            self.stats.inc_insert(model)
        self._resolve(key, tok, entry)

    def abandon(self, handle) -> None:
        """Leader died without a response (exception, shed, expiry…):
        wake followers empty-handed so they fall through to their own
        dispatch."""
        key, tok, _ph = handle
        self._resolve(key, tok, None)

    def _resolve(self, key, tok: Optional[_Inflight],
                 entry: Optional[CacheEntry]) -> None:
        if tok is None:
            return
        with self._lock:
            if self._inflight.get(key) is tok:
                del self._inflight[key]
        tok.entry = entry
        tok.event.set()

    # -- store ---------------------------------------------------------

    def _insert(self, key, entry: CacheEntry) -> None:
        if self.max_bytes <= 0:
            return
        if entry.cost > self.max_bytes:
            return  # larger than the whole budget: never cacheable
        evicted = 0
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= old.cost
                self._drop_phash(key, old)
            self._lru[key] = entry
            self._bytes += entry.cost
            if entry.phash is not None:
                # Index prefix mirrors the LOOKUP key components (the
                # REQUESTED arm, not the served X-Precision) so hits
                # and inserts agree on "" meaning server-default.
                self._phash[(key[1], key[2], key[3],
                             entry.phash)] = key
            while self._bytes > self.max_bytes and self._lru:
                k, e = self._lru.popitem(last=False)
                self._bytes -= e.cost
                self._drop_phash(k, e)
                evicted += 1
        if evicted:
            self.stats.inc_evictions(evicted)

    def _drop_phash(self, key, entry: CacheEntry) -> None:
        if entry.phash is None:
            return
        pk = (key[1], key[2], key[3], entry.phash)
        if self._phash.get(pk) == key:
            del self._phash[pk]

    # -- near-dup shadow gate ------------------------------------------

    def should_shadow(self) -> bool:
        """Deterministic every-Nth sampling of near-dup hits for the
        online quality gate (PR 10 discipline: sampled, bounded,
        drop-counted — never queued behind live traffic)."""
        if self.shadow_sample <= 0:
            return False
        with self._lock:
            self._near_seen += 1
            return self._near_seen % self.shadow_sample == 0

    def submit_shadow(self, body: bytes, served_body: bytes,
                      forward) -> None:
        """Score one near-dup hit off the request path: re-forward the
        ACTUAL request through ``forward(image) -> (pred, meta)`` (the
        engine's blocking predict — booked in the engine's own book
        like any direct submit, never the router book) and record the
        MAE between the served mask and the fresh one.  Bounded
        in-flight; saturated → dropped and counted."""
        if not self._shadow_sem.acquire(blocking=False):
            self.stats.record_shadow_dropped()
            return
        t = threading.Thread(
            target=self._shadow_run, args=(body, served_body, forward),
            name="cache-shadow", daemon=True)
        t.start()

    def _shadow_run(self, body: bytes, served_body: bytes, forward):
        try:
            img = np.load(io.BytesIO(body), allow_pickle=False)
            pred, _meta = forward(img)
            served = _decode_mask(served_body)
            fresh = np.asarray(pred, np.float32)
            if fresh.shape != served.shape:
                served = _decode_mask(
                    resize_mask_body(served_body, fresh.shape[:2]))
            self.stats.record_shadow(
                float(np.mean(np.abs(fresh - served))))
        except Exception:  # noqa: BLE001 — telemetry must not throw
            self.stats.record_shadow_dropped()
        finally:
            self._shadow_sem.release()

    # -- observability -------------------------------------------------

    def snapshot(self) -> Dict:
        out = self.stats.snapshot()
        with self._lock:
            out["bytes"] = self._bytes
            out["entries"] = len(self._lru)
            out["max_bytes"] = self.max_bytes
            out["inflight"] = len(self._inflight)
        out["near_dup"] = self.near_dup
        return out

    def prom_families(self, labels: str = ""):
        """dsod_cache_* families for the fleet /metrics render —
        merged through the same merge_prom_families machinery as every
        other family group, so TYPE appears once per family however
        many groups contribute."""
        from ..utils.observability import _merge_labels

        raw = self.stats.raw()
        with self._lock:
            nbytes = self._bytes
            entries = len(self._lru)

        def line(name, value, extra=""):
            lbl = _merge_labels(labels, extra)
            if lbl:
                return f"{name}{{{lbl}}} {value}"
            return f"{name} {value}"

        fams = [
            ("dsod_cache_hits_total", "counter",
             [line("dsod_cache_hits_total", n,
                   'model="%s",kind="%s"' % (m, k))
              for (m, k), n in sorted(raw["hits"].items())]),
            ("dsod_cache_misses_total", "counter",
             [line("dsod_cache_misses_total", n, 'model="%s"' % m)
              for m, n in sorted(raw["misses"].items())]),
            ("dsod_cache_coalesced_total", "counter",
             [line("dsod_cache_coalesced_total", n, 'model="%s"' % m)
              for m, n in sorted(raw["coalesced"].items())]),
            ("dsod_cache_inserts_total", "counter",
             [line("dsod_cache_inserts_total", n, 'model="%s"' % m)
              for m, n in sorted(raw["inserts"].items())]),
            ("dsod_cache_evictions_total", "counter",
             [line("dsod_cache_evictions_total", raw["evictions"])]),
            ("dsod_cache_bytes", "gauge",
             [line("dsod_cache_bytes", nbytes)]),
            ("dsod_cache_entries", "gauge",
             [line("dsod_cache_entries", entries)]),
        ]
        if self.near_dup:
            total = raw["shadow_total"]
            mae = (raw["shadow_mae_sum"] / total) if total else 0.0
            fams += [
                ("dsod_cache_shadow_total", "counter",
                 [line("dsod_cache_shadow_total", total)]),
                ("dsod_cache_shadow_dropped_total", "counter",
                 [line("dsod_cache_shadow_dropped_total",
                       raw["shadow_dropped"])]),
                ("dsod_cache_shadow_mae_avg", "gauge",
                 [line("dsod_cache_shadow_mae_avg", round(mae, 6))]),
            ]
        return fams
