"""Online serving subsystem (docs/SERVING.md).

Turns the repo's offline eval path into a request-facing service: a
dynamic micro-batching engine that coalesces arbitrary-time,
arbitrary-size requests into the fixed-shape compiled programs
evaluation already uses, behind a stdlib HTTP front end with admission
control, SLO deadline shedding, hot weight reload, and Prometheus
telemetry.
"""

from .admission import (
    AdmissionController,
    DeadlineExpired,
    EngineStopped,
    QueueFull,
)
from .batcher import DynamicBatcher, Request
from .engine import InferenceEngine, preprocess_image
from .failover import CircuitBreaker, RetryPolicy, pick_hedge_delay
from .fleet import (EngineBackend, Fleet, FleetDispatcher, RemoteBackend,
                    ReplicaSet)
from .precision import (
    PRECISION_ORDER,
    cast_variables,
    make_precision_forward,
    step_down,
    supported_arms,
    validate_arms,
)
from .router import (
    RouterStats,
    TenantAdmission,
    TokenBucket,
    make_fleet_server,
    serve_fleet_forever,
)
from .server import make_server

__all__ = [
    "AdmissionController",
    "CircuitBreaker",
    "DeadlineExpired",
    "DynamicBatcher",
    "EngineBackend",
    "EngineStopped",
    "Fleet",
    "FleetDispatcher",
    "InferenceEngine",
    "PRECISION_ORDER",
    "QueueFull",
    "RemoteBackend",
    "ReplicaSet",
    "Request",
    "RetryPolicy",
    "RouterStats",
    "TenantAdmission",
    "TokenBucket",
    "cast_variables",
    "make_fleet_server",
    "make_precision_forward",
    "make_server",
    "pick_hedge_delay",
    "preprocess_image",
    "serve_fleet_forever",
    "step_down",
    "supported_arms",
    "validate_arms",
]
