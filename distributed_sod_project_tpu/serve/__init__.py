"""Online serving subsystem (docs/SERVING.md).

Turns the repo's offline eval path into a request-facing service: a
dynamic micro-batching engine that coalesces arbitrary-time,
arbitrary-size requests into the fixed-shape compiled programs
evaluation already uses, behind a stdlib HTTP front end with admission
control, SLO deadline shedding, hot weight reload, and Prometheus
telemetry.
"""

from .admission import (
    AdmissionController,
    DeadlineExpired,
    EngineStopped,
    QueueFull,
)
from .batcher import DynamicBatcher, Request
from .engine import InferenceEngine, preprocess_image
from .precision import (
    PRECISION_ORDER,
    cast_variables,
    make_precision_forward,
    step_down,
    supported_arms,
    validate_arms,
)
from .server import make_server

__all__ = [
    "AdmissionController",
    "DeadlineExpired",
    "DynamicBatcher",
    "EngineStopped",
    "InferenceEngine",
    "PRECISION_ORDER",
    "QueueFull",
    "Request",
    "cast_variables",
    "make_precision_forward",
    "make_server",
    "preprocess_image",
    "step_down",
    "supported_arms",
    "validate_arms",
]
