"""Per-stream session state for streaming-video SOD serving
(docs/SERVING.md "Streaming"; ROADMAP item 5).

The serving stack was built for latency-sensitive sustained traffic,
yet until this module every request was independent: a client pushing
30 frames/s of the SAME scene paid a full device forward per frame and
could land on a different replica every time.  An ``X-Stream-ID``
header now opens a **stream session** at the router door:

- **bounded + TTL-evicted** — at most ``fleet.stream_sessions``
  concurrent sessions; a session idle past ``fleet.stream_ttl_s`` is
  evicted (LRU order).  A NEW stream past the cap sheds loudly at the
  door (429 ``kind=stream_budget``) — live sessions are never silently
  evicted to make room, because a session holds client-visible state.

- **warm state** — the previous frame's mask bytes (+ the response
  headers a replay must reproduce), its 256-bit perceptual hash
  (serve/cache.py machinery), and per-stream latency/freshness stats.

- **replica affinity** — the session records the replica that served
  its last frame; the router pins subsequent frames to it so warm
  state (engine-side batcher affinity, compiled-program residency)
  never crosses replicas.  When the home replica dies the session
  RE-HOMES to the next healthy pick and the move is counted
  (``dsod_stream_rehomed_total``) — failover is visible, not silent.

- **temporal-coherence fast path** — when a frame's phash is within
  ``fleet.stream_reuse_hamming`` Hamming bits of the stream's previous
  frame, the previous mask is served WITHOUT a forward: a sixth
  terminal class ``stream_reuse`` in the router book
  (``served + shed + expired + errors + cache_hit + stream_reuse ==
  submitted``).  Quality is gated the precision-arm way: offline by
  ``tools/stream_gate.py`` (checked-in ``tools/stream_baseline.json``
  delta ledger over synthetic perturbed sequences) and online by the
  cache shadow monitors watching temporal MAE.

- **EMA mask blend** — optional flicker damping: a FULL forward for a
  stream with a previous same-shape mask returns
  ``blend*prev + (1-blend)*new``.  Off by default so full forwards
  stay bitwise the engine's own answer.

Everything is off by default (``fleet.stream_sessions = 0``): the
fleet never constructs a StreamTable, `/metrics` is byte-identical,
the batcher never sees a stream key, and zero threads exist.

No jax import — this module runs on the router's request threads.
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from .cache import _decode_mask, _encode_mask, hamming

# X-Stream-ID values become label-ish internal keys; constrain them the
# way tenant names are constrained so a hostile header can't become an
# unbounded/binary key.  Longer ids are truncated (prefix keeps
# per-client uniqueness in practice); empty-after-sanitize ids are
# treated as "no stream".
STREAM_ID_MAX = 64
_STREAM_ID_RE = re.compile(r"[^A-Za-z0-9_.:-]")


def sanitize_stream_id(raw: Optional[str]) -> Optional[str]:
    """A bounded, charset-safe session key from a client header, or
    None when the header is absent/empty (the request then rides the
    normal independent path)."""
    if not raw:
        return None
    sid = _STREAM_ID_RE.sub("_", str(raw).strip())[:STREAM_ID_MAX]
    return sid or None


@dataclass
class StreamSession:
    """One client stream's warm state.  Mutated only under the owning
    :class:`StreamTable`'s lock."""

    stream_id: str
    opened_at: float
    last_seen: float
    # Replica currently holding the stream's warm state (batcher
    # affinity + compiled-program residency); None until first dispatch.
    home_rid: Optional[str] = None
    # Previous frame's fingerprint + served mask (the replay a
    # temporal-coherence hit returns).
    phash: Optional[int] = None
    mask_body: Optional[bytes] = field(default=None, repr=False)
    content_type: str = "application/x-npy"
    precision: str = ""
    res_bucket: str = ""
    # Per-stream stats: frames served, fast-path reuses, re-homes, an
    # EWMA of end-to-end latency, and the previous frame's wall time
    # (freshness: how stale a reuse answer can be).
    frames: int = 0
    reused: int = 0
    rehomes: int = 0
    lat_ewma_ms: float = 0.0
    last_frame_t: float = 0.0

    def snapshot(self, now: float) -> Dict:
        return {
            "stream": self.stream_id,
            "home": self.home_rid,
            "frames": self.frames,
            "reused": self.reused,
            "rehomes": self.rehomes,
            "lat_ewma_ms": round(self.lat_ewma_ms, 3),
            "idle_s": round(max(0.0, now - self.last_seen), 3),
            "age_s": round(max(0.0, now - self.opened_at), 3),
        }


@dataclass
class StreamStats:
    """Lock-guarded aggregate counters → /stats snapshot +
    dsod_stream_* prom families (rendered by
    :meth:`StreamTable.prom_families` so the session gauge can read the
    table's live size)."""

    _lock: threading.Lock = field(default_factory=threading.Lock)
    opened: int = 0
    expired: int = 0
    frames: int = 0
    reused: int = 0
    rehomed: int = 0
    budget_shed: int = 0
    blended: int = 0

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def raw(self) -> Dict:
        with self._lock:
            return {
                "opened": self.opened, "expired": self.expired,
                "frames": self.frames, "reused": self.reused,
                "rehomed": self.rehomed,
                "budget_shed": self.budget_shed,
                "blended": self.blended,
            }


class StreamTable:
    """The router-door session table.  Thread-safe; every request-path
    operation is dict/OrderedDict work under one lock (phash and blend
    math run OUTSIDE it, on bytes the caller owns).

    Request-path protocol (`RouterHandler.do_POST`):

    - ``touch(stream_id)`` → ``("ok", session)`` (existing or newly
      opened, LRU-refreshed) or ``("budget", None)`` — the table is
      full of LIVE sessions, shed 429 ``kind=stream_budget``.
    - ``reuse_body(session, phash)`` → previous mask bytes when the
      temporal-coherence fast path applies, else None.
    - ``note_result(...)`` after a full forward: store the served mask
      + fingerprint, update latency/freshness stats.
    - ``pin(session, rid)`` / re-home accounting when failover moves
      the stream.
    """

    def __init__(self, max_sessions: int, ttl_s: float, *,
                 reuse_hamming: int = 0, ema_blend: float = 0.0,
                 clock=time.monotonic):
        if max_sessions < 1:
            raise ValueError(
                f"StreamTable needs max_sessions >= 1, got {max_sessions}")
        self.max_sessions = int(max_sessions)
        self.ttl_s = float(ttl_s)
        self.reuse_hamming = int(reuse_hamming)
        self.ema_blend = float(ema_blend)
        self.stats = StreamStats()
        self._clock = clock
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, StreamSession]" = OrderedDict()

    # -- session lifecycle ---------------------------------------------

    def _evict_expired_locked(self, now: float) -> None:  # dsodlint: disable=accounting-seams -- StreamStats.expired counts session evictions (dsod_stream_expired_total), not the request-terminal book
        expired = 0
        while self._sessions:
            sid, sess = next(iter(self._sessions.items()))
            if now - sess.last_seen < self.ttl_s:
                break
            del self._sessions[sid]
            expired += 1
        if expired:
            self.stats.inc("expired", expired)

    def touch(self, stream_id: str
              ) -> Tuple[str, Optional[StreamSession]]:
        now = self._clock()
        with self._lock:
            self._evict_expired_locked(now)
            sess = self._sessions.get(stream_id)
            if sess is not None:
                sess.last_seen = now
                self._sessions.move_to_end(stream_id)
                return "ok", sess
            if len(self._sessions) >= self.max_sessions:
                self.stats.inc("budget_shed")
                return "budget", None
            sess = StreamSession(stream_id=stream_id, opened_at=now,
                                 last_seen=now)
            self._sessions[stream_id] = sess
            self.stats.inc("opened")
            return "ok", sess

    def get(self, stream_id: str) -> Optional[StreamSession]:
        with self._lock:
            return self._sessions.get(stream_id)

    # -- replica affinity ----------------------------------------------

    def pin(self, sess: StreamSession, rid: str) -> None:
        """Record (or move) the stream's home replica.  A move on an
        already-homed session is a RE-HOME (failover) and is counted."""
        with self._lock:
            if sess.home_rid is not None and sess.home_rid != rid:
                sess.rehomes += 1
                self.stats.inc("rehomed")
            sess.home_rid = rid

    # -- temporal-coherence fast path ----------------------------------

    def reuse_body(self, sess: StreamSession,
                   phash: Optional[int]) -> Optional[bytes]:
        """The previous mask bytes when the frame is temporally
        coherent with the stream's previous frame, else None.  The
        caller books ``stream_reuse`` and replays the stored headers."""
        if self.reuse_hamming <= 0 or phash is None:
            return None
        with self._lock:
            if sess.phash is None or sess.mask_body is None:
                return None
            if hamming(sess.phash, phash) > self.reuse_hamming:
                return None
            return sess.mask_body

    def note_reuse(self, sess: StreamSession, latency_ms: float) -> None:
        now = self._clock()
        with self._lock:
            sess.frames += 1
            sess.reused += 1
            sess.last_frame_t = now
            sess.lat_ewma_ms = (latency_ms if sess.lat_ewma_ms == 0.0
                                else 0.8 * sess.lat_ewma_ms
                                + 0.2 * latency_ms)
        self.stats.inc("frames")
        self.stats.inc("reused")

    # -- full-forward epilogue -----------------------------------------

    def blend_body(self, sess: StreamSession,
                   body: bytes) -> Tuple[bytes, bool]:
        """EMA mask blend for flicker damping: ``blend*prev +
        (1-blend)*new`` when armed and the previous mask has the same
        shape.  Returns ``(body, blended?)`` — the returned body is
        what the client gets AND what the session stores, so the EMA
        compounds across frames the way flicker damping needs."""
        if self.ema_blend <= 0.0:
            return body, False
        with self._lock:
            prev = sess.mask_body
        if prev is None:
            return body, False
        try:
            new = _decode_mask(body)
            old = _decode_mask(prev)
            if new.shape != old.shape:
                return body, False
            a = np.float32(self.ema_blend)
            out = _encode_mask(a * old + (np.float32(1.0) - a) * new)
        except Exception:  # noqa: BLE001 — damping must not lose a frame
            return body, False
        self.stats.inc("blended")
        return out, True

    def note_result(self, sess: StreamSession, *, body: bytes,
                    content_type: str, precision: str, res_bucket: str,
                    phash: Optional[int], latency_ms: float) -> None:
        """Store a full forward's served mask as the stream's new warm
        state (only non-degraded 200 x-npy bodies reach here — the
        caller applies the same cacheability rule as RouterCache)."""
        now = self._clock()
        with self._lock:
            sess.mask_body = bytes(body)
            sess.content_type = str(content_type)
            sess.precision = str(precision)
            sess.res_bucket = str(res_bucket)
            sess.phash = phash
            sess.frames += 1
            sess.last_frame_t = now
            sess.lat_ewma_ms = (latency_ms if sess.lat_ewma_ms == 0.0
                                else 0.8 * sess.lat_ewma_ms
                                + 0.2 * latency_ms)
        self.stats.inc("frames")

    # -- observability -------------------------------------------------

    def snapshot(self) -> Dict:
        now = self._clock()
        out = dict(self.stats.raw())
        with self._lock:
            out["sessions"] = len(self._sessions)
            out["max_sessions"] = self.max_sessions
            out["ttl_s"] = self.ttl_s
            out["reuse_hamming"] = self.reuse_hamming
            out["per_stream"] = [s.snapshot(now) for s in
                                 list(self._sessions.values())[-16:]]
        return out

    def prom_families(self, labels: str = ""):
        """dsod_stream_* families for the fleet /metrics render —
        appended by `Fleet._router_families` ONLY when streaming is
        armed, so the off-path rendering stays byte-identical."""
        from ..utils.observability import _merge_labels

        raw = self.stats.raw()
        with self._lock:
            live = len(self._sessions)

        def line(name, value, extra=""):
            lbl = _merge_labels(labels, extra)
            if lbl:
                return f"{name}{{{lbl}}} {value}"
            return f"{name} {value}"

        return [
            ("dsod_stream_sessions", "gauge",
             [line("dsod_stream_sessions", live)]),
            ("dsod_stream_opened_total", "counter",
             [line("dsod_stream_opened_total", raw["opened"])]),
            ("dsod_stream_expired_total", "counter",
             [line("dsod_stream_expired_total", raw["expired"])]),
            ("dsod_stream_frames_total", "counter",
             [line("dsod_stream_frames_total", raw["frames"])]),
            ("dsod_stream_reused_total", "counter",
             [line("dsod_stream_reused_total", raw["reused"])]),
            ("dsod_stream_rehomed_total", "counter",
             [line("dsod_stream_rehomed_total", raw["rehomed"])]),
            ("dsod_stream_budget_shed_total", "counter",
             [line("dsod_stream_budget_shed_total",
                   raw["budget_shed"])]),
            ("dsod_stream_blended_total", "counter",
             [line("dsod_stream_blended_total", raw["blended"])]),
        ]
