"""The serving engine: compiled-program cache + dynamic batching +
hot weight reload (docs/SERVING.md).

TF-Replicator's thesis (PAPERS.md) applied to serving: the user-facing
abstraction is thin — ``submit(image) -> Future`` — and everything
underneath maps onto the fixed-shape compiled programs the eval path
already owns.  Three device-facing invariants:

- **No request-time compilation.**  Every (resolution bucket, batch
  bucket, precision arm) program is AOT-compiled at startup via
  ``jax.jit(...).lower().compile()`` from the SAME ``make_forward`` the
  offline eval uses (quantized arms route through
  ``serve/precision.py``'s dequantizing forward), so a served
  prediction is bitwise what a direct call at the same bucket shapes
  and arm would produce.
- **Atomic weight swaps.**  The checkpoint watcher restores the newest
  VALID step (resilience integrity layer) off-thread, re-derives every
  precision arm's cast-on-load weight view, then swaps the whole
  arm→variables dict under a lock read once per dispatch — a
  concurrent /predict sees entirely-old or entirely-new weights, never
  a mix (across arms too).
- **Bounded device run-ahead.**  At most ``max_inflight`` dispatched-
  but-unfetched batches; the host completion pool (the
  ``run_inference`` overlap pattern, generalised to out-of-order
  completion) fetches, resizes back to each request's original
  resolution, and resolves futures.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ..eval.inference import _resize_pred, flip_tta, pad_to_batch
from ..utils.logging import get_logger
from ..utils.observability import ServeStats, TelemetryRegistry
from ..utils.tracing import Tracer
from .admission import (AdmissionController, DeadlineExpired, EngineStopped,
                        QueueFull)
from .batcher import DynamicBatcher, Request
from .precision import (cast_variables, make_precision_forward, step_down,
                        validate_arms)


def preprocess_image(image: np.ndarray, res: int, mean, std, *,
                     depth: bool = False):
    """Request image → the compiled forward's input row: resize to the
    (res, res) bucket (PIL bilinear, the eval-path convention), scale to
    [0, 1], normalize.  uint8 in; float32 [0,1] arrays are accepted and
    quantized through uint8 so the server and any offline comparator
    see bit-identical inputs for the same source image.

    ``depth=True`` (RGB-D models, e.g. HDFNet): the request is an
    ``(H, W, 4)`` RGBD stack — the first three channels preprocess as
    above and the fourth splits off as the model's ``depth`` input
    (resized to the same bucket, scaled to [0, 1], NOT mean/std
    normalized — the depth-plane convention the data pipeline uses).
    Returns ``(tensor, depth_plane)`` with depth_plane float32
    ``(res, res, 1)``; the RGB path keeps its historical single-array
    return."""
    arr = np.asarray(image)
    want_c = 4 if depth else 3
    if arr.ndim != 3 or arr.shape[2] != want_c:
        kind = "(H, W, 4) RGBD" if depth else "(H, W, 3)"
        raise ValueError(
            f"expected an {kind} image, got shape {arr.shape}")
    if arr.dtype != np.uint8:
        arr = (np.clip(arr, 0.0, 1.0) * 255.0).round().astype(np.uint8)
    from PIL import Image

    dplane = None
    if depth:
        d = Image.fromarray(arr[:, :, 3])
        if d.size != (res, res):
            d = d.resize((res, res), Image.BILINEAR)
        dplane = (np.asarray(d, np.float32) / 255.0)[:, :, None]
        arr = arr[:, :, :3]
    im = Image.fromarray(arr)
    if im.size != (res, res):
        im = im.resize((res, res), Image.BILINEAR)
    x = np.asarray(im, np.float32) / 255.0
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    tensor = ((x - mean) / std).astype(np.float32)
    if depth:
        return tensor, dplane
    return tensor


class InferenceEngine:
    """Dynamic-batching inference engine over one model.

    ``state`` is a restored ``TrainState`` (its ``eval_variables()`` —
    EMA weights when tracked — are served) or a bare variables dict.
    ``ckpt_dir`` plus ``cfg.serve.reload_poll_s > 0`` arms the hot
    weight reload watcher (requires a TrainState for the restore
    template).  Request lifecycle and knobs: docs/SERVING.md.
    """

    def __init__(self, cfg, model, state, *, ckpt_dir: Optional[str] = None,
                 stats: Optional[ServeStats] = None, clock=time.monotonic):
        self.cfg = cfg
        # RGB-D zoo members (HDFNet under a use_depth config) demand a
        # depth plane on every request: /predict payloads are
        # (H, W, 4) RGBD, split at preprocess; warmup/probe batches
        # carry a zero depth plane.  The HTTP front ends read this to
        # 400 channel-mismatched payloads BEFORE submit.
        self.wants_depth = bool(cfg.data.use_depth)
        self.model = model
        self.ckpt_dir = ckpt_dir
        self.stats = stats or ServeStats()
        self._clock = clock
        self._log = get_logger()
        # Request tracing (utils/tracing.py; docs/OBSERVABILITY.md):
        # the per-request queue/coalesce/device/fetch/resize_back span
        # timeline, sampled deterministically by trace id.  At
        # trace_sample=0 every touch below is a None check — the
        # /metrics surface and request path are byte-for-byte the
        # pre-tracing behavior.
        self.tracer = Tracer(sample=cfg.serve.trace_sample,
                             capacity=cfg.serve.trace_capacity,
                             worst_n=cfg.serve.trace_worst_n, clock=clock)
        # /metrics renders through the shared registry (one code path
        # with the trainer sidecar); a single provider renders
        # byte-identical to ServeStats.render_prometheus().
        self.telemetry = TelemetryRegistry().register(
            "serve", self.stats.prom_families)

        sc = cfg.serve

        # Black-box flight recorder (utils/flightrecorder.py;
        # docs/OBSERVABILITY.md "Flight recorder & incidents"): samples
        # this registry into an on-disk segment ring and bundles
        # incidents on alert firings / watchdog trips / dispatch
        # crashes / SIGTERM.  None when off — no thread, no files,
        # /metrics byte-identical (the recorder registers no families
        # of its own).  Constructed BEFORE the alert engines so their
        # on_transition hooks can reference it; the bundle sections are
        # lambdas evaluated at bundle time, so attribute order is free.
        import dataclasses as _dc

        from ..utils.flightrecorder import recorder_from_knobs

        self.recorder = recorder_from_knobs(
            sc, families_fn=self.telemetry.prom_families,
            sections={
                "stats": lambda: self.stats_snapshot(),
                "traces": lambda: self.tracer.snapshot(n=16),
                "alerts": lambda: (self.alerts.snapshot()
                                   if self.alerts is not None else {}),
                "slo": lambda: (self.slo.snapshot()
                                if self.slo is not None else {}),
                "capacity": lambda: (self.capacity.snapshot()
                                     if self.capacity is not None
                                     else {}),
                "config": lambda: _dc.asdict(self.cfg),
            },
            meta={"source": "engine", "model": cfg.model.name},
            clock=clock)
        self._last_rec_level = 0  # degraded-ladder move detection
        self.res_buckets = tuple(sorted(
            sc.resolution_buckets or (max(cfg.data.image_size),)))
        self.batch_buckets = tuple(sorted(sc.batch_buckets))
        self._mean = np.asarray(cfg.data.normalize_mean, np.float32)
        self._std = np.asarray(cfg.data.normalize_std, np.float32)

        # Precision arms (serve/precision.py): every enabled arm gets a
        # cast-on-load weight view and its own AOT programs; requests
        # pick an arm (serve.precision default, X-Precision override),
        # possibly stepped down by the degraded ladder.
        self.precision_arms = validate_arms(sc.precision_arms, sc.precision)
        self.default_precision = sc.precision

        # Online quality/drift monitors + alert engine (serve/quality.py,
        # utils/alerts.py; docs/OBSERVABILITY.md "Model health").  Both
        # None unless serve.quality_monitor — every touch on the request
        # path guards on that, and with them off the telemetry registry
        # holds the one "serve" provider, so /metrics stays
        # byte-identical to the monitor-less rendering.
        self.quality = None
        self.alerts = None
        self._next_alert_eval = 0.0
        if not sc.quality_monitor:
            # Loudness: a monitor-scoped knob set while the monitor is
            # off would be silently ignored — the operator believes
            # online validation is running when nothing is.
            if sc.quality_shadow_sample > 0:
                raise ValueError(
                    "serve.quality_shadow_sample > 0 requires "
                    "serve.quality_monitor=true (shadow scoring is part "
                    "of the quality monitor)")
            if sc.alert_rules:
                raise ValueError(
                    "serve.alert_rules set but serve.quality_monitor is "
                    "false — the serving alert engine only runs with the "
                    "monitor on")
        if sc.quality_monitor:
            from ..utils.alerts import AlertEngine, parse_rules
            from .quality import (QualityMonitor, default_quality_rules,
                                  load_reference)

            if sc.quality_shadow_sample > 0 and \
                    "f32" not in self.precision_arms:
                raise ValueError(
                    "serve.quality_shadow_sample > 0 needs the f32 "
                    "reference arm among serve.precision_arms — shadow "
                    "scoring re-scores sampled requests on f32")
            self.quality = QualityMonitor(
                cfg.model.name,
                shadow_sample=sc.quality_shadow_sample,
                reference=load_reference(sc.quality_reference,
                                         cfg.model.name),
                psi_min_count=sc.quality_psi_min_count)
            self.alerts = AlertEngine(
                default_quality_rules(sc) + parse_rules(sc.alert_rules),
                clock=clock, on_transition=self._alert_transition)
            self.telemetry.register("quality", self.quality.prom_families)
            self.telemetry.register("alerts", self.alerts.prom_families)

        # Capacity ledger + SLO tracker (utils/capacity.py, utils/slo.py;
        # docs/OBSERVABILITY.md "Capacity & SLO").  Both None unless
        # their knobs are on — every touch guards, and with them off
        # the registry keeps its historical providers, so /metrics is
        # byte-identical to the ledger-less rendering.
        self.capacity = None
        self.slo = None
        self._next_slo_eval = 0.0
        if sc.capacity_ledger:
            from ..utils.capacity import CapacityLedger

            def _stage_shares():
                # Device-vs-queue-vs-host attribution from the stage
                # splits the histograms already hold (PR-9 seams):
                # deep queues + high device share → scale out; deep
                # queues + low device share → host-bound, scaling out
                # is futile (ROADMAP item 2's signal).
                e2e = self.stats.e2e_ms.sum_ms
                if e2e <= 0:
                    return {"device": 0.0, "queue": 0.0, "host": 0.0}
                dev = self.stats.device_ms.sum_ms / e2e
                q = self.stats.queue_ms.sum_ms / e2e
                return {"device": min(dev, 1.0), "queue": min(q, 1.0),
                        "host": max(1.0 - dev - q, 0.0)}

            self.capacity = CapacityLedger(share_fn=_stage_shares)
            self.telemetry.register("capacity",
                                    self.capacity.prom_families)
        if sc.slo_objectives:
            from ..utils.slo import build_tracker

            self.slo = build_tracker(
                sc.slo_objectives, burn_threshold=sc.slo_burn_threshold,
                alert_for_s=sc.slo_alert_for_s,
                alert_clear_s=sc.slo_alert_clear_s, clock=clock,
                on_transition=self._alert_transition)
            self.telemetry.register("slo", self.slo.prom_families)
            self.telemetry.register("slo_alerts",
                                    self.slo.alerts.prom_families)

        self._template = state if hasattr(state, "eval_variables") else None
        self._conv_impl = getattr(cfg.model, "conv_impl", "xla")
        variables = (state.eval_variables()
                     if self._template is not None else state)
        self._var_lock = threading.Lock()
        self._arm_vars = self._derive_arm_vars(variables)
        # Seed the reload watermark from the state's own step so the
        # watcher doesn't "reload" the checkpoint we just restored.
        self._loaded_step: Optional[int] = (
            int(jax.device_get(state.step))
            if self._template is not None else None)

        self._fwds = {arm: make_precision_forward(
            model, arm, conv_impl=self._conv_impl)
            for arm in self.precision_arms}
        # Compiled-program cache, AOT-warmed in start().  The key spells
        # out everything that selects a distinct executable: model,
        # static shapes, the decoder resample implementation, the
        # conv-block implementation, and the precision arm (each a
        # different compiled program).
        self.programs: Dict[Tuple[str, int, int, str, str, str],
                            object] = {}

        self.batcher = DynamicBatcher(
            self.batch_buckets, sc.max_wait_ms / 1000.0,
            max_queue=sc.max_queue, clock=clock)
        # Ladder depth: one rung per precision downshift available from
        # the enabled arms, plus the final resolution rung (the
        # historical binary mode when only one arm is enabled).
        self._n_precision_rungs = len(self.precision_arms) - 1
        self.admission = AdmissionController(
            sc.max_queue, high=sc.degraded_high, low=sc.degraded_low,
            engage_s=sc.degraded_engage_s,
            disengage_s=sc.degraded_disengage_s,
            max_level=self._n_precision_rungs + 1, clock=clock)

        self._est_lock = threading.Lock()
        # (res bucket, arm) → EWMA device s: the arms are different
        # programs with different device costs, so the SLO-expiry
        # estimate must not blend them.
        self._est_s: Dict[Tuple[int, str], float] = {}

        self._stop = threading.Event()
        self._fault_plan = None  # armed from DSOD_FAULTS in start()
        self._running = False
        self._inflight_sem = threading.Semaphore(sc.max_inflight)
        self._inflight_lock = threading.Lock()
        self._inflight_n = 0
        self._dispatch_thread: Optional[threading.Thread] = None
        self._reload_thread: Optional[threading.Thread] = None
        self._watchdog = None
        self._fetch_pool = None
        self._post_pool = None
        # Shadow-scoring side lane: one worker, at most 2 queued+running
        # (try-acquire — a busy lane DROPS, counted, never queues live
        # traffic behind reference forwards).
        self._shadow_pool = None
        self._shadow_sem = threading.BoundedSemaphore(2)

    def _alert_transition(self, rule, old: str, new: str, state) -> None:
        """Alert/SLO state changes → flight-recorder events; a fresh
        firing also snapshots an incident bundle (debounced inside)."""
        if self.recorder is not None:
            self.recorder.alert_transition(rule, old, new, state)

    @property
    def loaded_step(self) -> Optional[int]:
        """The checkpoint step currently serving (``None`` for engines
        started from raw variables with no checkpoint identity).  The
        router cache (serve/cache.py) keys every entry on this, which
        is the whole invalidation story: hot reload, rollout
        promotion, and denylist rollback all move it, making old
        entries unreachable.  Reads are a single atomic attribute load
        — the reload path swaps it under ``_var_lock`` with the arm
        views, but a reader needs one consistent int, not the pair."""
        return self._loaded_step

    # -- precision arms ------------------------------------------------

    def _derive_arm_vars(self, variables) -> Dict[str, object]:
        """Every enabled arm's weight view of ``variables`` (the f32
        source of truth), device-resident.  Called at construction and
        on every hot reload — the views are RE-DERIVED from the freshly
        restored f32 state, then swapped in as one dict under the swap
        lock so no arm ever serves a different step than its siblings.

        At ``model.conv_impl=fused`` the quantized arms take the
        fused-kernel view (``precision.fused_conv_cast_variables``):
        conv kernels stay int8/fp8 leaves dequantized in-VMEM by the
        Pallas kernels, with the per-channel scales riding a parallel
        ``quant_scales`` collection."""
        from .precision import (QUANT_ARMS, fused_conv_cast_variables,
                                fused_conv_sites)

        out = {}
        sites = None  # site discovery is arm-independent: trace once
        for arm in self.precision_arms:
            if self._conv_impl == "fused" and arm in QUANT_ARMS:
                res = self.res_buckets[0]
                probe = {"image": np.zeros((1, res, res, 3), np.float32)}
                if self.wants_depth:
                    probe["depth"] = np.zeros((1, res, res, 1), np.float32)
                if sites is None:
                    sites = fused_conv_sites(self.model, variables, probe)
                view = fused_conv_cast_variables(self.model, variables,
                                                 arm, probe, sites=sites)
            else:
                view = cast_variables(variables, arm)
            out[arm] = jax.device_put(view)
        return out

    def _effective_arm(self, requested: str, level: int) -> str:
        """The arm a request actually serves at: the requested arm
        pushed down the enabled-arm ladder by the degraded level
        (resolution only degrades once every precision rung is spent —
        see :meth:`choose_res_bucket`)."""
        return step_down(requested, self.precision_arms,
                         min(level, self._n_precision_rungs))

    # -- lifecycle -----------------------------------------------------

    def start(self, own_dispatch: bool = True) -> "InferenceEngine":
        """Warm the programs and start serving.  ``own_dispatch=False``
        skips the engine's own dispatch thread — the fleet's interleaved
        dispatcher (serve/fleet.py) drives :meth:`_dispatch_once`
        instead, so N co-resident engines share one device through one
        loop that drains their batchers fairly."""
        if self._running:
            return self
        from concurrent.futures import ThreadPoolExecutor

        from ..resilience.inject import plan_from_env

        sc = self.cfg.serve
        self.warm()
        self._stop.clear()
        if self.recorder is not None:
            self.recorder.start()
        # Deterministic serve-tier chaos (resilience/inject.py): the
        # plan is cached once here so the dispatch hot path pays a
        # None check, not an environ read, per group.
        self._fault_plan = plan_from_env()
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=max(sc.max_inflight, 1),
            thread_name_prefix="serve-fetch")
        self._post_pool = ThreadPoolExecutor(
            max_workers=max(sc.post_workers, 1),
            thread_name_prefix="serve-post")
        if self.quality is not None and sc.quality_shadow_sample > 0:
            self._shadow_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="serve-shadow")
        if sc.watchdog_deadline_s > 0:
            from ..resilience.watchdog import StepWatchdog

            def _on_stall(msg):
                # Health first (the router's gate must flip even if the
                # bundle write below is slow), then the incident — a
                # wedged dispatch is exactly the post-mortem case the
                # recorder exists for.
                self.stats.set_health(False, msg)
                if self.recorder is not None:
                    self.recorder.trigger("watchdog", msg)

            self._watchdog = StepWatchdog(
                deadline_s=sc.watchdog_deadline_s, on_stall=_on_stall)
            self._watchdog.start()
        if self.ckpt_dir and sc.reload_poll_s > 0:
            if self._template is None:
                raise ValueError(
                    "hot weight reload needs a TrainState restore "
                    "template — construct the engine from a TrainState "
                    "(from_checkpoint does)")
            self._reload_thread = threading.Thread(
                target=self._reload_loop, name="serve-reload", daemon=True)
            self._reload_thread.start()
        self._running = True
        if own_dispatch:
            self._dispatch_thread = threading.Thread(
                target=self._dispatch_loop, name="serve-dispatch",
                daemon=True)
            self._dispatch_thread.start()
        return self

    def warm(self) -> int:
        """AOT-compile every (resolution, batch, precision-arm) bucket
        program so no request ever pays a compile; returns the program
        count."""
        name = self.cfg.model.name
        impl = self.cfg.model.resample_impl
        with self._var_lock:
            arm_vars = self._arm_vars
        for arm in self.precision_arms:
            for res in self.res_buckets:
                for bb in self.batch_buckets:
                    key = (name, res, bb, impl, self._conv_impl, arm)
                    if key in self.programs:
                        continue
                    batch = {"image": np.zeros((bb, res, res, 3),
                                               np.float32)}
                    if self.wants_depth:
                        batch["depth"] = np.zeros((bb, res, res, 1),
                                                  np.float32)
                    t0 = time.perf_counter()
                    self.programs[key] = self._fwds[arm].lower(
                        arm_vars[arm], batch).compile()
                    self._log.info(
                        "serve: warmed program %s in %.1fs", key,
                        time.perf_counter() - t0)
                    if self.capacity is not None:
                        # The live half of tools/roofline.py: ask the
                        # executable itself what it costs, once, here
                        # at warmup (cost_analysis on the cached AOT
                        # program — no extra compile).
                        self.capacity.record(
                            self._capacity_key(res, bb, arm),
                            self.programs[key])
        return len(self.programs)

    def _capacity_key(self, res: int, bb: int, arm: str) -> str:
        """One compiled program's ledger key (the cache key, rendered
        label-safe)."""
        return (f"{self.cfg.model.name}/r{res}b{bb}/"
                f"{self.cfg.model.resample_impl}/{self._conv_impl}/{arm}")

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self._stop.set()
        for r in self.batcher.close():
            self.stats.inc("errors")
            self._trace_end(r, "stopped")
            self._fail(r, EngineStopped("engine stopped"))
        if self._dispatch_thread is not None:
            self._dispatch_thread.join(timeout=10.0)
            self._dispatch_thread = None
        if self._reload_thread is not None:
            self._reload_thread.join(timeout=10.0)
            self._reload_thread = None
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=True)
            self._fetch_pool = None
        if self._post_pool is not None:
            self._post_pool.shutdown(wait=True)
            self._post_pool = None
        if self._shadow_pool is not None:
            self._shadow_pool.shutdown(wait=True)
            self._shadow_pool = None
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self.recorder is not None:
            self.recorder.stop()

    @classmethod
    def from_checkpoint(cls, ckpt_dir: str, config_name: Optional[str] = None,
                        overrides=(), step: Optional[int] = None,
                        **kw) -> "InferenceEngine":
        """Checkpoint directory → ready-to-start engine (config sidecar
        aware, via the shared ``restore_for_eval``)."""
        from ..eval.inference import restore_for_eval

        cfg, model, state = restore_for_eval(
            ckpt_dir, config_name=config_name, overrides=overrides,
            step=step)
        return cls(cfg, model, state, ckpt_dir=ckpt_dir, **kw)

    @classmethod
    def from_random_init(cls, cfg, **kw) -> "InferenceEngine":
        """Randomly-initialised engine for a config — the
        smoke/bench/loadgen posture where the serving machinery, not a
        particular checkpoint, is under test.  The single bring-up used
        by tools/serve.py --init-random AND bench.py --mode serve, so
        the two can't drift apart."""
        from ..models import build_model
        from ..train import build_optimizer, create_train_state

        model = build_model(cfg.model)
        tx, _ = build_optimizer(cfg.optim, 1)
        h, w = cfg.data.image_size
        probe = {"image": np.zeros((1, h, w, 3), np.float32)}
        if cfg.data.use_depth:
            probe["depth"] = np.zeros((1, h, w, 1), np.float32)
        state = create_train_state(jax.random.key(cfg.seed), model, tx,
                                   probe, ema=cfg.optim.ema_decay > 0)
        return cls(cfg, model, state, **kw)

    # -- request plane -------------------------------------------------

    def choose_res_bucket(self, h: int, w: int, degraded: bool) -> int:
        if degraded:
            return self.res_buckets[0]
        side = max(h, w)
        for r in self.res_buckets:
            if side <= r:
                return r
        return self.res_buckets[-1]

    def submit(self, image: np.ndarray,
               slo_ms: Optional[float] = None,
               precision: Optional[str] = None,
               trace_id: Optional[str] = None,
               trace_parent: Optional[str] = None,
               stream: Optional[str] = None):
        """Enqueue one prediction; returns a ``concurrent.futures.Future``
        resolving to ``(pred, meta)`` — pred float32 (H, W) at the
        request's original resolution.  ``precision`` selects the arm
        (default ``serve.precision``; must be an enabled arm — the
        degraded ladder may still step it further down).  ``trace_id``
        joins the request to an end-to-end trace (the HTTP front ends
        pass the X-Request-ID; sampling decides whether spans are
        actually recorded); ``trace_parent`` is the caller's span id —
        the fleet router parents the engine's request span under its
        dispatch-attempt span.  Raises :class:`QueueFull` /
        :class:`EngineStopped` at the door (nothing enqueued)."""
        # Every submit() call is a submitted request — door rejects
        # included — so the accounting identity composes fleet-wide:
        # a router's forwarded count equals this engine's submitted
        # count exactly, whatever fate each request meets.
        self.stats.inc("submitted")
        if not self._running:
            self.stats.inc("errors")
            raise EngineStopped("engine not running")
        if not self.stats.healthy:
            self.stats.inc("errors")
            raise EngineStopped(
                f"engine unhealthy: {self.stats.health_reason}")
        try:
            self.admission.try_admit(self.batcher.pending())
        except QueueFull:
            self.stats.inc("shed")
            raise
        level = self.admission.level
        try:
            requested = (self.default_precision if precision is None
                         else str(precision))
            if requested not in self.precision_arms:
                raise ValueError(
                    f"unknown precision {requested!r}; enabled arms: "
                    f"{list(self.precision_arms)}")
            arm = self._effective_arm(requested, level)
            arr = np.asarray(image)
            # Resolution degrades only once every precision rung is
            # spent — precision steps down BEFORE resolution.
            res = self.choose_res_bucket(arr.shape[0], arr.shape[1],
                                         level > self._n_precision_rungs)
            # Per-stream affinity (serve/streams.py): a stream's next
            # frame coalesces into the SAME (res_bucket, precision)
            # compiled program its previous frame ran on, so warm
            # state stays on one program.  Only when the arm still
            # matches (the degraded ladder wins over affinity) and the
            # bucket is still configured.
            aff = self.batcher.affinity_bucket(stream)
            if aff is not None and aff[1] == arm \
                    and aff[0] in self.res_buckets:
                res = aff[0]
            dplane = None
            if self.wants_depth:
                tensor, dplane = preprocess_image(
                    arr, res, self._mean, self._std, depth=True)
            else:
                tensor = preprocess_image(arr, res, self._mean, self._std)
            if self.quality is not None:
                # Input drift histogram (serve/quality.py) — one mean()
                # over an image preprocess already walked.  Guarded
                # separately from the validation above: a monitor bug
                # (or a NaN-poisoned but servable input) may only cost
                # telemetry, never the request.
                try:
                    from .quality import input_mean01

                    self.quality.observe_input(input_mean01(arr))
                except Exception:  # noqa: BLE001
                    self._log.exception("serve: quality monitor failed")
        except Exception:
            # Malformed input / unknown arm: terminate the request in
            # the accounting (the engine owns ALL terminal counters, so
            # the served+shed+expired+errors == submitted invariant
            # holds for 400s too) and let the front end surface it.
            self.stats.inc("errors")
            raise
        now = self._clock()
        slo = self.cfg.serve.slo_ms if slo_ms is None else slo_ms
        # Root span for the request's in-engine life (None unless the
        # trace is sampled — every later touch guards on that).  The
        # root PARENT may live in another tracer (the router's attempt
        # span); within this tracer the request span is the root whose
        # end completes the trace.
        root = self.tracer.begin(
            "request", trace_id, parent_id=trace_parent, t0=now, root=True,
            attrs={"model": self.cfg.model.name, "res_bucket": res,
                   "arm": arm, "level": level})
        req = Request(
            tensor=tensor, orig_hw=(int(arr.shape[0]), int(arr.shape[1])),
            res_bucket=res, arrival=now, precision=arm,
            deadline=(now + slo / 1000.0) if slo and slo > 0 else None,
            degraded=level > 0, level=level, trace_id=trace_id, root=root,
            stream=stream, depth=dplane)
        try:
            # The batcher re-checks the bound under ITS lock (the
            # try_admit above is the cheap pre-preprocess gate; N
            # concurrent submitters could all have passed it).
            self.batcher.put(req)
        except QueueFull:
            self.stats.inc("shed")
            self._trace_end(req, "shed")
            raise
        except RuntimeError as e:  # closed: stop() raced this submit
            self.stats.inc("errors")
            self._trace_end(req, "stopped")
            raise EngineStopped(str(e)) from e
        self.stats.set_queue_depth(self.batcher.pending())
        return req.future

    def predict(self, image: np.ndarray, slo_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                precision: Optional[str] = None):
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(image, slo_ms=slo_ms, precision=precision).result(
            timeout=timeout or self.cfg.serve.request_timeout_s)

    # -- dispatch loop -------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            self._dispatch_once(blocking=True)

    def _observe_depth(self) -> int:
        depth = self.batcher.pending()
        self.stats.set_queue_depth(depth)
        self.admission.observe(depth)
        level = self.admission.level
        self.stats.set_degraded(level)
        if self.recorder is not None and level != self._last_rec_level:
            # Degraded-ladder move: one typed event per rung change
            # (the observe point runs at ms cadence; the compare is
            # the only cost on the non-moving path).
            self.recorder.event("degraded_level", level=level,
                                prev=self._last_rec_level, depth=depth)
            self._last_rec_level = level
        if self.alerts is not None:
            # Throttled quality→alert evaluation rides the dispatch
            # loop's existing observe point (the fleet loop spins this
            # at ms cadence; the rules only need ~1 Hz).
            now = self._clock()
            if now >= self._next_alert_eval:
                self._next_alert_eval = now + 1.0
                sigs, details = self.quality.signals()
                self.alerts.evaluate(sigs, now=now, details=details)
        if self.slo is not None:
            # Same cadence for the SLO burn rules: window decay must
            # CLEAR a burn alert even when no new requests arrive to
            # trigger an ingest-side evaluation.
            now = self._clock()
            if now >= self._next_slo_eval:
                self._next_slo_eval = now + 1.0
                self.slo.evaluate(now)
        return depth

    def _dispatch_once(self, blocking: bool = True) -> bool:
        """One dispatch-loop iteration; returns True when a group came
        off the batcher.  ``blocking=True`` is the engine's own loop
        (waits on the coalescing deadline / idle timeout).
        ``blocking=False`` is the fleet's interleaved loop: it never
        waits — not on an empty queue, not on a group still coalescing,
        and not on this engine's inflight semaphore — so one
        back-pressured model reports False and its co-resident siblings
        keep dispatching.  The watchdog contract holds in both modes:
        the beat STOPS while ready work cannot enter the device (the
        wedged-device /healthz signal) and keeps ticking when idle."""
        if blocking:
            if self._watchdog is not None:
                self._watchdog.beat()
            got = self.batcher.get_batch(idle_timeout_s=0.1)
            self._observe_depth()
            if got is None:
                return False
            return self._dispatch_group(got, preacquired=False)
        self._observe_depth()
        if not self.batcher.ready():
            if self._watchdog is not None:
                self._watchdog.beat()
            return False
        if not self._inflight_sem.acquire(blocking=False):
            # Ready work but no device slot: NO beat, so a wedged
            # device still flips THIS model's health while the fleet
            # loop carries on serving its siblings.
            return False
        if self._watchdog is not None:
            self._watchdog.beat()
        got = self.batcher.poll_batch()
        if got is None:  # raced a close(); return the unused slot
            self._inflight_sem.release()
            return False
        return self._dispatch_group(got, preacquired=True)

    def _dispatch_group(self, got, preacquired: bool) -> bool:
        """Expiry-filter, pad, and dispatch one coalesced group.
        ``preacquired`` means the caller already holds one inflight
        semaphore slot (the non-blocking path acquires it BEFORE
        popping, so a group is never stranded outside the queue)."""
        t_pop = self._clock()  # the group just left the batcher
        if self._fault_plan is not None:
            # serve_stall@G:SEC — wedge THIS dispatch before its
            # forward; the watchdog's beat stops while the stall holds
            # ready work out of the device (the /healthz flip the
            # router's health gate reads).
            self._fault_plan.maybe_stall_serve_dispatch()
        (res, arm), reqs = got
        with self._est_lock:
            est = self._est_s.get((res, arm), 0.0)
        now = self._clock()
        live = []
        for r in reqs:
            if AdmissionController.expired(r.deadline, est, now):
                self.stats.inc("expired")
                self._trace_end(r, "expired", t_pop=t_pop)
                self._fail(r, DeadlineExpired(
                    f"deadline missed before dispatch (est device "
                    f"{est * 1000:.1f}ms)"))
            else:
                live.append(r)
        if not live:
            if preacquired:
                self._inflight_sem.release()
            return True
        bb = self.batcher.pick_batch_bucket(len(live))
        stacked = {"image": np.stack([r.tensor for r in live])}
        if self.wants_depth:
            # submit() guarantees every request for a depth model
            # carries its plane, so the stack is total.
            stacked["depth"] = np.stack([r.depth for r in live])
        batch = pad_to_batch(stacked, bb)
        with self._var_lock:
            variables = self._arm_vars[arm]
            step = self._loaded_step
        tta = self.cfg.serve.tta and not self.admission.degraded
        if not preacquired:
            # Bound run-ahead WITHOUT beating the watchdog while we
            # wait: a wedged device keeps this semaphore drained, the
            # beats stop, and /healthz flips — the intended signal.
            acquired = False
            while not self._stop.is_set():
                if self._inflight_sem.acquire(timeout=0.25):
                    acquired = True
                    break
            if not acquired:
                for r in live:
                    self.stats.inc("errors")
                    self._trace_end(r, "stopped", t_pop=t_pop)
                    self._fail(r, EngineStopped("engine stopped"))
                return True
        t0 = self._clock()
        for r in live:
            r.dispatch_t = t0
            self.stats.queue_ms.observe((t0 - r.arrival) * 1000.0)
            if r.root is not None:
                # queue: batcher wait (backlog + coalescing window);
                # coalesce: group assembly — expiry filter, padding,
                # the inflight-semaphore wait.  Together they tile
                # arrival → dispatch exactly (== the queue_ms
                # histogram's observation for this request).
                self.tracer.record(r.trace_id, "queue", r.arrival, t_pop,
                                   parent_id=r.root.span_id)
                self.tracer.record(r.trace_id, "coalesce", t_pop, t0,
                                   parent_id=r.root.span_id,
                                   attrs={"group": len(live), "bucket": bb})
        # Count the in-flight slot the moment the semaphore is held
        # so the error path's _release_inflight always undoes a
        # matching increment (the gauge must never go negative-ish
        # while OTHER batches are genuinely in flight).
        with self._inflight_lock:
            self._inflight_n += 1
            self.stats.set_inflight(self._inflight_n)
        try:
            probs = self._forward(res, bb, arm, variables, batch, tta)
        except Exception as e:  # noqa: BLE001 — per-request surface
            self._release_inflight()
            self._log.exception("serve: dispatch failed")
            for r in live:
                self.stats.inc("errors")
                self._trace_end(r, "error")
                self._fail(r, e)
            if self.recorder is not None:
                # A failed device dispatch is an incident: bundle the
                # telemetry around it (debounced — a poisoned program
                # failing every group cannot bundle-storm).
                self.recorder.event(
                    "dispatch_error", res=res, arm=arm,
                    requests=len(live),
                    error=f"{type(e).__name__}: {e}"[:200])
                # Background: this is the engine's ONE dispatch loop —
                # the capture must not stall sibling batches.
                self.recorder.trigger("dispatch_error",
                                      f"{type(e).__name__}",
                                      background=True)
            return True
        self.stats.observe_batch(len(live), bb, arm=arm)
        meta = {"res_bucket": res, "batch_bucket": bb, "tta": tta,
                "step": step, "precision": arm}
        self._fetch_pool.submit(self._complete, probs, live, meta, t0)
        return True

    def _forward(self, res: int, bb: int, arm: str, variables, batch,
                 tta: bool):
        key = (self.cfg.model.name, res, bb, self.cfg.model.resample_impl,
               self._conv_impl, arm)
        call = self.programs.get(key, self._fwds[arm])

        def fn(b):
            return call(variables, b)

        # Same wrapper the offline eval uses — serving TTA can never
        # drift from test.py's convention.
        return (flip_tta(fn) if tta else fn)(batch)

    # -- completion (host) ---------------------------------------------

    def _release_inflight(self) -> None:
        self._inflight_sem.release()
        with self._inflight_lock:
            self._inflight_n = max(self._inflight_n - 1, 0)
            self.stats.set_inflight(self._inflight_n)

    def _complete(self, probs, live, meta, t0: float) -> None:
        try:
            t_f0 = self._clock()
            arr = np.asarray(probs)[: len(live)]  # the blocking fetch
            t_f1 = self._clock()
            dev_ms = (t_f1 - t0) * 1000.0
            for r in live:
                if r.root is not None:
                    # device: dispatch → fetch complete (== the
                    # device_ms histogram's observation); fetch is the
                    # host-blocking tail of it, parented under device.
                    dev_sid = self.tracer.record(
                        r.trace_id, "device", t0, t_f1,
                        parent_id=r.root.span_id,
                        attrs={"batch_bucket": meta["batch_bucket"]})
                    self.tracer.record(r.trace_id, "fetch", t_f0, t_f1,
                                       parent_id=dev_sid)
            if self.capacity is not None and not meta.get("tta"):
                # Per-program measured time → live MFU.  TTA responses
                # are skipped: flip_tta runs the program twice, which
                # would halve the reported utilization of a program
                # that ran at full tilt.
                self.capacity.observe(
                    self._capacity_key(meta["res_bucket"],
                                       meta["batch_bucket"],
                                       meta["precision"]), dev_ms)
            est_key = (meta["res_bucket"], meta["precision"])
            with self._est_lock:
                old = self._est_s.get(est_key)
                now_s = dev_ms / 1000.0
                self._est_s[est_key] = (now_s if old is None
                                        else 0.8 * old + 0.2 * now_s)
            arm_stats = self.stats.arm(meta["precision"])
            for _ in live:
                self.stats.device_ms.observe(dev_ms)
                arm_stats.device_ms.observe(dev_ms)
            for j, r in enumerate(live):
                self._post_pool.submit(
                    self._finish, r, arr[j], dict(meta, device_ms=dev_ms))
        except Exception as e:  # noqa: BLE001 — per-request surface
            self._log.exception("serve: completion failed")
            for r in live:
                self.stats.inc("errors")
                self._trace_end(r, "error")
                self._fail(r, e)
        finally:
            self._release_inflight()

    def _finish(self, r: Request, row: np.ndarray, meta: dict) -> None:
        try:
            t_r0 = self._clock()
            pred = _resize_pred(row, r.orig_hw)
            t_done = self._clock()
            e2e = (t_done - r.arrival) * 1000.0
            meta.update(
                degraded=r.degraded, degraded_level=r.level,
                queue_ms=round((r.dispatch_t - r.arrival) * 1000.0, 3),
                resize_ms=round((t_done - t_r0) * 1000.0, 3),
                e2e_ms=round(e2e, 3),
                # trace_id only when the trace was SAMPLED (spans
                # exist in /debug/traces); X-Timing says "trace=-"
                # otherwise, request id still echoed separately.
                trace_id=r.trace_id if r.root is not None else None)
            if r.root is not None:
                self.tracer.record(r.trace_id, "resize_back", t_r0, t_done,
                                   parent_id=r.root.span_id)
                # Root ends with t1 = the same instant e2e_ms was
                # computed at, so the trace's dur_ms, the X-Timing
                # header, and the e2e histogram observation agree.
                r.root.end(t1=t_done,
                           key=(self.cfg.model.name, r.res_bucket),
                           outcome="served")
            self.stats.e2e_ms.observe(e2e)
            arm_stats = self.stats.arm(r.precision)
            arm_stats.e2e_ms.observe(e2e)
            arm_stats.inc_served()
            self.stats.inc("served")
            self._set_result(r, (pred, meta))
        except Exception as e:  # noqa: BLE001 — per-request surface
            self.stats.inc("errors")
            self._trace_end(r, "error")
            self._fail(r, e)
            return
        if self.quality is not None:
            # Quality monitors run AFTER the future resolved: the
            # response never waits on stats, and a monitor bug can
            # only cost telemetry, not a request.
            try:
                self.quality.observe_output(row)
                # Shadow only non-f32, non-TTA responses (a TTA row
                # vs a plain f32 forward would measure TTA, not the
                # arm) — the sampler sees every eligible response.
                if (r.precision != "f32" and not meta.get("tta")
                        and self.quality.should_shadow()):
                    self._submit_shadow(r.tensor, row, meta,
                                        depth=r.depth)
            except Exception:  # noqa: BLE001 — telemetry must not throw
                self._log.exception("serve: quality monitor failed")

    # -- shadow scoring (serve/quality.py) ------------------------------

    def _submit_shadow(self, tensor: np.ndarray, row: np.ndarray,
                       meta: dict,
                       depth: Optional[np.ndarray] = None) -> None:
        """Queue one arm-vs-f32 shadow score on the side lane, or DROP
        (counted) when the lane is full — reference forwards must never
        queue live traffic behind them."""
        if self._shadow_pool is None \
                or not self._shadow_sem.acquire(blocking=False):
            self.quality.record_shadow_dropped()
            return
        try:
            self._shadow_pool.submit(self._shadow_score, tensor, row,
                                     dict(meta), depth)
        except RuntimeError:  # pool shut down under us
            self._shadow_sem.release()
            self.quality.record_shadow_dropped()

    def _shadow_score(self, tensor: np.ndarray, row: np.ndarray,
                      meta: dict,
                      depth: Optional[np.ndarray] = None) -> None:
        """Re-run one served input through the f32 reference program
        and record the live disagreement (mean |Δ| + thresholded-mask
        flip rate) for the arm that served it.  A hot reload between
        the serve and the shadow invalidates the comparison (the arm
        row came from other weights) — dropped, counted."""
        try:
            with self._var_lock:
                variables = self._arm_vars["f32"]
                step = self._loaded_step
            if step != meta.get("step"):
                self.quality.record_shadow_dropped()
                return
            res = meta["res_bucket"]
            bb = self.batcher.pick_batch_bucket(1)
            stacked = {"image": tensor[None]}
            if depth is not None:
                stacked["depth"] = depth[None]
            batch = pad_to_batch(stacked, bb)
            probs = self._forward(res, bb, "f32", variables, batch,
                                  tta=False)
            ref = np.asarray(probs)[0].astype(np.float32)
            arm_row = np.asarray(row, np.float32)
            mae = float(np.mean(np.abs(arm_row - ref)))
            flip = float(np.mean((arm_row > 0.5) != (ref > 0.5)))
            self.quality.record_shadow(meta["precision"], mae, flip)
        except Exception:  # noqa: BLE001 — telemetry must not throw
            self._log.exception("serve: shadow score failed")
            self.quality.record_shadow_dropped()
        finally:
            self._shadow_sem.release()

    def stats_snapshot(self) -> Dict:
        """The /stats payload: ServeStats plus — when the monitors are
        on — the quality snapshot and the active alerts (the full rule
        states live at /alerts)."""
        out = self.stats.snapshot()
        if self._loaded_step is not None:
            # Which checkpoint is actually serving — the rollout control
            # plane (serve/rollout.py) reads this per replica to confirm
            # a canary/promote landed where it was sent.
            out["loaded_step"] = int(self._loaded_step)
        if self.quality is not None:
            out["quality"] = self.quality.snapshot()
        if self.alerts is not None:
            out["alerts"] = self.alerts.active()
        if self.capacity is not None:
            out["capacity"] = self.capacity.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.recorder is not None:
            out["recorder"] = self.recorder.snapshot()
        return out

    def _trace_end(self, r: Request, outcome: str,
                   t_pop: Optional[float] = None) -> None:
        """Close a failed/shed request's trace with its outcome (the
        happy path ends the root in :meth:`_finish`).  ``t_pop`` (the
        expiry path) records the queue span the request DID spend
        before being dropped."""
        if r.root is None:
            return
        if t_pop is not None:
            self.tracer.record(r.trace_id, "queue", r.arrival, t_pop,
                               parent_id=r.root.span_id)
        r.root.end(key=(self.cfg.model.name, r.res_bucket),
                   outcome=outcome)

    @staticmethod
    def _set_result(r: Request, value) -> None:
        try:
            r.future.set_result(value)
        except Exception:  # noqa: BLE001 — abandoned/cancelled future
            pass

    @staticmethod
    def _fail(r: Request, exc: Exception) -> None:
        try:
            r.future.set_exception(exc)
        except Exception:  # noqa: BLE001 — abandoned/cancelled future
            pass

    # -- hot weight reload ---------------------------------------------

    def _reload_loop(self) -> None:
        from ..ckpt import CheckpointManager

        mgr = CheckpointManager(self.ckpt_dir, async_save=False)
        try:
            while not self._stop.wait(self.cfg.serve.reload_poll_s):
                try:
                    self._maybe_reload(mgr)
                except Exception:  # noqa: BLE001 — keep serving old weights
                    self._log.exception(
                        "serve: weight reload failed; keeping current "
                        "weights")
        finally:
            mgr.close()

    def _maybe_reload(self, mgr) -> None:
        # Newest VALID (integrity-gated) step that the rollout denylist
        # (serve/rollout.py) has not pinned bad: a step that canaried
        # badly and was rolled back must never be re-picked by the
        # background poll, or the rollback would undo itself one poll
        # later.
        from .rollout import read_step_denylist

        mgr.reload()  # steps (and denylist verdicts) land between scans
        deny = read_step_denylist(self.ckpt_dir)
        steps = [s for s in mgr.valid_steps() if s not in deny]
        step = max(steps) if steps else None
        if step is None or step == self._loaded_step:
            return
        self._reload_step(mgr, step)

    def _reload_step(self, mgr, step: int) -> None:
        """Restore ``step`` and swap it in (the shared tail of the
        background poll and :meth:`reload_to`)."""
        state = mgr.restore(self._template, step)
        # Re-derive EVERY arm's weight view off-lock (cast + quantize
        # are the slow part), then swap the whole dict in one motion —
        # a concurrent dispatch sees either the old step's views or the
        # new step's views, never a mix across arms.
        arm_vars = self._derive_arm_vars(state.eval_variables())
        with self._var_lock:
            self._arm_vars = arm_vars
            self._loaded_step = step
        self.stats.inc("reloads")
        if self.recorder is not None:
            self.recorder.event("hot_reload", step=int(step))
        self._log.info("serve: hot-reloaded weights from step %d", step)

    def reload_to(self, step: int) -> int:
        """Synchronously load checkpoint ``step`` — the rollout control
        plane's targeted reload (serve/rollout.py drives ONE canary
        replica to the candidate step, everyone else on promote).
        Returns the loaded step; raises on a missing/invalid/denylisted
        step or an engine without a checkpoint source."""
        from ..ckpt import CheckpointManager

        from .rollout import read_step_denylist

        if not self.ckpt_dir or self._template is None:
            raise RuntimeError(
                "reload_to: engine has no checkpoint source (started "
                "from random init without ckpt_dir)")
        step = int(step)
        if step in read_step_denylist(self.ckpt_dir):
            raise ValueError(
                f"reload_to: step {step} is denylisted (it canaried "
                "badly and was rolled back)")
        mgr = CheckpointManager(self.ckpt_dir, async_save=False)
        try:
            if step not in mgr.valid_steps():
                raise ValueError(
                    f"reload_to: step {step} is not a VALID checkpoint "
                    f"in {self.ckpt_dir} (have {mgr.valid_steps()})")
            if step != self._loaded_step:
                self._reload_step(mgr, step)
        finally:
            mgr.close()
        return step
