"""Multi-model serving fleet (docs/SERVING.md "Fleet").

TF-Replicator's thin-abstraction thesis, extended one more axis: PR 5
mapped a request stream onto ONE family of compiled programs; a fleet
maps N model streams onto N families — and when those families are
co-resident on one device, nothing about the engines changes except who
turns the dispatch crank.  Three fleet-level invariants:

- **One device, one loop.**  Co-resident in-process engines keep their
  own batchers, program caches, admission ladders and watchdogs, but a
  single :class:`FleetDispatcher` thread drains them round-robin — at
  most one coalesced group per model per cycle, never waiting on one
  model's coalescing window or back-pressured inflight semaphore — so
  a hot model cannot starve a cold one (asserted under one-hot
  overload in tests/test_fleet.py).
- **Health degrades, never flips.**  /healthz reports per-model health;
  a wedged subset marks the fleet ``degraded`` (200, with the wedged
  models named) and only an all-models-down fleet answers 503.  A
  fronting LB drains the whole process only when there is nothing left
  to route to.
- **One accounting book.**  The PR-5 identity
  ``served + shed + expired + errors == submitted`` holds fleet-wide —
  and it is the ROUTER'S book: the door counts submissions and every
  handler path terminates each one in exactly one router outcome, so
  the identity survives retries, hedges, and a replica SIGKILLed
  mid-load (a dead replica's local counters vanish from scrape; a
  router-owned book cannot lose history it wrote itself).  Per-replica
  engine books remain exposed as observational detail — each replica's
  LOCAL identity still holds over the attempts it saw.

Backends are in-process engines (:class:`EngineBackend`) and/or remote
serve processes (:class:`RemoteBackend` — scale-out across
processes/hosts; the remote owns its own device loop and the router
adds tenancy + aggregation on top).  Backends sharing one routing key
form a :class:`ReplicaSet`: round-robin spread, health- and circuit-
breaker-gated pick, failover between members (serve/failover.py;
docs/SERVING.md "Failure semantics").
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from ..configs.base import FleetConfig, validate_fleet_config
from ..utils.logging import get_logger
from ..utils.observability import (TailEstimator, merge_prom_families,
                                   parse_prom_text, render_prom_families)
from ..utils.tracing import Tracer
from .failover import STATE_GAUGE, CircuitBreaker, RetryPolicy
from .router import RouterStats, TenantAdmission


class EngineBackend:
    """An in-process :class:`~..serve.engine.InferenceEngine` replica.
    Started with ``own_dispatch=False`` — the fleet's interleaved
    dispatcher turns its crank."""

    kind = "engine"

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine

    def start(self) -> None:
        self.engine.start(own_dispatch=False)

    def stop(self) -> None:
        self.engine.stop()

    def queue_depth(self) -> Optional[int]:
        return self.engine.batcher.pending()

    @property
    def max_queue(self) -> Optional[int]:
        return self.engine.cfg.serve.max_queue

    def healthy(self) -> bool:
        return self.engine._running and self.engine.stats.healthy

    def health_reason(self) -> str:
        if not self.engine._running:
            return "engine not running"
        return self.engine.stats.health_reason

    def prom_families(self, labels: str):
        # The engine's full registry (ServeStats + — when the quality
        # monitors are on — dsod_quality_*/dsod_alert_* families), so
        # the fleet aggregation carries model health per replica.  With
        # one provider registered this is exactly
        # stats.prom_families(labels) (merge of one group = identity).
        return self.engine.telemetry.prom_families(labels)

    def stats_snapshot(self) -> Dict:
        return self.engine.stats_snapshot()

    def alerts_snapshot(self) -> Optional[Dict]:
        return (self.engine.alerts.snapshot()
                if self.engine.alerts is not None else None)

    def alert_reasons(self) -> List[str]:
        return (self.engine.alerts.active_reasons()
                if self.engine.alerts is not None else [])

    def debug_traces(self, n: int = 50) -> Dict:
        return self.engine.tracer.snapshot(n)

    def incidents_snapshot(self) -> Optional[Dict]:
        return (self.engine.recorder.snapshot()
                if self.engine.recorder is not None else None)

    def describe(self) -> Dict:
        cfg = self.engine.cfg
        return {
            "kind": self.kind,
            "model": cfg.model.name,
            "backbone": cfg.model.backbone,
            "res_buckets": list(self.engine.res_buckets),
            "batch_buckets": list(self.engine.batch_buckets),
            "precision_arms": list(self.engine.precision_arms),
        }


class RemoteBackend:
    """A remote serve process proxied by the router.  The remote owns
    its own admission/accounting; the router adds tenancy on top and
    scrapes /metrics + /stats into the fleet aggregation.

    Health is probed by a BACKGROUND thread every ``health_poll_s``;
    :meth:`healthy` only ever reads the cached verdict, so the 2 s
    connect timeout of a dead host can never run inline inside the
    router's request path or its /healthz//metrics handlers.  The
    verdict starts optimistic ("not probed yet" but routable) — the
    per-replica circuit breaker catches a genuinely dead remote on the
    first dispatch, which is cheaper than holding every request
    hostage to the first probe's round trip.
    """

    kind = "remote"

    # Probe/scrape timeout (healthz, /metrics, /stats) — deliberately
    # tight: a dead host must cost the PROBER thread one short dial
    # per window (and a /metrics scrape of a believed-healthy remote
    # at most this), never a Prometheus scrape-timeout for the fleet.
    PROBE_TIMEOUT_S = 2.0

    def __init__(self, name: str, url: str, *, timeout_s: float = 30.0,
                 health_poll_s: float = 2.0):
        self.name = name
        self.url = url.rstrip("/")
        self._timeout = float(timeout_s)
        self._health_poll_s = float(health_poll_s)
        self._lock = threading.Lock()
        self._healthy = True  # optimistic until the first probe lands
        self._reason = ""
        self._probed_once = False
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None

    def start(self) -> None:
        """Start the background prober (the remote PROCESS has its own
        lifecycle — this only owns the health loop)."""
        if self._prober is not None:
            return
        self._stop.clear()
        self._prober = threading.Thread(
            target=self._probe_loop, name=f"fleet-probe-{self.name}",
            daemon=True)
        self._prober.start()

    def stop(self) -> None:
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout=self.PROBE_TIMEOUT_S + 5.0)
            self._prober = None

    def _probe_loop(self) -> None:
        # First probe immediately (the optimistic verdict should be
        # corrected within one dial, not one poll window), then every
        # health_poll_s.
        while True:
            self.probe_now()
            if self._stop.wait(self._health_poll_s):
                return

    def probe_now(self) -> bool:
        """One synchronous /healthz dial; updates the cached verdict.
        Called by the prober thread (and tests); the request path
        NEVER calls this."""
        try:
            with urllib.request.urlopen(self.url + "/healthz",
                                        timeout=self.PROBE_TIMEOUT_S) as r:
                ok = r.status == 200
                reason = "" if ok else f"/healthz {r.status}"
        except (urllib.error.URLError, OSError,
                http.client.HTTPException) as e:
            ok, reason = False, f"unreachable: {e}"
        with self._lock:
            self._healthy, self._reason = ok, reason
            self._probed_once = True
        return ok

    def queue_depth(self) -> Optional[int]:
        return None  # unknown here; the remote's own admission bounds it

    @property
    def max_queue(self) -> Optional[int]:
        return None

    def healthy(self) -> bool:
        """The CACHED verdict — never dials (the prober thread owns
        the refresh; the router's note_transport_failure fast-paths a
        flip the moment a dispatch sees the remote dead)."""
        with self._lock:
            return self._healthy

    def health_reason(self) -> str:
        with self._lock:
            if not self._probed_once and self._healthy:
                return "not probed yet (optimistic)"
            return self._reason

    def note_transport_failure(self, reason: str) -> None:
        """Router fast path: a dispatch just saw this remote dead —
        flip the cached verdict NOW instead of waiting out the poll
        window.  The prober flips it back when /healthz answers."""
        with self._lock:
            self._healthy = False
            self._reason = f"transport failure: {reason}"

    def admin_reload(self, step: int, timeout_s: Optional[float] = None
                     ) -> int:
        """POST /admin/reload on the remote — the rollout control
        plane's targeted reload (serve/rollout.py).  Returns the
        loaded step; raises on transport failure or a non-200 answer
        (the remote refuses denylisted/invalid steps with a 409)."""
        body = json.dumps({"step": int(step)}).encode()
        req = urllib.request.Request(
            self.url + "/admin/reload", data=body,
            headers={"Content-Type": "application/json"},
            method="POST")
        timeout = self._timeout if timeout_s is None else float(timeout_s)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                payload = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:200]
            raise RuntimeError(
                f"{self.name}: /admin/reload {e.code}: {detail}")
        return int(payload.get("step", step))

    def predict_raw(self, body: bytes, headers: Dict[str, str],
                    timeout_s: Optional[float] = None
                    ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """POST /predict on the remote; returns (status, headers,
        body) — HTTP error statuses are answers, not exceptions (only
        transport failures raise).  ``timeout_s`` caps this attempt
        below the default (deadline-budgeted retries must not let a
        stalled remote eat the full router timeout)."""
        req = urllib.request.Request(self.url + "/predict", data=body,
                                     headers=headers, method="POST")
        timeout = self._timeout if timeout_s is None \
            else min(self._timeout, float(timeout_s))
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, list(r.headers.items()), r.read()
        except urllib.error.HTTPError as e:
            return e.code, list(e.headers.items()), e.read()

    def prom_families(self, labels: str):
        """The remote's /metrics relabeled under this fleet key; a
        known-down replica (cached health verdict) is skipped without
        a scrape — its absence plus ``dsod_fleet_replica_up 0`` is the
        signal, and a dead host must not stall the fleet's scrape."""
        if not self.healthy():
            return []
        try:
            with urllib.request.urlopen(
                    self.url + "/metrics",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return parse_prom_text(r.read().decode(), labels)
        except (urllib.error.URLError, OSError):
            return []

    def stats_snapshot(self) -> Dict:
        if not self.healthy():
            return {"unreachable": self.health_reason()}
        try:
            with urllib.request.urlopen(
                    self.url + "/stats",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            return {"unreachable": str(e)}

    def alerts_snapshot(self) -> Optional[Dict]:
        """The remote's /alerts (bounded like every other scrape;
        None on a known-down/unreachable replica or an old remote
        without the endpoint)."""
        if not self.healthy():
            return None
        try:
            with urllib.request.urlopen(
                    self.url + "/alerts",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def alert_reasons(self) -> List[str]:
        snap = self.alerts_snapshot()
        return list(snap.get("active", [])) if snap else []

    def debug_traces(self, n: int = 50) -> Dict:
        """The remote's /debug/traces (its half of the end-to-end
        timelines — same trace ids as the router's spans, thanks to
        deterministic sampling on the forwarded X-Request-ID).  Empty
        on a known-down or unreachable replica: a debug endpoint must
        never stall on a dead host either."""
        if not self.healthy():
            return {}
        try:
            with urllib.request.urlopen(
                    self.url + f"/debug/traces?n={int(n)}",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            return {}

    def incidents_snapshot(self) -> Optional[Dict]:
        """The remote's /incidents (bounded scrape; None on a
        known-down replica, an unreachable one, an old remote without
        the endpoint, or a recorder-off replica — the killed replica's
        evidence lives in ITS ring on disk, which is the point)."""
        if not self.healthy():
            return None
        try:
            with urllib.request.urlopen(
                    self.url + "/incidents",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                snap = json.loads(r.read().decode())
                return snap if snap.get("enabled") else None
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def describe(self) -> Dict:
        return {"kind": self.kind, "url": self.url}


class ReplicaSet:
    """All backends sharing ONE routing key, plus their circuit
    breakers.  :meth:`pick` is the router's dispatch gate: rotate
    round-robin over members, skipping anything excluded by the
    caller, flagged unhealthy by its probe/watchdog, or blocked by an
    OPEN breaker — so a wedged replica is routed AROUND for the cost
    of two predicate reads, not its connect timeout.  A single-member
    set keeps the member's replica id equal to the group name (the
    PR-7 label/metric surface is unchanged until a second replica
    actually exists)."""

    def __init__(self, name: str, members: List[Tuple[str, object]],
                 breaker_factory=CircuitBreaker):
        if not members:
            raise ValueError(f"replica set {name!r} needs >= 1 member")
        self.name = name
        self.members: List[Tuple[str, object]] = list(members)
        self.breakers: Dict[str, CircuitBreaker] = {
            rid: breaker_factory() for rid, _ in members}
        self.tail = TailEstimator()  # router-observed e2e ms (hedging)
        self._breaker_factory = breaker_factory
        self._draining: Set[str] = set()
        self._rr = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.members)

    # -- membership dynamics (serve/controller.py) ---------------------
    # The members list is COPY-ON-WRITE: mutators build a NEW list and
    # swap it under the lock, so the handler paths that iterate
    # ``self.members`` without the lock (health, metrics, stats
    # gathers) always see one consistent roster — old or new, never a
    # list mutating under their feet.

    def add_member(self, rid: str, backend,
                   breaker: Optional[CircuitBreaker] = None) -> None:
        """Admit a new replica into routing (the controller's scale-
        out/heal admission — caller has already health-gated it)."""
        with self._lock:
            if any(r == rid for r, _b in self.members):
                raise ValueError(
                    f"replica set {self.name!r}: duplicate replica id "
                    f"{rid!r}")
            self.breakers = dict(self.breakers)
            self.breakers[rid] = (breaker if breaker is not None
                                  else self._breaker_factory())
            self.members = self.members + [(rid, backend)]
            self._draining.discard(rid)

    def remove_member(self, rid: str):
        """Drop a replica from routing; returns its backend (caller
        owns the backend/process teardown) or None if unknown.  The
        set may go EMPTY — pick()/healthy() answer None/False and the
        controller heals it back."""
        with self._lock:
            backend = None
            kept = []
            for r, b in self.members:
                if r == rid:
                    backend = b
                else:
                    kept.append((r, b))
            if backend is None:
                return None
            self.members = kept
            self.breakers = {r: brk for r, brk in self.breakers.items()
                             if r != rid}
            self._draining.discard(rid)
            self._rr = self._rr % max(len(kept), 1)
            return backend

    def set_draining(self, rid: str, draining: bool = True) -> None:
        """Flip a member out of (or back into) routing WITHOUT
        touching its process: a draining replica finishes its in-
        flight work but :meth:`pick` never offers it new work — the
        drain-then-retire half of spot-aware scale-in."""
        with self._lock:
            if draining:
                self._draining.add(rid)
            else:
                self._draining.discard(rid)

    def draining(self) -> Set[str]:
        with self._lock:
            return set(self._draining)

    def pick(self, exclude: Optional[Set[str]] = None,
             prefer: Optional[str] = None
             ) -> Optional[Tuple[str, object, CircuitBreaker]]:
        """The next dispatchable replica ``(rid, backend, breaker)``,
        or None when every member is excluded, unhealthy, or breaker-
        blocked.  Advances the round-robin head past the pick so
        successive requests spread over the set.

        ``prefer`` (stream affinity, serve/streams.py): return that
        member WITHOUT advancing the round-robin head when it is
        routable — a pinned stream must not skew the spread the
        independent traffic sees.  A dead/blocked/unknown preference
        falls through to the normal rotation (the caller re-homes)."""
        exclude = exclude or set()
        with self._lock:
            if prefer is not None and prefer not in exclude \
                    and prefer not in self._draining:
                for rid, backend in self.members:
                    if rid != prefer:
                        continue
                    if backend.healthy():
                        breaker = self.breakers[rid]
                        if breaker.allow():
                            return rid, backend, breaker
                    break
            start = self._rr
            n = len(self.members)
            for i in range(n):
                j = (start + i) % n
                rid, backend = self.members[j]
                if rid in exclude or rid in self._draining:
                    continue
                # Health BEFORE the breaker: allow() on an open-but-
                # rested breaker grants its single half-open probe, and
                # an unhealthy member must not eat that slot for a
                # request that will never be dispatched to it.
                if not backend.healthy():
                    continue
                breaker = self.breakers[rid]
                if not breaker.allow():
                    continue
                self._rr = (j + 1) % n
                return rid, backend, breaker
            return None

    def healthy(self) -> bool:
        """Is ANYTHING routable?  A member counts only while its probe
        verdict is good AND its breaker would admit a dispatch now or
        imminently — a live listener whose /predict 5xxes keeps its
        probe verdict but trips the breaker, and /healthz must tell
        the fronting LB the truth about routability, not liveness.
        Draining members are NOT routable by definition."""
        with self._lock:
            members = list(self.members)
            breakers = dict(self.breakers)
            draining = set(self._draining)
        return any(rid not in draining and b.healthy()
                   and rid in breakers and breakers[rid].would_allow()
                   for rid, b in members)

    def member_state(self, rid: str) -> str:
        """One member's routability verdict for health surfaces."""
        with self._lock:
            backend = dict(self.members).get(rid)
            breaker = self.breakers.get(rid)
            draining = rid in self._draining
        if backend is None or breaker is None:
            return "removed"
        if draining:
            return "draining"
        if not backend.healthy():
            return backend.health_reason() or "unhealthy"
        if not breaker.would_allow():
            snap = breaker.snapshot()
            return ("breaker open "
                    f"({snap['consecutive_failures']} consecutive "
                    "failures)")
        return "ok"

    def health_reason(self) -> str:
        reasons = []
        for rid, _ in self.members:
            state = self.member_state(rid)
            if state != "ok":
                reasons.append(f"{rid}: {state}")
        return "; ".join(reasons)


class FleetDispatcher:
    """ONE dispatch loop for N co-resident engines sharing a device.

    Round-robin with a rotating head: each cycle offers every engine at
    most one coalesced group, via the engine's non-blocking
    ``_dispatch_once(blocking=False)`` — which never waits on an empty
    queue, a still-coalescing group, or a back-pressured inflight
    semaphore.  Fairness is structural: a hot model's deep backlog
    cannot deny a cold model its one slot per cycle, and a wedged
    model's drained semaphore costs the loop a failed try-acquire, not
    a stall.  Per-engine watchdogs keep their PR-5 meaning (beats stop
    while ready work cannot enter the device), so /healthz stays
    per-model.
    """

    def __init__(self, engines: List, idle_sleep_s: float = 0.002):
        self._engines = list(engines)
        self._idle_sleep_s = float(idle_sleep_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rr = 0
        self._log = get_logger()

    def start(self) -> None:
        if self._thread is not None or not self._engines:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-dispatch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        n = len(self._engines)
        while not self._stop.is_set():
            progressed = False
            for i in range(n):
                eng = self._engines[(self._rr + i) % n]
                if not eng._running:
                    continue
                try:
                    progressed = eng._dispatch_once(blocking=False) \
                        or progressed
                except Exception:  # noqa: BLE001 — keep siblings alive
                    self._log.exception(
                        "fleet: dispatch iteration failed; continuing")
            self._rr = (self._rr + 1) % n
            if not progressed:
                self._stop.wait(self._idle_sleep_s)


class Fleet:
    """The assembled fleet: named backends + tenant admission + router
    accounting + aggregation.  ``serve/router.py`` provides the HTTP
    front end; tests may drive :meth:`resolve`/``backends`` directly."""

    def __init__(self, backends: List, cfg: Optional[FleetConfig] = None,
                 clock=time.monotonic):
        cfg = cfg or FleetConfig()  # tenants/policy only — the
        #   backends list IS the model set when built programmatically
        self.cfg = cfg
        self._clock = clock
        # Backends sharing a name form a ReplicaSet (failover targets);
        # a lone name keeps its replica id == group name, so the PR-7
        # single-replica metric/label surface is byte-identical.
        grouped: Dict[str, List] = {}
        for b in backends:
            grouped.setdefault(b.name, []).append(b)
        self.backends: Dict[str, object] = {}  # flat: replica id → backend
        self.groups: Dict[str, ReplicaSet] = {}

        def breaker_factory():
            return CircuitBreaker(cfg.breaker_failures,
                                  cfg.breaker_reset_s, clock=clock)

        # The controller mints breakers for replicas it admits at
        # runtime — same policy knobs as construction-time members.
        self._breaker_factory = breaker_factory
        self._rid_counter: Dict[str, int] = {}
        for name, members in grouped.items():
            ids = ([name] if len(members) == 1
                   else [f"{name}#{i}" for i in range(len(members))])
            for rid, b in zip(ids, members):
                self.backends[rid] = b
            self.groups[name] = ReplicaSet(
                name, list(zip(ids, members)),
                breaker_factory=breaker_factory)
            self._rid_counter[name] = len(members)
        self.admission = TenantAdmission(
            cfg.tenants, default_tenant=cfg.default_tenant,
            strict=cfg.strict_tenants, clock=clock)
        self.rstats = RouterStats()
        # Router-tier tracing: the request root + per-attempt spans
        # (serve/router.py); in-process engines record their half of
        # the same trace ids in their OWN tracers, merged on demand by
        # :meth:`debug_traces`.
        self.tracer = Tracer(sample=cfg.trace_sample,
                             capacity=cfg.trace_capacity,
                             worst_n=cfg.trace_worst_n, clock=clock)
        self.retry_policy = RetryPolicy(
            cfg.retry_max_attempts, cfg.retry_backoff_ms,
            cfg.retry_backoff_max_ms, clock=clock)
        # Capacity & SLO observability (utils/slo.py, serve/prober.py;
        # docs/OBSERVABILITY.md "Capacity & SLO").  Both None/off by
        # default — the aggregated /metrics stays byte-identical.  The
        # SLO tracker is fed by the ROUTER'S OWN terminal book
        # (serve/router.py calls observe_slo at every booking point);
        # ProbeStats is written by the SyntheticProber the serving CLI
        # arms against the router's own bound address
        # (serve/router.py::serve_fleet_forever).
        # Router-tier flight recorder (utils/flightrecorder.py): samples
        # the router's OWN families — the terminal book plus replica
        # up/breaker gauges, both local reads — never a per-second
        # scrape of every replica.  Triggers: replica transport
        # failures (note_replica_failure, from the router's dispatch
        # path), SLO burn firings, SIGTERM.  None when off.  Built
        # before the SLO tracker so burn/budget transitions hook in.
        from ..utils.flightrecorder import recorder_from_knobs

        self.recorder = recorder_from_knobs(
            cfg, families_fn=self._router_families,
            sections={
                "stats": lambda: self.stats(),
                "traces": lambda: self.tracer.snapshot(16),
                "alerts": lambda: self.alerts(),
                "slo": lambda: (self.slo.snapshot()
                                if self.slo is not None else {}),
                "health": lambda: self.health()[1],
            },
            meta={"source": "router"}, clock=clock)
        self.slo = None
        if cfg.slo_objectives:
            from ..utils.slo import build_tracker

            self.slo = build_tracker(
                cfg.slo_objectives,
                burn_threshold=cfg.slo_burn_threshold,
                alert_for_s=cfg.slo_alert_for_s,
                alert_clear_s=cfg.slo_alert_clear_s, clock=clock,
                on_transition=(self.recorder.alert_transition
                               if self.recorder is not None else None))
        self.probe_stats = None
        if cfg.prober_interval_s > 0:
            from .prober import ProbeStats

            self.probe_stats = ProbeStats()
        # Closed-loop control plane (docs/SERVING.md "Fleet control
        # plane"): the controller heals/scales replica sets, the
        # rollout manager delivers checkpoints canary-first.  Both
        # None/off by default — constructed (no threads yet) here so
        # their metric families render the moment the fleet is built,
        # started/stopped with the fleet's own lifecycle.
        self.controller = None
        if cfg.controller:
            from .controller import FleetController

            self.controller = FleetController(self, cfg, clock=clock)
        self.rollout = None
        if cfg.rollout_ckpt_dir:
            from .rollout import RolloutManager

            self.rollout = RolloutManager(self, cfg, clock=clock)
        # Router-door response cache (serve/cache.py; docs/SERVING.md
        # "Router cache").  None/off by default: no cache object, zero
        # threads, /metrics byte-identical.  The router checks it after
        # the body read, before dispatch; a hit books the cache_hit
        # terminal class (see :meth:`stats`).
        self.cache = None
        if cfg.cache_bytes > 0:
            from .cache import RouterCache

            self.cache = RouterCache(
                cfg.cache_bytes, coalesce=cfg.cache_coalesce,
                near_dup=cfg.cache_near_dup,
                near_hamming=cfg.cache_near_dup_hamming,
                shadow_sample=cfg.cache_shadow_sample)
        # Streaming-video session table (serve/streams.py;
        # docs/SERVING.md "Streaming").  None/off by default: no
        # table, zero threads, X-Stream-ID inert, /metrics
        # byte-identical.  Armed, the router opens per-stream sessions
        # at the door, pins frames to the session's home replica, and
        # may serve the temporal-coherence fast path — booked as the
        # sixth terminal class ``stream_reuse`` (see :meth:`stats`).
        self.streams = None
        if cfg.stream_sessions > 0:
            from .streams import StreamTable

            self.streams = StreamTable(
                cfg.stream_sessions, cfg.stream_ttl_s,
                reuse_hamming=cfg.stream_reuse_hamming,
                ema_blend=cfg.stream_ema_blend, clock=clock)
        self.dispatcher = FleetDispatcher(
            [b.engine for b in backends if b.kind == "engine"])
        self._started = False
        self._log = get_logger()

    @classmethod
    def from_config(cls, fc: FleetConfig, extra_overrides=()) -> "Fleet":
        """Build every backend a validated FleetConfig names.
        ``extra_overrides`` (dotted ``section.field=value``) apply to
        every IN-PROCESS member after its own overrides — the
        tools/serve.py ``--set`` passthrough."""
        from ..configs import apply_overrides, get_config
        from .engine import InferenceEngine

        fc = validate_fleet_config(fc)
        backends = []
        for m in fc.models:
            if m.urls:  # remote replica set under one routing key
                for u in m.urls:
                    backends.append(RemoteBackend(
                        m.name, u, timeout_s=fc.request_timeout_s,
                        health_poll_s=fc.health_poll_s))
                continue
            if m.url:
                backends.append(RemoteBackend(
                    m.name, m.url, timeout_s=fc.request_timeout_s,
                    health_poll_s=fc.health_poll_s))
                continue
            overrides = tuple(m.overrides) + tuple(extra_overrides)
            if m.ckpt_dir:
                eng = InferenceEngine.from_checkpoint(
                    m.ckpt_dir, config_name=m.config,
                    overrides=overrides)
            else:
                cfg = apply_overrides(get_config(m.config), overrides)
                eng = InferenceEngine.from_random_init(cfg)
            backends.append(EngineBackend(m.name, eng))
        return cls(backends, fc)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Fleet":
        if self._started:
            return self
        for b in self.backends.values():
            b.start()  # engines warm their AOT programs here
        self.dispatcher.start()
        if self.recorder is not None:
            self.recorder.start()
        if self.controller is not None:
            self.controller.start()
        if self.rollout is not None:
            self.rollout.start()
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        # Control plane first: the controller must retire its
        # supervised subprocesses (and the rollout finish its tick)
        # while the routing/backend layer is still alive under them.
        if self.rollout is not None:
            self.rollout.stop()
        if self.controller is not None:
            self.controller.stop()
        self.dispatcher.stop()
        for b in self.backends.values():
            b.stop()
        if self.recorder is not None:
            self.recorder.stop()

    # -- routing -------------------------------------------------------

    def resolve(self, model: Optional[str]) -> Optional[ReplicaSet]:
        """Routing key → :class:`ReplicaSet`; None on an unknown key.
        A single-model fleet serves header-less requests (the
        single-engine CLI posture behind the router)."""
        if model is None or model == "":
            if len(self.groups) == 1:
                return next(iter(self.groups.values()))
            return None
        return self.groups.get(model)

    # -- membership dynamics (serve/controller.py) ---------------------

    def attach_replica(self, name: str, backend) -> str:
        """Admit an already-health-gated backend into ``name``'s
        replica set (the controller's scale-out/heal admission path).
        Returns the minted replica id.  Replica ids are monotonic per
        group (``name#N``) — an id is never reused after a detach, so
        flight-recorder timelines and metric series stay unambiguous."""
        group = self.groups.get(name)
        if group is None:
            raise ValueError(f"attach_replica: unknown model {name!r}")
        n = self._rid_counter.get(name, len(group))
        self._rid_counter[name] = n + 1
        rid = f"{name}#{n}"
        group.add_member(rid, backend,
                         breaker=self._breaker_factory())
        # COW swap: handler threads iterating self.backends see the
        # old or the new dict, never one mutating under them.
        new = dict(self.backends)
        new[rid] = backend
        self.backends = new
        if self._started:
            backend.start()
        if self.recorder is not None:
            self.recorder.event("replica_attached", replica=rid,
                                model=name,
                                url=getattr(backend, "url", ""))
        return rid

    def detach_replica(self, rid: str):
        """Remove a replica from routing and the flat backend map;
        returns its backend (stopped) or None if unknown.  The caller
        (controller) owns the PROCESS teardown for supervised
        replicas — this only unhooks the router's view."""
        backend = None
        for name, g in self.groups.items():
            if any(r == rid for r, _b in g.members):
                backend = g.remove_member(rid)
                break
        if backend is None:
            return None
        new = dict(self.backends)
        new.pop(rid, None)
        self.backends = new
        try:
            backend.stop()
        except Exception:  # noqa: BLE001 — a dead remote's prober
            pass
        if self.recorder is not None:
            self.recorder.event("replica_detached", replica=rid)
        return backend

    def reload_replica(self, rid: str, step: int) -> int:
        """Targeted checkpoint reload of ONE replica (the rollout
        manager's canary/promote actuator): in-process engines load
        synchronously, remotes via POST /admin/reload.  Returns the
        loaded step; raises when the replica is unknown, has no
        checkpoint source, or refuses the step (denylisted/invalid)."""
        backend = self.backends.get(rid)
        if backend is None:
            raise ValueError(f"reload_replica: unknown replica {rid!r}")
        if backend.kind == "engine":
            return backend.engine.reload_to(step)
        return backend.admin_reload(step)

    def observe_latency(self, model: str, ms: float) -> None:
        """Router-observed e2e per successful attempt — feeds the
        per-model tail estimate the auto hedge trigger reads."""
        g = self.groups.get(model)
        if g is not None:
            g.tail.observe(ms)

    def observe_slo(self, model: Optional[str], tenant: Optional[str],
                    outcome: str, ms: float) -> None:
        """One SLO event per router terminal — called at the SAME
        points the router book terminates a counted submission, so
        /slo reconciles against /stats exactly (client-fault terminals
        excluded inside; no-op with the tracker off)."""
        if self.slo is not None:
            self.slo.observe_outcome(outcome, ms, model=model,
                                     tenant=tenant)

    # -- aggregation ---------------------------------------------------

    def _replica_label(self, group: ReplicaSet, rid: str) -> str:
        """Metric label set for one replica: ``model=`` only while the
        group has a single member (the PR-7 surface), ``model=`` +
        ``replica=`` once real replicas exist."""
        if len(group) == 1:
            return 'model="%s"' % group.name
        return 'model="%s",replica="%s"' % (group.name, rid)

    def health(self) -> Tuple[int, Dict]:
        """Degrading health: (200, ok) all healthy; (200, degraded +
        the wedged models) when a SUBSET is wedged — the fleet still
        routes around them; (503, unhealthy) only when NOTHING is left
        to route to.  A MODEL is healthy while ANY of its replicas is
        (that is what "routes around" means); the per-replica detail
        rides under ``replicas``."""
        per = {}
        replicas = {}
        for name, g in sorted(self.groups.items()):
            ok = g.healthy()
            per[name] = "ok" if ok else (g.health_reason() or "unhealthy")
            if len(g) > 1:
                replicas.update({rid: g.member_state(rid)
                                 for rid, _b in g.members})
        down = [n for n, v in per.items() if v != "ok"]
        body = {"models": per}
        if replicas:
            body["replicas"] = replicas
        # Active model-health alerts (docs/OBSERVABILITY.md "Model
        # health") from IN-PROCESS engines only — a remote's alerts
        # would cost a dial on the request path; they surface through
        # the aggregated /alerts (bounded, concurrent) and the
        # remote's own /healthz instead.
        alerts = {}
        for name, g in sorted(self.groups.items()):
            for rid, b in g.members:
                reasons = (b.alert_reasons()
                           if b.kind == "engine"
                           and hasattr(b, "alert_reasons") else [])
                if reasons:
                    alerts.setdefault(name, []).extend(reasons)
        if alerts:
            body["alerts"] = alerts
        # Router-tier SLO burn/budget alerts degrade the fleet verdict
        # the same way ("Capacity & SLO"): the fleet still routes, the
        # error budget says it should not be trusted blindly.
        slo_active = (self.slo.active_reasons()
                      if self.slo is not None else [])
        if slo_active:
            body["slo_alerts"] = slo_active
        if not down:
            if alerts or slo_active:
                return 200, dict(body, status="degraded")
            return 200, dict(body, status="ok")
        if len(down) < len(per):
            return 200, dict(body, status="degraded", unhealthy=down)
        return 503, dict(body, status="unhealthy", unhealthy=down)

    def _router_families(self):
        """The router's OWN families — terminal book, per-replica
        up/breaker gauges, SLO + probe families when armed.  All local
        reads (cached health verdicts, in-process counters): this is
        both the router-owned half of :meth:`metrics_text` and what the
        flight recorder samples every second, so it must never dial a
        replica."""
        groups = [self.rstats.prom_families()]
        up, bstate, bopen = [], [], []
        for name, g in sorted(self.groups.items()):
            for rid, b in g.members:
                # .get: membership is dynamic (attach/detach under the
                # group lock, COW member lists) — a reader holding the
                # pre-detach roster must skip, not KeyError.
                breaker = g.breakers.get(rid)
                if breaker is None:
                    continue
                labels = self._replica_label(g, rid)
                up.append('dsod_fleet_replica_up{%s} %d'
                          % (labels, 1 if b.healthy() else 0))
                snap = breaker.snapshot()
                bstate.append('dsod_fleet_breaker_state{%s} %d'
                              % (labels, STATE_GAUGE[snap["state"]]))
                bopen.append('dsod_fleet_breaker_open_total{%s} %d'
                             % (labels, snap["opened_total"]))
        groups.append([("dsod_fleet_replica_up", "gauge", up),
                       ("dsod_fleet_breaker_state", "gauge", bstate),
                       ("dsod_fleet_breaker_open_total", "counter", bopen)])
        if self.controller is not None:
            groups.append(self.controller.stats.prom_families())
        if self.rollout is not None:
            groups.append(self.rollout.stats.prom_families())
        if self.cache is not None:
            groups.append(self.cache.prom_families())
        if self.streams is not None:
            groups.append(self.streams.prom_families())
        if self.slo is not None:
            # Router-tier SLO families + their alert rules (the
            # replica-level dsod_alert_* families merge into the same
            # family groups — TYPE once, samples labeled per rule).
            groups.append(self.slo.prom_families())
            groups.append(self.slo.alerts.prom_families())
        if self.probe_stats is not None:
            groups.append(self.probe_stats.prom_families())
        return merge_prom_families(groups)

    def note_replica_failure(self, rid: str, model: str,
                             reason: str) -> None:
        """Router dispatch path: one replica just failed a transport.
        An event always; an incident bundle debounced (a dying replica
        under load fails many dispatches — one bundle tells the story,
        the ring holds every event)."""
        if self.recorder is None:
            return
        self.recorder.event("replica_transport_failure", replica=rid,
                            model=model, error=str(reason)[:200])
        # Background: this runs on the REQUEST-HANDLER thread right
        # before its failover retry — the bundle's section scrapes
        # (2 s-bounded replica dials) must not delay the very
        # failover that handles the incident.
        self.recorder.trigger(f"replica:{rid}", str(reason)[:200],
                              background=True)

    def incidents(self) -> Dict:
        """The router's /incidents payload: its own recorder snapshot
        plus every reachable replica's (in-process engines read direct;
        healthy remotes scraped bounded + concurrently, dead ones
        skipped — their rings live on THEIR disk and replay after the
        fact, which tools/fleet_chaos.py proves)."""
        snaps = self._gather_replicas(
            lambda _g, rid, b: (rid, b.incidents_snapshot()
                                if hasattr(b, "incidents_snapshot")
                                else None))
        replicas = {rid: s for rid, s in snaps if s}
        return {
            "enabled": self.recorder is not None or bool(replicas),
            "router": (self.recorder.snapshot()
                       if self.recorder is not None else None),
            "replicas": replicas,
        }

    def metrics_text(self) -> str:
        """The aggregated fleet /metrics: router families (tenant=/
        model= labels, incl. the retry/hedge/failover counters), a
        per-replica up gauge, per-replica breaker state/trip families,
        then every replica's ServeStats families relabeled under its
        ``model=`` (+ ``replica=``) key — each family declared ONCE
        across all replicas (utils/observability.merge_prom_families)."""
        groups = [self._router_families()]
        groups.extend(self._gather_replicas(
            lambda g, rid, b: b.prom_families(
                self._replica_label(g, rid))))
        return render_prom_families(merge_prom_families(groups))

    def _gather_replicas(self, fn):
        """Run ``fn(group, rid, backend)`` for every replica and
        return the results in sorted-replica order — CONCURRENTLY when
        remotes are present, because each believed-healthy remote
        scrape can cost up to PROBE_TIMEOUT_S and N replicas paid
        serially is exactly the Prometheus scrape-timeout the probe
        comment forbids."""
        work = []
        for name, g in sorted(self.groups.items()):
            for rid, b in g.members:
                work.append((g, rid, b))
        if sum(1 for _g, _r, b in work if b.kind == "remote") <= 1:
            return [fn(g, rid, b) for g, rid, b in work]
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(8, len(work)),
                thread_name_prefix="fleet-scrape") as ex:
            futs = [ex.submit(fn, g, rid, b) for g, rid, b in work]
            return [f.result() for f in futs]

    def stats(self) -> Dict:
        """One JSON object: router book, per-replica snapshots, breaker
        states, and the fleet-wide accounting identity
        (``served + shed + expired + errors == submitted``) computed
        from the ROUTER'S OWN terminal book — exact through retries,
        hedges, and replica death (a killed replica cannot scrape away
        counters the router wrote), eventually consistent while
        requests are in flight."""
        router = self.rstats.snapshot()
        snaps = self._gather_replicas(
            lambda _g, rid, b: (rid, b.stats_snapshot()))
        models = dict(sorted(snaps))
        breakers = {}
        for name, g in sorted(self.groups.items()):
            for rid in g.breakers:
                breakers[rid] = g.breakers[rid].snapshot()

        # The router terminates every counted submission in exactly one
        # outcome; classify those outcomes into the identity buckets.
        # Engine-owned semantics map 1:1 (ok→served, …); router-only
        # terminals (rejected, transport_error, no_healthy_replica)
        # are errors; "timeout" joins expired (the client-visible fate
        # — the engine's own late terminal is per-replica detail, not
        # fleet book); "cache_hit" (serve/cache.py — exact, near-dup,
        # and coalesced answers served from the router door without a
        # backend forward) is its own fifth bucket; "stream_reuse"
        # (serve/streams.py — the temporal-coherence fast path
        # replaying a stream's previous mask without a forward) the
        # sixth, so the identity reads served + shed + expired +
        # errors + cache_hit + stream_reuse == submitted.
        outcomes = router["outcomes"]
        cls = {"ok": "served", "shed": "shed", "expired": "expired",
               "timeout": "expired", "cache_hit": "cache_hit",
               "stream_reuse": "stream_reuse"}
        book = {"served": 0, "shed": router["shed_total"], "expired": 0,
                "errors": 0, "cache_hit": 0, "stream_reuse": 0}
        for outcome, n in outcomes.items():
            book[cls.get(outcome, "errors")] += n
        fleet = dict(book, submitted=router["submitted_total"])
        fleet["terminal"] = (fleet["served"] + fleet["shed"]
                             + fleet["expired"] + fleet["errors"]
                             + fleet["cache_hit"]
                             + fleet["stream_reuse"])
        fleet["consistent"] = fleet["terminal"] == fleet["submitted"]
        out = {"router": router, "models": models, "fleet": fleet,
               "breakers": breakers}
        if self.cache is not None:
            out["cache"] = self.cache.snapshot()
        if self.streams is not None:
            out["streams"] = self.streams.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.probe_stats is not None:
            out["probes"] = self.probe_stats.snapshot()
        if self.controller is not None:
            out["controller"] = self.controller.snapshot()
        if self.rollout is not None:
            out["rollout"] = self.rollout.snapshot()
        return out

    def alerts(self) -> Dict:
        """The router's /alerts payload: every replica's alert-engine
        snapshot (in-process engines read directly; healthy remotes
        scraped bounded + concurrently, dead ones skipped) plus the
        fleet-wide active union."""
        snaps = self._gather_replicas(
            lambda _g, rid, b: (rid, b.alerts_snapshot()
                                if hasattr(b, "alerts_snapshot")
                                else None))
        models = {rid: s for rid, s in snaps if s}
        if self.slo is not None:
            # The router's own SLO rules ride the same payload under a
            # reserved key (":" is not a valid replica id).
            models["router:slo"] = self.slo.alerts.snapshot()
        active = sorted({a for s in models.values()
                         for a in s.get("active", [])})
        return {"active": active, "models": models}

    def describe_models(self) -> Dict:
        return {rid: b.describe()
                for rid, b in sorted(self.backends.items())}

    def debug_traces(self, n: int = 50) -> Dict:
        """The router's /debug/traces payload: every source's snapshot
        (router + one per replica) PLUS a merged per-trace view — the
        router's request/attempt spans and each replica's in-engine
        spans grouped under their shared trace id, which is what "follow
        ONE request through router → replica → batcher → device →
        fetch" renders as.  Replica snapshots gather concurrently
        (remote scrapes are bounded by PROBE_TIMEOUT_S and skipped for
        known-down replicas)."""
        sources = {"router": self.tracer.snapshot(n)}
        snaps = self._gather_replicas(
            lambda _g, rid, b: (rid, b.debug_traces(n)
                                if hasattr(b, "debug_traces") else {}))
        for rid, snap in snaps:
            if snap:
                sources[f"replica:{rid}"] = snap
        merged: Dict[str, Dict] = {}
        for src, snap in sources.items():
            for tr in snap.get("traces", []):
                m = merged.setdefault(tr["trace_id"], {
                    "trace_id": tr["trace_id"], "spans": [],
                    "sources": []})
                m["spans"].extend(tr["spans"])
                m["sources"].append(src)
                if src == "router":
                    # The router root's duration IS the request's
                    # door-to-response time.
                    m["dur_ms"] = tr.get("dur_ms")
        return {"sources": sources,
                "merged": sorted(merged.values(),
                                 key=lambda t: t.get("dur_ms") or 0.0)}
