"""Multi-model serving fleet (docs/SERVING.md "Fleet").

TF-Replicator's thin-abstraction thesis, extended one more axis: PR 5
mapped a request stream onto ONE family of compiled programs; a fleet
maps N model streams onto N families — and when those families are
co-resident on one device, nothing about the engines changes except who
turns the dispatch crank.  Three fleet-level invariants:

- **One device, one loop.**  Co-resident in-process engines keep their
  own batchers, program caches, admission ladders and watchdogs, but a
  single :class:`FleetDispatcher` thread drains them round-robin — at
  most one coalesced group per model per cycle, never waiting on one
  model's coalescing window or back-pressured inflight semaphore — so
  a hot model cannot starve a cold one (asserted under one-hot
  overload in tests/test_fleet.py).
- **Health degrades, never flips.**  /healthz reports per-model health;
  a wedged subset marks the fleet ``degraded`` (200, with the wedged
  models named) and only an all-models-down fleet answers 503.  A
  fronting LB drains the whole process only when there is nothing left
  to route to.
- **One accounting book.**  The PR-5 identity
  ``served + shed + expired + errors == submitted`` holds fleet-wide:
  the router door counts submissions, router-terminal rejects
  (tenant budget/priority sheds, pre-submit 400s, unreachable remotes)
  add to the engines' own terminal counters, and each engine's local
  identity is untouched (serve/router.py spells out the ledger).

Backends are in-process engines (:class:`EngineBackend`) and/or remote
serve processes (:class:`RemoteBackend` — scale-out across
processes/hosts; the remote owns its own device loop and the router
adds tenancy + aggregation on top).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..configs.base import FleetConfig, validate_fleet_config
from ..utils.logging import get_logger
from ..utils.observability import (merge_prom_families, parse_prom_text,
                                   render_prom_families)
from .router import RouterStats, TenantAdmission


class EngineBackend:
    """An in-process :class:`~..serve.engine.InferenceEngine` replica.
    Started with ``own_dispatch=False`` — the fleet's interleaved
    dispatcher turns its crank."""

    kind = "engine"

    def __init__(self, name: str, engine):
        self.name = name
        self.engine = engine

    def start(self) -> None:
        self.engine.start(own_dispatch=False)

    def stop(self) -> None:
        self.engine.stop()

    def queue_depth(self) -> Optional[int]:
        return self.engine.batcher.pending()

    @property
    def max_queue(self) -> Optional[int]:
        return self.engine.cfg.serve.max_queue

    def healthy(self) -> bool:
        return self.engine._running and self.engine.stats.healthy

    def health_reason(self) -> str:
        if not self.engine._running:
            return "engine not running"
        return self.engine.stats.health_reason

    def prom_families(self, labels: str):
        return self.engine.stats.prom_families(labels)

    def stats_snapshot(self) -> Dict:
        return self.engine.stats.snapshot()

    def describe(self) -> Dict:
        cfg = self.engine.cfg
        return {
            "kind": self.kind,
            "model": cfg.model.name,
            "backbone": cfg.model.backbone,
            "res_buckets": list(self.engine.res_buckets),
            "batch_buckets": list(self.engine.batch_buckets),
            "precision_arms": list(self.engine.precision_arms),
        }


class RemoteBackend:
    """A remote serve process proxied by the router.  The remote owns
    its own admission/accounting; the router adds tenancy on top and
    scrapes /metrics + /stats into the fleet aggregation.  Health is
    probed at most once per ``health_poll_s`` (cached in between) so
    /healthz stays cheap."""

    kind = "remote"

    # Probe/scrape timeout (healthz, /metrics, /stats) — deliberately
    # tight: these run inline in the router's /healthz and /metrics
    # handlers, and a down remote must cost ONE short probe per
    # ``health_poll_s`` window (the cached verdict gates the scrapes),
    # not a Prometheus scrape-timeout for the whole fleet.
    PROBE_TIMEOUT_S = 2.0

    def __init__(self, name: str, url: str, *, timeout_s: float = 30.0,
                 health_poll_s: float = 2.0, clock=time.monotonic):
        self.name = name
        self.url = url.rstrip("/")
        self._timeout = float(timeout_s)
        self._health_poll_s = float(health_poll_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._probed_at: Optional[float] = None
        self._healthy = False
        self._reason = "not probed yet"

    def start(self) -> None:  # the remote process has its own lifecycle
        pass

    def stop(self) -> None:
        pass

    def queue_depth(self) -> Optional[int]:
        return None  # unknown here; the remote's own admission bounds it

    @property
    def max_queue(self) -> Optional[int]:
        return None

    def healthy(self) -> bool:
        with self._lock:
            now = self._clock()
            if (self._probed_at is not None
                    and now - self._probed_at < self._health_poll_s):
                return self._healthy
            self._probed_at = now
        try:
            with urllib.request.urlopen(self.url + "/healthz",
                                        timeout=self.PROBE_TIMEOUT_S) as r:
                ok = r.status == 200
                reason = "" if ok else f"/healthz {r.status}"
        except (urllib.error.URLError, OSError) as e:
            ok, reason = False, f"unreachable: {e}"
        with self._lock:
            self._healthy, self._reason = ok, reason
            return ok

    def health_reason(self) -> str:
        with self._lock:
            return self._reason

    def predict_raw(self, body: bytes, headers: Dict[str, str]
                    ) -> Tuple[int, List[Tuple[str, str]], bytes]:
        """POST /predict on the remote; returns (status, headers,
        body) — HTTP error statuses are answers, not exceptions (only
        transport failures raise)."""
        req = urllib.request.Request(self.url + "/predict", data=body,
                                     headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return r.status, list(r.headers.items()), r.read()
        except urllib.error.HTTPError as e:
            return e.code, list(e.headers.items()), e.read()

    def prom_families(self, labels: str):
        """The remote's /metrics relabeled under this fleet key; a
        known-down replica (cached health verdict) is skipped without
        a scrape — its absence plus ``dsod_fleet_replica_up 0`` is the
        signal, and a dead host must not stall the fleet's scrape."""
        if not self.healthy():
            return []
        try:
            with urllib.request.urlopen(
                    self.url + "/metrics",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return parse_prom_text(r.read().decode(), labels)
        except (urllib.error.URLError, OSError):
            return []

    def stats_snapshot(self) -> Dict:
        if not self.healthy():
            return {"unreachable": self.health_reason()}
        try:
            with urllib.request.urlopen(
                    self.url + "/stats",
                    timeout=self.PROBE_TIMEOUT_S) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as e:
            return {"unreachable": str(e)}

    def describe(self) -> Dict:
        return {"kind": self.kind, "url": self.url}


class FleetDispatcher:
    """ONE dispatch loop for N co-resident engines sharing a device.

    Round-robin with a rotating head: each cycle offers every engine at
    most one coalesced group, via the engine's non-blocking
    ``_dispatch_once(blocking=False)`` — which never waits on an empty
    queue, a still-coalescing group, or a back-pressured inflight
    semaphore.  Fairness is structural: a hot model's deep backlog
    cannot deny a cold model its one slot per cycle, and a wedged
    model's drained semaphore costs the loop a failed try-acquire, not
    a stall.  Per-engine watchdogs keep their PR-5 meaning (beats stop
    while ready work cannot enter the device), so /healthz stays
    per-model.
    """

    def __init__(self, engines: List, idle_sleep_s: float = 0.002):
        self._engines = list(engines)
        self._idle_sleep_s = float(idle_sleep_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rr = 0
        self._log = get_logger()

    def start(self) -> None:
        if self._thread is not None or not self._engines:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-dispatch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        n = len(self._engines)
        while not self._stop.is_set():
            progressed = False
            for i in range(n):
                eng = self._engines[(self._rr + i) % n]
                if not eng._running:
                    continue
                try:
                    progressed = eng._dispatch_once(blocking=False) \
                        or progressed
                except Exception:  # noqa: BLE001 — keep siblings alive
                    self._log.exception(
                        "fleet: dispatch iteration failed; continuing")
            self._rr = (self._rr + 1) % n
            if not progressed:
                self._stop.wait(self._idle_sleep_s)


class Fleet:
    """The assembled fleet: named backends + tenant admission + router
    accounting + aggregation.  ``serve/router.py`` provides the HTTP
    front end; tests may drive :meth:`resolve`/``backends`` directly."""

    def __init__(self, backends: List, cfg: Optional[FleetConfig] = None,
                 clock=time.monotonic):
        cfg = cfg or FleetConfig()  # tenants/strictness only — the
        #   backends list IS the model set when built programmatically
        names = [b.name for b in backends]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate backend names in {names}")
        self.cfg = cfg
        self.backends: Dict[str, object] = {b.name: b for b in backends}
        self.admission = TenantAdmission(
            cfg.tenants, default_tenant=cfg.default_tenant,
            strict=cfg.strict_tenants, clock=clock)
        self.rstats = RouterStats()
        self.dispatcher = FleetDispatcher(
            [b.engine for b in backends if b.kind == "engine"])
        self._started = False
        self._log = get_logger()

    @classmethod
    def from_config(cls, fc: FleetConfig, extra_overrides=()) -> "Fleet":
        """Build every backend a validated FleetConfig names.
        ``extra_overrides`` (dotted ``section.field=value``) apply to
        every IN-PROCESS member after its own overrides — the
        tools/serve.py ``--set`` passthrough."""
        from ..configs import apply_overrides, get_config
        from .engine import InferenceEngine

        fc = validate_fleet_config(fc)
        backends = []
        for m in fc.models:
            if m.url:
                backends.append(RemoteBackend(
                    m.name, m.url, timeout_s=fc.request_timeout_s,
                    health_poll_s=fc.health_poll_s))
                continue
            overrides = tuple(m.overrides) + tuple(extra_overrides)
            if m.ckpt_dir:
                eng = InferenceEngine.from_checkpoint(
                    m.ckpt_dir, config_name=m.config,
                    overrides=overrides)
            else:
                cfg = apply_overrides(get_config(m.config), overrides)
                eng = InferenceEngine.from_random_init(cfg)
            backends.append(EngineBackend(m.name, eng))
        return cls(backends, fc)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Fleet":
        if self._started:
            return self
        for b in self.backends.values():
            b.start()  # engines warm their AOT programs here
        self.dispatcher.start()
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self.dispatcher.stop()
        for b in self.backends.values():
            b.stop()

    # -- routing -------------------------------------------------------

    def resolve(self, model: Optional[str]):
        """Routing key → backend; None on an unknown key.  A
        single-model fleet serves header-less requests (the
        single-engine CLI posture behind the router)."""
        if model is None or model == "":
            if len(self.backends) == 1:
                return next(iter(self.backends.values()))
            return None
        return self.backends.get(model)

    # -- aggregation ---------------------------------------------------

    def health(self) -> Tuple[int, Dict]:
        """Degrading health: (200, ok) all healthy; (200, degraded +
        the wedged models) when a SUBSET is wedged — the fleet still
        routes around them; (503, unhealthy) only when NOTHING is left
        to route to."""
        per = {}
        for name, b in sorted(self.backends.items()):
            ok = b.healthy()
            per[name] = "ok" if ok else (b.health_reason() or "unhealthy")
        down = [n for n, v in per.items() if v != "ok"]
        if not down:
            return 200, {"status": "ok", "models": per}
        if len(down) < len(per):
            return 200, {"status": "degraded", "models": per,
                         "unhealthy": down}
        return 503, {"status": "unhealthy", "models": per,
                     "unhealthy": down}

    def metrics_text(self) -> str:
        """The aggregated fleet /metrics: router families (tenant=/
        model= labels), a per-replica up gauge, then every replica's
        ServeStats families relabeled under its ``model=`` key — each
        family declared ONCE across all replicas
        (utils/observability.merge_prom_families)."""
        groups = [self.rstats.prom_families()]
        up = []
        for name, b in sorted(self.backends.items()):
            up.append('dsod_fleet_replica_up{model="%s"} %d'
                      % (name, 1 if b.healthy() else 0))
        groups.append([("dsod_fleet_replica_up", "gauge", up)])
        for name, b in sorted(self.backends.items()):
            groups.append(b.prom_families('model="%s"' % name))
        return render_prom_families(merge_prom_families(groups))

    def stats(self) -> Dict:
        """One JSON object: router book, per-model replica snapshots,
        and the fleet-wide accounting identity
        (``served + shed + expired + errors == submitted``, with
        router terminals folded in — eventually consistent while
        requests are in flight)."""
        router = self.rstats.snapshot()
        models = {name: b.stats_snapshot()
                  for name, b in sorted(self.backends.items())}

        def total(key: str) -> float:
            return sum(m.get(key, 0) for m in models.values()
                       if isinstance(m, dict))

        fleet = {
            "submitted": router["submitted_total"],
            "served": total("served"),
            "shed": router["shed_total"] + total("shed"),
            "expired": total("expired"),
            "errors": (router["rejected_total"]
                       + router["transport_errors_total"]
                       + total("errors")),
        }
        fleet["terminal"] = (fleet["served"] + fleet["shed"]
                             + fleet["expired"] + fleet["errors"])
        fleet["consistent"] = fleet["terminal"] == fleet["submitted"]
        return {"router": router, "models": models, "fleet": fleet}

    def describe_models(self) -> Dict:
        return {name: b.describe()
                for name, b in sorted(self.backends.items())}
