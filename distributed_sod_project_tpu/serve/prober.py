"""Synthetic canary prober (docs/OBSERVABILITY.md "Capacity & SLO").

Every sensor so far is fed by LIVE traffic — which means at zero
traffic an outage is invisible (no requests, no errors, no burn), and
a quality cliff after a hot reload waits for the first real user to
find it.  The prober closes that hole with black-box canaries:

- a background thread pushes ONE low-rate synthetic probe through the
  **full router→engine HTTP path** per tick, round-robin over the
  fleet's models, under a reserved tenant (registered at the lowest
  priority so probes are the first thing the router sheds under
  overload);
- probe inputs come from the deterministic SyntheticSOD generator WITH
  their ground-truth masks, so the returned prediction is *scored*
  (MAE + IoU@0.5 against GT) — a model serving garbage after a bad
  reload moves the probe-quality gauges even when latency looks fine;
- probe latency / availability / quality export as ``dsod_probe_*``
  families on the router's /metrics.

Accounting: probes ride the real door, so they are counted in the
router's terminal book under the probe tenant (the fleet identity
holds WITH them), they feed any model-scoped SLO objective (that is
the point — a dead replica set burns the SLO budget with zero live
traffic), and they can never touch another tenant's token bucket
(their tenant is their own).  The prober itself never queues: if the
previous probe is still in flight at the next tick, the tick is
DROPPED and counted (``dsod_probe_dropped_total``) — synthetic load
must not pile onto an already-overloaded fleet.
"""

from __future__ import annotations

import http.client
import io
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger
from ..utils.observability import LatencyHistogram

# Windowed probe gauges: small — probes are low-rate by design, and a
# cliff should move the gauge within a handful of probes.
_WINDOW = 32

_TRANSPORT_ERRORS = (urllib.error.URLError, OSError,
                     http.client.HTTPException)


def make_probe_set(n: int = 4, px: int = 64, seed: int = 1234
                   ) -> List[Tuple[bytes, np.ndarray]]:
    """``n`` deterministic ``(request_body, ground_truth_mask)`` pairs:
    SyntheticSOD samples denormalized to the uint8 request shape (the
    same in-distribution posture tools/health_smoke.py probes with),
    masks float32 (px, px) in {0, 1}."""
    from ..data.synthetic import SyntheticSOD

    ds = SyntheticSOD(size=max(n, 1), image_size=(px, px), seed=seed)
    out = []
    for i in range(n):
        s = ds[i]
        raw = np.clip(s["image"] * ds.std + ds.mean, 0.0, 1.0)
        img = (raw * 255.0).round().astype(np.uint8)
        buf = io.BytesIO()
        np.save(buf, img)
        out.append((buf.getvalue(), s["mask"][..., 0].astype(np.float32)))
    return out


def score_probe(pred: np.ndarray, gt: np.ndarray
                ) -> Tuple[float, float]:
    """``(mae, iou@0.5)`` of one probe prediction against its ground
    truth (resized to the prediction's shape when the server answered
    at a different resolution)."""
    p = np.asarray(pred, np.float32)
    g = np.asarray(gt, np.float32)
    if p.shape != g.shape:
        # Nearest-neighbor GT resize: masks are {0,1}, interpolation
        # would invent soft edges the scorer then penalizes.
        yi = (np.arange(p.shape[0]) * g.shape[0] // p.shape[0])
        xi = (np.arange(p.shape[1]) * g.shape[1] // p.shape[1])
        g = g[yi][:, xi]
    mae = float(np.mean(np.abs(p - g)))
    pb, gb = p > 0.5, g > 0.5
    union = float(np.logical_or(pb, gb).sum())
    iou = float(np.logical_and(pb, gb).sum()) / union if union else 1.0
    return mae, iou


class _Ring:
    """Fixed-window mean (the serve/quality.py idiom)."""

    __slots__ = ("_buf", "_i", "_cap")

    def __init__(self, cap: int = _WINDOW):
        self._buf: List[float] = []
        self._i = 0
        self._cap = cap

    def add(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(float(v))
        else:
            self._buf[self._i] = float(v)
            self._i = (self._i + 1) % self._cap

    def mean(self) -> float:
        return (sum(self._buf) / len(self._buf)) if self._buf else 0.0


class _ModelProbeStats:
    __slots__ = ("sent", "ok", "failed", "latency_ms", "mae", "iou",
                 "avail")

    def __init__(self):
        self.sent = 0
        self.ok = 0
        self.failed = 0
        self.latency_ms = LatencyHistogram()
        self.mae = _Ring()
        self.iou = _Ring()
        self.avail = _Ring()  # 1/0 per probe, windowed availability


class ProbeStats:
    """Thread-safe probe telemetry, owned by the Fleet (so the router's
    /metrics and /stats render it) and written by the prober thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelProbeStats] = {}
        self._dropped = 0

    def _model(self, name: str) -> _ModelProbeStats:
        st = self._models.get(name)
        if st is None:
            st = self._models[name] = _ModelProbeStats()
        return st

    def record(self, model: str, ok: bool, latency_ms: float,
               mae: Optional[float] = None,
               iou: Optional[float] = None) -> None:
        with self._lock:
            st = self._model(model)
            st.sent += 1
            st.avail.add(1.0 if ok else 0.0)
            if ok:
                st.ok += 1
                st.latency_ms.observe(latency_ms)
                if mae is not None:
                    st.mae.add(mae)
                if iou is not None:
                    st.iou.add(iou)
            else:
                st.failed += 1

    def record_dropped(self) -> None:
        with self._lock:
            self._dropped += 1

    def snapshot(self) -> Dict:
        with self._lock:
            out = {"dropped": self._dropped, "models": {}}
            for name, st in sorted(self._models.items()):
                out["models"][name] = {
                    "sent": st.sent, "ok": st.ok, "failed": st.failed,
                    "availability": round(st.avail.mean(), 4),
                    "mae_avg": round(st.mae.mean(), 6),
                    "iou_avg": round(st.iou.mean(), 6),
                    **{f"latency_{k}": v
                       for k, v in st.latency_ms.snapshot().items()},
                }
            return out

    def prom_families(self, labels: str = ""):
        """``dsod_probe_*`` families under ``model=`` labels (the
        per-arm ServeStats idiom: one TYPE per family, every model's
        sample in the one group)."""
        with self._lock:
            dropped = self._dropped
            rows = sorted(self._models.items())
            counts = [(n, st.sent, st.ok, st.failed, st.avail.mean(),
                       st.mae.mean(), st.iou.mean()) for n, st in rows]
        pre = f"{labels}," if labels else ""
        sb = f"{{{labels}}}" if labels else ""

        def lbl(n):
            return f'{pre}model="{n}"'

        fams = [("dsod_probe_dropped_total", "counter",
                 [f"dsod_probe_dropped_total{sb} {dropped}"])]
        series = (("dsod_probe_sent_total", "counter", 1),
                  ("dsod_probe_ok_total", "counter", 2),
                  ("dsod_probe_failed_total", "counter", 3),
                  ("dsod_probe_availability", "gauge", 4),
                  ("dsod_probe_mae_avg", "gauge", 5),
                  ("dsod_probe_iou_avg", "gauge", 6))
        for name, typ, idx in series:
            samples = ['%s{%s} %g' % (name, lbl(r[0]), r[idx])
                       for r in counts]
            if samples:
                fams.append((name, typ, samples))
        lat = []
        for n, st in rows:
            lat += st.latency_ms.prom_lines(
                "dsod_probe_latency_ms", labels=f'{pre}model="{n}"',
                include_type=False)
        if lat:
            fams.append(("dsod_probe_latency_ms", "histogram", lat))
        return fams


class SyntheticProber:
    """The canary thread.  ``base_url`` is the ROUTER'S OWN bound
    address (loopback) so probes traverse the full front door —
    tenancy, routing, failover, accounting — exactly like a client."""

    def __init__(self, base_url: str, models: List[str], *,
                 stats: ProbeStats, interval_s: float,
                 tenant: str = "_probe", px: int = 64,
                 timeout_s: float = 10.0, n_probes: int = 4):
        if interval_s <= 0:
            raise ValueError(
                f"prober interval_s must be > 0, got {interval_s}")
        if not models:
            raise ValueError("prober needs at least one model")
        self.base_url = base_url.rstrip("/")
        self.models = list(models)
        self.stats = stats
        self.interval_s = float(interval_s)
        self.tenant = tenant
        self.timeout_s = float(timeout_s)
        self.probes = make_probe_set(n_probes, px=px)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._worker: Optional[threading.Thread] = None
        # Drop-not-queue: one probe in flight, ever.  A busy lane at
        # tick time is a DROP (counted), never a backlog.
        self._busy = threading.Semaphore(1)
        # Guards the tick↔stop handoff of the worker handle: stop()'s
        # loop-thread join can TIME OUT (a probe wedged in urlopen),
        # after which a bare self._worker swap would race a concurrent
        # tick — losing a live worker handle (never joined) or
        # clobbering it with None mid-spawn.
        self._worker_lock = threading.Lock()
        self._i = 0
        self._log = get_logger()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "SyntheticProber":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-prober", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 5.0)
            self._thread = None
        with self._worker_lock:
            worker, self._worker = self._worker, None
        if worker is not None:
            worker.join(timeout=self.timeout_s + 5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    # -- one probe -----------------------------------------------------

    def tick(self) -> bool:
        """Fire one probe (round-robin model × probe sample) on the
        worker lane, or DROP when the previous probe is still in
        flight.  Returns True when a probe was dispatched."""
        if not self._busy.acquire(blocking=False):
            self.stats.record_dropped()
            return False
        i = self._i
        self._i += 1
        model = self.models[i % len(self.models)]
        body, gt = self.probes[i % len(self.probes)]

        def run():
            try:
                self.probe_once(model, body, gt)
            finally:
                self._busy.release()

        worker = threading.Thread(target=run, name="serve-probe",
                                  daemon=True)
        with self._worker_lock:
            if self._stop.is_set():
                # stop() is (or has been) draining: a worker spawned
                # now would never be joined — drop the tick instead.
                self._busy.release()
                self.stats.record_dropped()
                return False
            # Start BEFORE publishing, both under the lock: stop()
            # must never join a not-yet-started handle (RuntimeError),
            # and a failed start must not leak the probe lane.
            try:
                worker.start()
            except RuntimeError:  # thread resources exhausted
                self._busy.release()
                self.stats.record_dropped()
                return False
            self._worker = worker
        return True

    def probe_once(self, model: str, body: bytes, gt: np.ndarray) -> bool:
        """One synchronous probe round trip, scored and recorded."""
        headers = {"Content-Type": "application/x-npy",
                   "X-Tenant": self.tenant, "X-Model": model}
        req = urllib.request.Request(self.base_url + "/predict",
                                     data=body, headers=headers,
                                     method="POST")
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                payload = r.read()
                ok = r.status == 200
        except urllib.error.HTTPError as e:
            e.read()
            ok, payload = False, b""
        except _TRANSPORT_ERRORS:
            ok, payload = False, b""
        ms = (time.monotonic() - t0) * 1000.0
        mae = iou = None
        if ok:
            try:
                pred = np.load(io.BytesIO(payload), allow_pickle=False)
                mae, iou = score_probe(pred, gt)
            except Exception:  # noqa: BLE001 — an unscorable 200 is a
                self._log.exception("prober: could not score probe")
                ok = False  # quality outage, not a success
        self.stats.record(model, ok, ms, mae=mae, iou=iou)
        return ok
