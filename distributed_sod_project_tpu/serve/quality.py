"""Online serving quality/drift monitors (docs/OBSERVABILITY.md
"Model health").

The serving telemetry so far answers "how fast" (PR 5/9) and "how
available" (PR 7/8); this module answers "how *good*, right now":

- **Per-request output statistics** — foreground fraction, mean
  confidence, boundary entropy — cheap scalars of the predicted
  saliency map, windowed so a regime change moves the gauge.  A model
  that suddenly predicts empty masks (a bad hot reload, a broken
  preprocessing change upstream) shows up here within one window.
- **Input/output distribution drift** — online histograms of the input
  mean intensity and the output foreground fraction, scored as PSI
  (population stability index) against a CHECKED-IN reference
  histogram per model (``tools/quality_reference.json``).  PSI is the
  standard "has traffic moved off the distribution my quality gate was
  run on" number: 0 = identical, >0.25 = conventionally "major shift".
- **Shadow scoring** — a sampled fraction of non-f32 responses
  re-scored on the f32 reference arm, recording the live arm-vs-f32
  disagreement (mean |Δ| and thresholded-mask flip rate).  This turns
  ``tools/precision_gate.py``'s offline per-arm budget into a
  CONTINUOUS online check against real traffic: the offline gate
  proves an arm safe on the eval set at ship time; the shadow gauges
  prove it is still safe on today's traffic and today's weights.

All of it renders as ``dsod_quality_*`` families through the engine's
``TelemetryRegistry`` (model=/arm= labels ride the same label plumbing
as every other serve family, so the fleet router aggregates them for
free) and feeds the alert engine (utils/alerts.py).  Everything is off
by default (``serve.quality_monitor=false``): the request hot path
pays a None check and /metrics stays byte-identical.

Cost model (measured in docs/OBSERVABILITY.md): output stats subsample
the bucket-resolution map to ≤ ~4k pixels (a few µs of numpy); the
input stat is one ``mean()`` over the request image; shadow scoring is
the expensive lever — ONE extra f32 forward per sampled response, run
on a single-thread side lane capped at 2 in flight that DROPS (counted
``dsod_quality_shadow_dropped_total``) rather than queue behind live
traffic, so its worst-case tax is bounded by the sampling rate, not
the offered load.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.alerts import Rule

# Uniform-bin histograms over [0, 1] for both drift signals.
PSI_BINS = 10
# Halve the online histogram once it holds this many observations so
# the PSI compares RECENT traffic, not the run's whole history.
HIST_CAP = 4096
# Windowed means: enough to be stable, small enough to track a regime
# change within ~a window of traffic.
WINDOW = 256

DRIFT_SIGNALS = ("input_mean", "fg_fraction")

DEFAULT_REFERENCE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "quality_reference.json")


def input_mean01(image: np.ndarray) -> float:
    """The request image's mean intensity in [0, 1] (uint8 and float
    [0,1] images normalize identically — the drift histogram must not
    split on the client's dtype)."""
    arr = np.asarray(image)
    if arr.dtype == np.uint8:
        return float(arr.mean()) / 255.0
    return float(np.clip(arr, 0.0, 1.0).mean())


def output_stats(pred: np.ndarray, max_pixels: int = 4096
                 ) -> Tuple[float, float, float]:
    """``(fg_fraction, mean_confidence, boundary_entropy)`` of one
    probability map, on a strided subsample bounded at ``max_pixels``:

    - fg_fraction — fraction of pixels past the 0.5 decision line;
    - mean_confidence — mean ``|p - 0.5| * 2`` (1 = saturated, 0 =
      everywhere-ambiguous);
    - boundary_entropy — mean binary entropy in bits (high = wide
      uncertain boundary band, the classic quality-collapse shape).
    """
    p = np.asarray(pred, np.float32)
    if p.size > max_pixels:
        stride = int(math.ceil(math.sqrt(p.size / max_pixels)))
        p = p[::stride, ::stride]
    p = np.clip(p, 1e-6, 1.0 - 1e-6)
    fg = float((p > 0.5).mean())
    conf = float(np.abs(p - 0.5).mean() * 2.0)
    ent = float((-(p * np.log2(p) + (1 - p) * np.log2(1 - p))).mean())
    return fg, conf, ent


def psi(cur_counts: Sequence[float], ref_counts: Sequence[float],
        eps: float = 1e-4) -> float:
    """Population stability index between two histograms (smoothed so
    empty bins cannot produce infinities)."""
    cur = np.asarray(cur_counts, np.float64)
    ref = np.asarray(ref_counts, np.float64)
    if cur.sum() <= 0 or ref.sum() <= 0:
        return 0.0
    n = len(cur)
    p = (cur + eps) / (cur.sum() + eps * n)
    q = (ref + eps) / (ref.sum() + eps * n)
    return float(np.sum((p - q) * np.log(p / q)))


def load_reference(path: str, model_name: str) -> Optional[Dict]:
    """Reference histograms for ``model_name``:
    ``{signal: [counts per uniform [0,1] bin]}``.

    ``path=""`` falls back to the checked-in
    ``tools/quality_reference.json`` and answers None (drift gauges
    idle) when it is absent or has no entry; an EXPLICIT path that is
    missing or lacks the model raises — a named reference that
    silently doesn't apply would report PSI 0 forever."""
    p = path or DEFAULT_REFERENCE_PATH
    if not os.path.exists(p):
        if path:
            raise ValueError(f"serve.quality_reference {path!r} not found")
        return None
    with open(p) as f:
        data = json.load(f)
    entry = data.get(model_name)
    if entry is None:
        if path:
            raise ValueError(
                f"serve.quality_reference {path!r} has no entry for "
                f"model {model_name!r} (has: {sorted(data)})")
        return None
    out = {}
    for sig in DRIFT_SIGNALS:
        counts = entry.get(sig)
        if counts is not None:
            if len(counts) != PSI_BINS:
                raise ValueError(
                    f"reference {sig!r} for {model_name!r} has "
                    f"{len(counts)} bins, expected {PSI_BINS}")
            out[sig] = [float(c) for c in counts]
    return out or None


def default_quality_rules(sc) -> List[Rule]:
    """The built-in serving alert set (custom rules join via
    ``serve.alert_rules``): drift PSI past its threshold, shadow
    disagreement past its budget — both with the configured hysteresis
    dwells."""
    return [
        Rule("quality_drift_psi", "quality_psi_max", "gt",
             sc.quality_psi_threshold,
             for_s=sc.quality_alert_for_s,
             clear_s=sc.quality_alert_clear_s),
        Rule("quality_shadow_disagreement", "shadow_mae_max", "gt",
             sc.quality_shadow_budget,
             for_s=sc.quality_alert_for_s,
             clear_s=sc.quality_alert_clear_s),
    ]


class _Ring:
    """Fixed-window mean (the TailEstimator idiom without the sort)."""

    __slots__ = ("_buf", "_i", "_cap", "_n")

    def __init__(self, cap: int = WINDOW):
        self._buf: List[float] = []
        self._i = 0
        self._cap = cap
        self._n = 0  # total ever observed

    def add(self, v: float) -> None:
        if len(self._buf) < self._cap:
            self._buf.append(float(v))
        else:
            self._buf[self._i] = float(v)
            self._i = (self._i + 1) % self._cap
        self._n += 1

    def mean(self) -> float:
        return (sum(self._buf) / len(self._buf)) if self._buf else 0.0

    @property
    def count(self) -> int:
        return self._n


class QualityMonitor:
    """Per-engine (one model) online quality state.  Thread-safe: the
    HTTP handler pool writes input stats, the post pool writes output
    stats, the shadow lane writes disagreements, and the telemetry
    renderers read concurrently."""

    def __init__(self, model_name: str, *, shadow_sample: float = 0.0,
                 reference: Optional[Dict] = None,
                 psi_min_count: int = 64):
        if not 0.0 <= float(shadow_sample) <= 1.0:
            raise ValueError(
                "serve.quality_shadow_sample must be in [0, 1], got "
                f"{shadow_sample}")
        if int(psi_min_count) < 1:
            raise ValueError(
                "serve.quality_psi_min_count must be >= 1, got "
                f"{psi_min_count}")
        self.model_name = model_name
        self.shadow_sample = float(shadow_sample)
        self.reference = reference
        self.psi_min_count = int(psi_min_count)
        self._lock = threading.Lock()
        self._scored = 0
        self._fg = _Ring()
        self._conf = _Ring()
        self._ent = _Ring()
        self._hists: Dict[str, List[float]] = {
            s: [0.0] * PSI_BINS for s in DRIFT_SIGNALS}
        # arm → (mae ring, flip ring)
        self._shadow: Dict[str, Tuple[_Ring, _Ring]] = {}
        self._shadow_total: Dict[str, int] = {}
        self._shadow_dropped = 0
        self._shadow_acc = 0.0  # deterministic sampling accumulator

    # -- ingest --------------------------------------------------------

    def _bump_hist(self, signal: str, value01: float) -> None:
        if not math.isfinite(value01):
            return  # a NaN-poisoned input is not drift evidence
        h = self._hists[signal]
        i = min(max(int(value01 * PSI_BINS), 0), PSI_BINS - 1)
        h[i] += 1.0
        if sum(h) >= HIST_CAP:  # keep PSI about RECENT traffic
            self._hists[signal] = [c / 2.0 for c in h]

    def observe_input(self, mean01: float) -> None:
        with self._lock:
            self._bump_hist("input_mean", mean01)

    def observe_output(self, pred: np.ndarray) -> None:
        fg, conf, ent = output_stats(pred)
        with self._lock:
            self._scored += 1
            self._fg.add(fg)
            self._conf.add(conf)
            self._ent.add(ent)
            self._bump_hist("fg_fraction", fg)

    def should_shadow(self) -> bool:
        """Deterministic counter sampling: at rate r, True on the
        requests where the accumulated rate crosses an integer — every
        request at r=1, every other at r=0.5, never at r=0."""
        if self.shadow_sample <= 0.0:
            return False
        with self._lock:
            self._shadow_acc += self.shadow_sample
            if self._shadow_acc >= 1.0:
                self._shadow_acc -= 1.0
                return True
            return False

    def record_shadow(self, arm: str, mae: float, flip: float) -> None:
        with self._lock:
            rings = self._shadow.get(arm)
            if rings is None:
                rings = self._shadow[arm] = (_Ring(), _Ring())
            rings[0].add(mae)
            rings[1].add(flip)
            self._shadow_total[arm] = self._shadow_total.get(arm, 0) + 1

    def record_shadow_dropped(self) -> None:
        with self._lock:
            self._shadow_dropped += 1

    # -- reads ---------------------------------------------------------

    def psi_values(self) -> Dict[str, float]:
        """PSI per drift signal vs the reference.  Empty without a
        reference, and a signal renders no verdict until its online
        histogram holds ``psi_min_count`` observations — an unwarmed
        histogram scored against a reference reads as a huge (false)
        shift."""
        with self._lock:
            if not self.reference:
                return {}
            return {s: round(psi(self._hists[s], self.reference[s]), 6)
                    for s in DRIFT_SIGNALS
                    if s in self.reference
                    and sum(self._hists[s]) >= self.psi_min_count}

    def histogram(self, signal: str) -> List[float]:
        with self._lock:
            return list(self._hists[signal])

    def signals(self) -> Tuple[Dict[str, float], Dict[str, str]]:
        """``(signals, details)`` for the alert engine: the worst PSI
        and the worst per-arm shadow disagreement, detail-tagged with
        which signal/arm is responsible."""
        psis = self.psi_values()
        with self._lock:
            shadow = {a: (r[0].mean(), r[1].mean())
                      for a, r in self._shadow.items()}
            sigs = {
                "fg_fraction_avg": self._fg.mean(),
                "confidence_avg": self._conf.mean(),
                "boundary_entropy_avg": self._ent.mean(),
            }
        details: Dict[str, str] = {}
        sigs["quality_psi_max"] = max(psis.values(), default=0.0)
        if psis:
            worst = max(psis, key=psis.get)
            details["quality_psi_max"] = f"signal={worst}"
        sigs["shadow_mae_max"] = max(
            (m for m, _f in shadow.values()), default=0.0)
        sigs["shadow_flip_max"] = max(
            (f for _m, f in shadow.values()), default=0.0)
        if shadow:
            worst = max(shadow, key=lambda a: shadow[a][0])
            details["shadow_mae_max"] = f"arm={worst}"
        return sigs, details

    def snapshot(self) -> Dict:
        psis = self.psi_values()
        with self._lock:
            out = {
                "scored": self._scored,
                "fg_fraction_avg": round(self._fg.mean(), 6),
                "confidence_avg": round(self._conf.mean(), 6),
                "boundary_entropy_avg": round(self._ent.mean(), 6),
                "shadow_sample": self.shadow_sample,
                "shadow_dropped": self._shadow_dropped,
                "shadow": {
                    a: {"n": self._shadow_total[a],
                        "mae_avg": round(r[0].mean(), 6),
                        "flip_avg": round(r[1].mean(), 6)}
                    for a, r in sorted(self._shadow.items())},
            }
        if psis:
            out["psi"] = psis
        return out

    def reference_counts(self) -> Dict[str, List[float]]:
        """The CURRENT histograms in the reference-file shape — what
        ``tools/quality_reference.py`` writes after an in-distribution
        calibration run."""
        with self._lock:
            return {s: list(self._hists[s]) for s in DRIFT_SIGNALS}

    # -- exposition ----------------------------------------------------

    def prom_families(self, labels: str = ""):
        """The ``dsod_quality_*`` families.  Base families render
        unconditionally (inventory-stable); per-arm shadow families
        render one sample per arm that has shadow data, sharing one
        TYPE line (the per-arm ServeStats idiom); PSI renders one
        sample per referenced signal."""
        psis = self.psi_values()
        with self._lock:
            scored = self._scored
            fg, conf, ent = (self._fg.mean(), self._conf.mean(),
                             self._ent.mean())
            dropped = self._shadow_dropped
            shadow = [(a, self._shadow_total[a], r[0].mean(), r[1].mean())
                      for a, r in sorted(self._shadow.items())]
        sb = f"{{{labels}}}" if labels else ""
        pre = f"{labels}," if labels else ""
        fams = [
            ("dsod_quality_scored_total", "counter",
             [f"dsod_quality_scored_total{sb} {scored}"]),
            ("dsod_quality_fg_fraction_avg", "gauge",
             [f"dsod_quality_fg_fraction_avg{sb} {fg:g}"]),
            ("dsod_quality_confidence_avg", "gauge",
             [f"dsod_quality_confidence_avg{sb} {conf:g}"]),
            ("dsod_quality_boundary_entropy_avg", "gauge",
             [f"dsod_quality_boundary_entropy_avg{sb} {ent:g}"]),
            ("dsod_quality_shadow_dropped_total", "counter",
             [f"dsod_quality_shadow_dropped_total{sb} {dropped}"]),
        ]
        if psis:
            fams.append(("dsod_quality_psi", "gauge", [
                'dsod_quality_psi{%ssignal="%s"} %g' % (pre, s, v)
                for s, v in sorted(psis.items())]))
        if shadow:
            fams.append(("dsod_quality_shadow_total", "counter", [
                'dsod_quality_shadow_total{%sarm="%s"} %d'
                % (pre, a, n) for a, n, _m, _f in shadow]))
            fams.append(("dsod_quality_shadow_mae_avg", "gauge", [
                'dsod_quality_shadow_mae_avg{%sarm="%s"} %g'
                % (pre, a, m) for a, _n, m, _f in shadow]))
            fams.append(("dsod_quality_shadow_flip_avg", "gauge", [
                'dsod_quality_shadow_flip_avg{%sarm="%s"} %g'
                % (pre, a, f) for a, _n, _m, f in shadow]))
        return fams
