"""Dynamic micro-batching: coalesce concurrent requests into the static
batch buckets the compiled programs expect (docs/SERVING.md).

The TPU-shaped constraint (same as eval/inference.py): the compiled
forward only ever sees ONE static shape per (resolution, batch) bucket,
so the request plane's job is to group same-resolution requests and pad
up to a bucket — never to hand XLA a new shape.  The coalescing rule
balances occupancy against latency: a batch dispatches the moment the
largest bucket fills, or when its oldest request has waited
``max_wait``, whichever comes first.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .admission import QueueFull

# Per-stream affinity entries are tiny (key string -> bucket tuple) but
# adversarial stream ids must not grow the map without bound; LRU-cap it
# well above any realistic concurrent-stream count.
AFFINITY_CAP = 4096


@dataclass
class Request:
    """One in-flight prediction request.

    ``tensor`` is the preprocessed (res, res, 3) float32 input — resize
    + normalize happen in the submitting thread (the HTTP handler pool)
    so the dispatch loop never does per-request host work.
    ``precision`` is the arm the request will be served at (already
    ladder-adjusted at submit) — it is part of the coalescing key,
    because a batch runs through exactly ONE compiled program.
    ``deadline`` is monotonic-clock absolute (None = no SLO).  The
    result — ``(pred, meta)`` with pred the float32 (H, W) saliency map
    at the request's ORIGINAL resolution — or a shed/expiry exception
    is delivered through ``future``.
    """

    tensor: np.ndarray
    orig_hw: Tuple[int, int]
    res_bucket: int
    arrival: float
    precision: str = "f32"
    deadline: Optional[float] = None
    degraded: bool = False
    level: int = 0
    future: Future = field(default_factory=Future)
    dispatch_t: float = 0.0
    # Per-stream affinity key (serve/streams.py; None for the normal
    # independent-request path — the batcher then behaves exactly as it
    # did before streaming existed).  Frames carrying the same stream
    # key coalesce into the same (res_bucket, precision) program: the
    # batcher records stream -> bucket_key on every put and the engine
    # consults it at submit to keep a stream on one compiled program.
    stream: Optional[str] = None
    # Optional depth plane riding with an RGB-D request — (res, res, 1)
    # float32, preprocessed alongside ``tensor`` (satellite: RGB-D
    # serving).  None for RGB models; the dispatch loop stacks it into
    # the batch dict only when present.
    depth: Optional[np.ndarray] = field(default=None, repr=False)
    # Tracing (utils/tracing.py): the request's trace id (propagated
    # from X-Request-ID) and its open root span — None when the trace
    # was not sampled, and every span touch downstream guards on that.
    trace_id: Optional[str] = None
    root: object = field(default=None, repr=False)

    @property
    def bucket_key(self) -> Tuple[int, str]:
        """The coalescing key: same resolution AND same precision arm
        (one compiled program per group)."""
        return (self.res_bucket, self.precision)


class DynamicBatcher:
    """Thread-safe coalescing queue over per-(resolution, precision)
    bucket deques.

    ``get_batch`` (the dispatch loop's pull) blocks until it can return
    ``((res_bucket, precision), requests)`` where the group is FIFO
    within its bucket key, never exceeds the largest batch bucket, and
    is released early once the oldest member has waited ``max_wait_s``
    (the max-wait deadline holds even when no further requests ever
    arrive — a stalled queue still drains).  Bucket keys are served
    oldest-head-first so no bucket starves.
    """

    def __init__(self, batch_buckets, max_wait_s: float,
                 max_queue: Optional[int] = None, clock=time.monotonic):
        buckets = sorted(int(b) for b in batch_buckets)
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bad batch_buckets {batch_buckets!r}")
        self.batch_buckets = tuple(buckets)
        self.max_batch = buckets[-1]
        self.max_wait_s = float(max_wait_s)
        self.max_queue = max_queue
        self._clock = clock
        self._queues: Dict[int, deque] = {}
        self._cv = threading.Condition()
        self._closed = False
        # stream key -> last bucket_key, LRU-bounded.  Written on every
        # put of a stream-tagged request; read by the engine at submit
        # so a stream's next frame preprocesses into the SAME
        # (res_bucket, precision) program.  Empty (and never touched)
        # when no request carries a stream key.
        self._affinity: "OrderedDict[str, Tuple[int, str]]" = OrderedDict()

    # -- producer side -------------------------------------------------

    def put(self, req: Request) -> None:
        """Enqueue, or raise :class:`QueueFull`.  The depth check and
        the append share the lock — N concurrent producers can never
        overshoot ``max_queue`` the way a check-then-put from outside
        would (each would read the same depth and all pass)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self.max_queue is not None:
                depth = sum(len(q) for q in self._queues.values())
                if depth >= self.max_queue:
                    raise QueueFull(
                        f"queue at capacity ({depth}/{self.max_queue})")
            self._queues.setdefault(req.bucket_key, deque()).append(req)
            if req.stream is not None:
                self._affinity[req.stream] = req.bucket_key
                self._affinity.move_to_end(req.stream)
                while len(self._affinity) > AFFINITY_CAP:
                    self._affinity.popitem(last=False)
            self._cv.notify_all()

    def affinity_bucket(self, stream: Optional[str]
                        ) -> Optional[Tuple[int, str]]:
        """The (res_bucket, precision) program the stream's previous
        frame coalesced into, or None for an unknown/absent stream."""
        if stream is None:
            return None
        with self._cv:
            return self._affinity.get(stream)

    def pending(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    # -- consumer side -------------------------------------------------

    def _oldest_head(self) -> Optional[Request]:
        head = None
        for q in self._queues.values():
            if q and (head is None or q[0].arrival < head.arrival):
                head = q[0]
        return head

    def _next_group_locked(self, now: float) -> Optional[Tuple[int, str]]:
        """The bucket key that should dispatch RIGHT NOW, or None.

        A FULL group dispatches immediately — oldest-head-first among
        full groups — even when the globally-oldest head sits in a
        different, unfilled group.  Under per-stream affinity a pinned
        stream can fill its group arbitrarily fast; the pre-affinity
        rule (only ever examine the oldest head's queue) stalled such a
        group behind an unrelated older head's max-wait window, growing
        it toward max_queue sheds.  The max-wait deadline itself is
        untouched: the oldest head still dispatches no later than its
        own ``arrival + max_wait_s`` — a stream filling some other
        group never extends it.
        """
        head = self._oldest_head()
        if head is None:
            return None
        full = None
        for q in self._queues.values():
            if len(q) >= self.max_batch and (
                    full is None or q[0].arrival < full[0].arrival):
                full = q
        if full is not None:
            return full[0].bucket_key
        if (head.arrival + self.max_wait_s) <= now:
            return head.bucket_key
        return None

    def get_batch(self, idle_timeout_s: float
                  ) -> Optional[Tuple[Tuple[int, str], List[Request]]]:
        """Next coalesced group as ``((res_bucket, precision), reqs)``,
        or None after ``idle_timeout_s`` with an empty queue (so the
        caller's loop can heartbeat)."""
        idle_deadline = self._clock() + idle_timeout_s
        with self._cv:
            while True:
                if self._closed:
                    return None
                now = self._clock()
                key = self._next_group_locked(now)
                if key is not None:
                    q = self._queues[key]
                    n = min(len(q), self.max_batch)
                    return key, [q.popleft() for _ in range(n)]
                head = self._oldest_head()
                if head is None:
                    if now >= idle_deadline:
                        return None
                    self._cv.wait(min(idle_deadline - now, 0.05))
                    continue
                wait_left = (head.arrival + self.max_wait_s) - now
                self._cv.wait(min(wait_left, 0.05))

    def _ready_locked(self, now: float) -> bool:
        return self._next_group_locked(now) is not None

    def ready(self) -> bool:
        """True when a group would dispatch RIGHT NOW (full bucket, or
        the oldest head past max-wait) — the non-blocking probe the
        fleet's interleaved dispatch loop polls so one slow model's
        coalescing wait never blocks its co-resident siblings."""
        with self._cv:
            if self._closed:
                return False
            return self._ready_locked(self._clock())

    def poll_batch(self) -> Optional[Tuple[Tuple[int, str], List[Request]]]:
        """Non-blocking :meth:`get_batch`: the next coalesced group if
        one is ready, else None immediately (never waits on max-wait or
        an empty queue)."""
        with self._cv:
            if self._closed:
                return None
            key = self._next_group_locked(self._clock())
            if key is None:
                return None
            q = self._queues[key]
            n = min(len(q), self.max_batch)
            return key, [q.popleft() for _ in range(n)]

    def pick_batch_bucket(self, n: int) -> int:
        """Smallest static batch bucket that fits ``n`` requests (the
        largest bucket when none does — callers never hand us more than
        ``max_batch``, but stay total anyway)."""
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.max_batch

    # -- shutdown ------------------------------------------------------

    def close(self) -> List[Request]:
        """Stop accepting work; returns every still-queued request so
        the engine can fail their futures instead of leaking waiters."""
        with self._cv:
            self._closed = True
            drained = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._cv.notify_all()
        return drained
