"""Precision arms for the serving fast path (docs/SERVING.md
"Precision arms").

TF-Replicator's thin-abstraction thesis, extended one axis: the serve
engine already owns a *family* of compiled programs keyed on static
shape (resolution bucket × batch bucket); this module adds a precision
axis to that family.  Each arm is a **cast-on-load weight view** of the
one f32 variables pytree the checkpoint owns — the request plane picks
an arm per request, the program cache holds one AOT-compiled executable
per (shape bucket, arm), and nothing about the f32 source of truth
changes (hot reload re-derives every view from the freshly restored
state).

Arms, best quality first (``PRECISION_ORDER``):

- ``f32``  — the identity view; bitwise the offline eval path.
- ``bf16`` — every floating leaf cast to bfloat16: half the weight
  bytes in HBM and no per-dispatch f32→bf16 weight cast inside the
  program (the zoo's ``compute_dtype`` is bf16 already, so the math
  was rounding there anyway — this arm moves the rounding to load
  time and halves the weight traffic).
- ``int8`` — weight-only symmetric per-output-channel quantization of
  every ≥2-D floating leaf (conv kernels, dense matrices); biases and
  BN stats stay f32.  The compiled program dequantizes on the fly
  (``q·scale``), so weights ship and live at 1/4 the bytes.
- ``fp8``  — same per-channel scaling, stored as ``float8_e4m3fn``
  (only offered when this jaxlib build has the dtype —
  ``supported_arms()`` gates it).

Quality is not assumed: ``tools/precision_gate.py`` scores every
enabled arm against f32 on a fixed eval set (max-Fβ / MAE) and fails
loudly when an arm drifts past its checked-in budget.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Best → worst expected quality; the degraded ladder steps DOWN this
# order (within the enabled set) before it touches resolution.
PRECISION_ORDER: Tuple[str, ...] = ("f32", "bf16", "int8", "fp8")

# Arms whose weight view is a (quantized leaves, scales) bundle rather
# than a plain cast of the variables pytree.
QUANT_ARMS: Tuple[str, ...] = ("int8", "fp8")

# Largest representable magnitudes the per-channel scale maps amax to.
_QMAX = {"int8": 127.0, "fp8": 448.0}  # e4m3fn max normal = 448


def quant_dtypes():
    """The storage dtypes of quantized weight leaves this jaxlib build
    knows — the ONE definition (pallas/fused_conv.is_quantized_weight
    and every dequant site key off it)."""
    return tuple(jnp.dtype(d) for d in ("int8",)) + (
        (jnp.dtype(jnp.float8_e4m3fn),)
        if hasattr(jnp, "float8_e4m3fn") else ())


def supported_arms() -> Tuple[str, ...]:
    """Arms this jaxlib build can serve (fp8 needs the float8 dtype)."""
    arms = ["f32", "bf16", "int8"]
    if hasattr(jnp, "float8_e4m3fn"):
        arms.append("fp8")
    return tuple(arms)


def validate_arms(arms: Sequence[str], default: str) -> Tuple[str, ...]:
    """Normalize a config's enabled-arm set: known, supported, deduped,
    ordered best-quality-first, and containing the default arm.
    Raises ``ValueError`` naming the offending knob."""
    sup = supported_arms()
    seen = []
    for a in arms:
        if a not in PRECISION_ORDER:
            raise ValueError(
                f"unknown precision arm {a!r} in serve.precision_arms; "
                f"known: {list(PRECISION_ORDER)}")
        if a not in sup:
            raise ValueError(
                f"precision arm {a!r} is not supported by this jaxlib "
                f"build (supported: {list(sup)})")
        if a not in seen:
            seen.append(a)
    if not seen:
        raise ValueError("serve.precision_arms must enable at least one arm")
    if default not in seen:
        raise ValueError(
            f"serve.precision={default!r} is not among the enabled "
            f"serve.precision_arms {list(seen)}")
    return tuple(sorted(seen, key=PRECISION_ORDER.index))


def step_down(arm: str, enabled: Sequence[str], steps: int = 1) -> str:
    """``steps`` quality notches below ``arm`` within ``enabled``
    (clamped at the lowest enabled arm; 0 steps is the identity).
    ``enabled`` must be ordered best-first (``validate_arms`` output)."""
    if arm not in enabled:
        raise ValueError(f"arm {arm!r} not in enabled set {list(enabled)}")
    i = list(enabled).index(arm)
    return enabled[min(i + max(int(steps), 0), len(enabled) - 1)]


# -- weight views ------------------------------------------------------


def _is_weight(x) -> bool:
    """Quantization targets: ≥2-D floating leaves (conv kernels, dense
    matrices).  1-D floats (biases, BN scale/offset/stats) stay f32 —
    they are byte-trivial and quality-critical."""
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) and \
        np.ndim(x) >= 2


def quantize_variables(variables, arm: str) -> Dict[str, Any]:
    """f32 variables → ``{"q": leaves, "s": scales}`` bundle (same
    treedef twice).  Weight leaves are stored at 8 bits with a
    per-output-channel (last axis) symmetric scale; every other leaf
    rides along unchanged in ``q`` (its ``s`` slot is a placeholder the
    dequantizer never reads)."""
    if arm not in QUANT_ARMS:
        raise ValueError(f"{arm!r} is not a quantized arm ({QUANT_ARMS})")
    qmax = _QMAX[arm]
    one = np.ones((), np.float32)  # placeholder scale for pass-through

    def split(leaf):
        if not _is_weight(leaf):
            return np.asarray(jax.device_get(leaf)), one
        x = np.asarray(jax.device_get(leaf), np.float32)
        axes = tuple(range(x.ndim - 1))
        amax = np.max(np.abs(x), axis=axes, keepdims=True)
        scale = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
        if arm == "int8":
            q = np.clip(np.round(x / scale), -qmax, qmax).astype(np.int8)
        else:  # fp8: the cast itself rounds to e4m3's grid
            q = (x / scale).astype(jnp.float8_e4m3fn)
        return q, scale

    flat = jax.tree_util.tree_map(split, variables)
    return {
        "q": jax.tree_util.tree_map(lambda p: p[0], flat,
                                    is_leaf=lambda p: isinstance(p, tuple)),
        "s": jax.tree_util.tree_map(lambda p: p[1], flat,
                                    is_leaf=lambda p: isinstance(p, tuple)),
    }


def dequantize_variables(qvars: Dict[str, Any]):
    """Bundle → dense f32-ish variables (runs inside the compiled
    forward; the dtype check is static at trace time)."""
    qdtypes = quant_dtypes()

    def deq(q, s):
        if jnp.asarray(q).dtype in qdtypes:
            return q.astype(jnp.float32) * s
        return q

    return jax.tree_util.tree_map(deq, qvars["q"], qvars["s"])


def fused_conv_sites(model, variables, probe: Dict[str, Any]):
    """Scope paths (tuples of names) of every ConvBNAct that routes the
    fused conv seam in this model — discovered by one ABSTRACT apply
    (``jax.eval_shape``, no FLOPs) collecting the seam's
    ``dsod_fused_conv`` sow markers.  Each returned scope's
    ``Conv_0/kernel`` param is consumed by ``pallas/fused_conv.py``,
    which dequantizes int8/fp8 leaves in-VMEM — those kernels may stay
    quantized in the apply variables (``fused_conv_cast_variables``)."""

    def _run(v):
        return model.apply(v, probe["image"], probe.get("depth"),
                           train=False, mutable=["dsod_fused_conv"])

    # Abstract trace: ShapeDtypeStructs in and out, nothing executes.
    _, aux = jax.eval_shape(_run, variables)
    sites = []
    flat = jax.tree_util.tree_flatten_with_path(
        aux.get("dsod_fused_conv", {}))[0]
    for path, _ in flat:
        names = []
        for p in path:
            key = getattr(p, "key", None)
            if key is None:
                continue  # tuple index inside the sow'd value
            names.append(str(key))
        if names and names[-1] == "site":
            names = names[:-1]
        if tuple(names) not in sites:
            sites.append(tuple(names))
    return tuple(sites)


def fused_conv_cast_variables(model, variables, arm: str,
                              probe: Dict[str, Any],
                              sites=None) -> Dict[str, Any]:
    """The quantized weight view for a ``model.conv_impl=fused`` model:
    apply-ready variables where every fused-seam conv kernel STAYS an
    int8/fp8 leaf (dequantized in-VMEM by the kernel, per-channel scale
    delivered via a parallel ``quant_scales`` collection the seam reads
    back), and every other quantized leaf — plain head convs, dense
    matrices — is densely dequantized up front exactly as the
    ``dequantize_variables`` program would have produced it.

    Unlike :func:`cast_variables`' ``{"q", "s"}`` bundle this view runs
    through the UNWRAPPED canonical forward (``make_precision_forward``
    returns ``make_forward`` itself for fused+quant), so the fused
    kernels see 1/4-byte weights end-to-end with no dense dequantized
    copy materialized per dispatch.
    """
    if arm not in QUANT_ARMS:
        raise ValueError(f"{arm!r} is not a quantized arm ({QUANT_ARMS})")
    if sites is None:
        # ``sites`` lets multi-arm callers (the engine's reload path)
        # pay the abstract discovery trace once, not once per arm.
        sites = fused_conv_sites(model, variables, probe)
    if not sites:
        raise ValueError(
            "fused_conv_cast_variables: the model routed no fused conv "
            "sites — is model.conv_impl set to 'fused'?")
    keep = {("params",) + s + ("Conv_0", "kernel") for s in sites}
    bundle = quantize_variables(variables, arm)
    qdtypes = quant_dtypes()

    out: Dict[str, Any] = {}
    scales: Dict[str, Any] = {}

    def _names(path):
        return tuple(str(getattr(p, "key")) for p in path
                     if getattr(p, "key", None) is not None)

    flat_q = jax.tree_util.tree_flatten_with_path(bundle["q"])[0]
    flat_s = {(_names(p)): s for p, s
              in jax.tree_util.tree_flatten_with_path(bundle["s"])[0]}

    def _set(tree, names, leaf):
        node = tree
        for n in names[:-1]:
            node = node.setdefault(n, {})
        node[names[-1]] = leaf

    for path, q in flat_q:
        names = _names(path)
        s = flat_s[names]
        if jnp.asarray(q).dtype in qdtypes:
            if names in keep:
                _set(out, names, q)
                # quant_scales mirrors the params subtree minus the
                # leading collection name (it IS a collection).
                _set(scales, ("quant_scales",) + names[1:], s)
            else:
                _set(out, names, (np.asarray(q, np.float32) * s))
        else:
            _set(out, names, q)
    if scales:
        out.update(scales)
    return out


def cast_variables(variables, arm: str):
    """The arm's weight view of an f32 variables pytree.

    - ``f32``: the identity (same object — no copy).
    - ``bf16``: every floating leaf cast to bfloat16.
    - ``int8``/``fp8``: the quantized ``{"q", "s"}`` bundle
      (:func:`quantize_variables`).
    """
    if arm == "f32":
        return variables
    if arm == "bf16":
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            variables)
    if arm in QUANT_ARMS:
        return quantize_variables(variables, arm)
    raise ValueError(f"unknown precision arm {arm!r}")


# -- forwards ----------------------------------------------------------


def make_precision_forward(model, arm: str, conv_impl: str = "xla"):
    """The canonical serving forward for one arm:
    ``(arm_variables, batch) -> probs`` (sigmoid on the primary logit,
    f32, [B,H,W]) — the same contract as ``eval/inference.make_forward``
    so a served map is bitwise what a direct call at the same arm
    produces.  f32/bf16 arms run ``make_forward`` itself (plain
    variables); quantized arms dequantize in-program first — EXCEPT at
    ``conv_impl='fused'``, where the arm variables are the apply-ready
    :func:`fused_conv_cast_variables` view (conv kernels stay int8/fp8
    into the Pallas kernels; the residual non-conv leaves were already
    densified at view-build time), so the canonical forward runs as-is.
    """
    from ..eval.inference import make_forward

    base = make_forward(model)
    if arm in ("f32", "bf16"):
        return base
    if arm not in QUANT_ARMS:
        raise ValueError(f"unknown precision arm {arm!r}")
    if conv_impl == "fused":
        return base

    # Delegate to the ONE canonical forward (inlined at trace time):
    # the quantized arms can never drift from the eval-path contract.
    @jax.jit
    def forward(qvars, batch):
        return base(dequantize_variables(qvars), batch)

    return forward
