"""Closed-loop fleet controller — sensor-driven autoscaling with a
spot-aware replica lifecycle (docs/SERVING.md "Fleet control plane").

Five observability PRs built the sensors: the router's exact terminal
book, SLO burn rates, the capacity ledger's stage-share attribution
(queue vs host vs device), per-replica health/breaker gauges, and the
flight recorder.  This module is the ACTUATOR that consumes them:

- **Heal.**  A replica set below its target count (a member SIGKILLed,
  its process crash-looped) gets a new supervised subprocess — spawned
  from ``ctrl_spawn_cmd``, crash-loop backoff per set, admitted into
  the router's :class:`~.fleet.ReplicaSet` only after its /healthz
  answers (breaker/health-gated admission: a corpse never enters
  routing).
- **Scale out — but only when it would help.**  SLO burn at or past
  ``ctrl_scale_out_burn`` AND the replicas' queue stage share at or
  past ``ctrl_queue_share`` (queue-bound: another replica absorbs the
  backlog) spawns a member, dwell-gated (``ctrl_dwell_s``) with a
  post-action cooldown — the degraded ladder's fake-clock-provable
  hysteresis idiom.  Burn WITHOUT queue share means the bottleneck is
  host- or device-side; the controller REFUSES and records which,
  because a second replica on the same device just splits the same
  roofline.
- **Scale in / preemption: drain, never kill.**  Scale-in (and a spot
  preemption notice, via :class:`~..utils.observability.
  PreemptionGuard` or :meth:`FleetController.notify_preemption`) flips
  the victim to DRAINING — out of routing immediately, in-flight work
  completes — and only after ``ctrl_drain_grace_s`` is the process
  retired (SIGTERM first: the replica's own clean drain).

Every decision — spawn, restart, refusal (with why), drain, retire —
is booked through :meth:`FleetController._record`, THE controller
accounting seam (tools/dsodlint.py ``BOOKING_SEAMS``): one typed
flight-recorder event plus one ``dsod_ctrl_decisions_total`` sample
per decision.  All of it is off by default (``controller=false``) and
/metrics stays byte-identical while it is.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger


class CtrlStats:
    """Thread-safe controller telemetry: decision counters keyed
    ``(action, reason)``, per-model restart counters, a supervised-
    replica state gauge.  Rendered into the router's /metrics by
    ``Fleet._router_families`` while the controller is armed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._decisions: Dict[Tuple[str, str], int] = {}
        self._restarts: Dict[str, int] = {}
        self._supervised: Dict[Tuple[str, str], int] = {}

    def inc_decision(self, action: str, reason: str = "") -> None:
        with self._lock:
            k = (action, reason)
            self._decisions[k] = self._decisions.get(k, 0) + 1

    def inc_restart(self, model: str) -> None:
        with self._lock:
            self._restarts[model] = self._restarts.get(model, 0) + 1

    def set_supervised(self, model: str, state: str, n: int) -> None:
        """Gauge: supervised replicas of ``model`` in ``state``
        (``running`` / ``draining``)."""
        with self._lock:
            self._supervised[(model, state)] = int(n)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "decisions": {f"{a}:{r}" if r else a: n for (a, r), n
                              in sorted(self._decisions.items())},
                "restarts": dict(sorted(self._restarts.items())),
                # "supervised_gauge", not "supervised": the
                # controller's own snapshot() reserves "supervised"
                # for the rid → url map (which processes we own and
                # where) and merges this dict over its own keys.
                "supervised_gauge": {f"{m}:{s}": n for (m, s), n
                                     in sorted(self._supervised.items())},
            }

    def prom_families(self):
        """``dsod_ctrl_*`` families (counters only once non-empty —
        the RouterStats conditional-render idiom; the supervised gauge
        always while armed so a scrape can tell "armed, zero
        supervised" from "off")."""
        with self._lock:
            dec = sorted(self._decisions.items())
            res = sorted(self._restarts.items())
            sup = sorted(self._supervised.items())
        fams = []
        if dec:
            fams.append((
                "dsod_ctrl_decisions_total", "counter",
                ['dsod_ctrl_decisions_total{action="%s",reason="%s"} %d'
                 % (a, r, n) for (a, r), n in dec]))
        if res:
            fams.append((
                "dsod_ctrl_restarts_total", "counter",
                ['dsod_ctrl_restarts_total{model="%s"} %d' % (m, n)
                 for m, n in res]))
        fams.append((
            "dsod_ctrl_supervised_replicas", "gauge",
            ['dsod_ctrl_supervised_replicas{model="%s",state="%s"} %d'
             % (m, s, n) for (m, s), n in sup]))
        return fams


class SupervisedReplica:
    """One subprocess the supervisor owns: its process handle, bound
    port, and base URL.  ``backend`` is a test seam — a fake
    supervisor pre-wires the backend so fake-clock tests never touch
    HTTP."""

    __slots__ = ("model", "port", "url", "proc", "port_file", "backend")

    def __init__(self, model: str, port: int, url: str, proc,
                 port_file: str, backend=None):
        self.model = model
        self.port = port
        self.url = url
        self.proc = proc
        self.port_file = port_file
        self.backend = backend


def _free_port() -> int:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class ReplicaSupervisor:
    """Spawns and retires real replica subprocesses from an argv
    template with ``{port}``/``{port_file}`` placeholders — the
    tools/fleet_chaos.py harness pattern, generalized and owned by the
    control plane.

    Crash-loop discipline per model: consecutive spawn failures double
    a backoff (``backoff_s`` → ``backoff_max_s``) the controller must
    wait out (:meth:`can_spawn`) before the next attempt — a replica
    that dies on arrival must not be respawned in a hot loop.  The
    backoff clock is injectable, so the discipline is fake-clock
    provable; the spawn itself (process + port-file wait) uses real
    time because it IS real.
    """

    def __init__(self, spawn_cmd, *, deadline_s: float = 150.0,
                 backoff_s: float = 2.0, backoff_max_s: float = 60.0,
                 clock=time.monotonic):
        self.spawn_cmd = tuple(spawn_cmd)
        if self.spawn_cmd:
            joined = " ".join(self.spawn_cmd)
            if "{port}" not in joined or "{port_file}" not in joined:
                raise ValueError(
                    "spawn_cmd needs {port} and {port_file} "
                    f"placeholders, got {self.spawn_cmd!r}")
        self.deadline_s = float(deadline_s)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._fails: Dict[str, int] = {}
        self._next_ok: Dict[str, float] = {}
        self._procs: Dict[str, SupervisedReplica] = {}
        # Spawns that launched but have not bound yet.  stop() must see
        # them: a replica can take tens of seconds to warm and publish
        # its port, and a controller torn down inside that window would
        # otherwise orphan a process that is in no one's books.
        self._inflight: List[subprocess.Popen] = []
        self._closing = threading.Event()
        self._log = get_logger()

    def can_spawn(self, model: str) -> bool:
        """False while ``model`` is inside its crash-loop backoff."""
        if not self.spawn_cmd or self._closing.is_set():
            return False
        with self._lock:
            return self._clock() >= self._next_ok.get(model, 0.0)

    def backoff_remaining(self, model: str) -> float:
        with self._lock:
            return max(0.0, self._next_ok.get(model, 0.0) - self._clock())

    def _book_failure(self, model: str) -> None:
        with self._lock:
            fails = self._fails.get(model, 0) + 1
            self._fails[model] = fails
            delay = min(self.backoff_s * (2.0 ** (fails - 1)),
                        self.backoff_max_s)
            self._next_ok[model] = self._clock() + delay

    def spawn(self, model: str) -> Optional[SupervisedReplica]:
        """Spawn one replica subprocess and wait for it to publish its
        port.  Returns None (with the backoff booked) when the process
        dies or misses the deadline — the caller records the decision;
        this owns only the lifecycle."""
        port = _free_port()
        fd, port_file = tempfile.mkstemp(prefix=f"ctrl-{model}-",
                                         suffix=".port")
        os.close(fd)
        os.unlink(port_file)  # the replica's atomic publish creates it
        cmd = [a.replace("{port}", str(port))
                .replace("{port_file}", port_file)
               for a in self.spawn_cmd]
        try:
            proc = subprocess.Popen(
                cmd, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True)
        except OSError as e:
            self._log.error("supervisor: spawn failed for %s: %s",
                            model, e)
            self._book_failure(model)
            return None
        with self._lock:
            self._inflight.append(proc)
        deadline = time.monotonic() + self.deadline_s
        bound: Optional[int] = None
        while time.monotonic() < deadline and not self._closing.is_set():
            if proc.poll() is not None:
                break  # died before binding
            try:
                with open(port_file) as f:
                    bound = int(f.read().strip())
                break
            except (OSError, ValueError):
                time.sleep(0.1)
        with self._lock:
            if proc in self._inflight:
                self._inflight.remove(proc)
        if self._closing.is_set():
            # Shutdown mid-spawn: not the step's fault, no backoff —
            # just make sure nothing outlives the supervisor.
            self._kill(proc)
            return None
        if bound is None:
            self._log.error(
                "supervisor: replica for %s never published its port "
                "(rc=%s)", model, proc.poll())
            self._kill(proc)
            self._book_failure(model)
            return None
        with self._lock:
            self._fails[model] = 0
        rep = SupervisedReplica(model, bound,
                                f"http://127.0.0.1:{bound}", proc,
                                port_file)
        return rep

    def adopt(self, rid: str, rep: SupervisedReplica) -> None:
        """Track an admitted replica under its fleet replica id."""
        if self._closing.is_set():
            # stop() already swept _procs; a late adopt would escape
            # the sweep.  Kill instead of track.
            if rep.proc is not None:
                self._kill(rep.proc)
            return
        with self._lock:
            self._procs[rid] = rep

    def owns(self, rid: str) -> bool:
        with self._lock:
            return rid in self._procs

    def owned(self) -> Dict[str, SupervisedReplica]:
        with self._lock:
            return dict(self._procs)

    def poll(self) -> List[str]:
        """Reap exited supervised replicas; returns their rids (the
        controller detaches them from routing and heals)."""
        dead = []
        with self._lock:
            for rid, rep in list(self._procs.items()):
                if rep.proc is not None and rep.proc.poll() is not None:
                    dead.append(rid)
                    del self._procs[rid]
        return dead

    def retire(self, rid: str, grace_s: float = 10.0) -> None:
        """SIGTERM (the replica's own clean drain) → wait → SIGKILL."""
        with self._lock:
            rep = self._procs.pop(rid, None)
        if rep is None or rep.proc is None:
            return
        self._kill(rep.proc, grace_s=grace_s)

    def stop(self, grace_s: float = 10.0) -> None:
        self._closing.set()  # wakes in-flight spawn waits
        with self._lock:
            procs, self._procs = self._procs, {}
            inflight, self._inflight = self._inflight, []
        for rep in procs.values():
            if rep.proc is not None:
                self._kill(rep.proc, grace_s=grace_s)
        for proc in inflight:
            self._kill(proc, grace_s=grace_s)

    @staticmethod
    def _kill(proc, grace_s: float = 5.0) -> None:
        try:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        except OSError:
            pass


def default_spawn_cmd(config: str,
                      extra: Tuple[str, ...] = ()) -> Tuple[str, ...]:
    """The tools/serve.py single-engine argv for supervised replicas
    (what tools/fleet_chaos.py arms the controller with).  The model
    identity comes from ``config``; the fleet group a spawned replica
    joins is the controller's business, not the argv's."""
    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tools", "serve.py")
    return (sys.executable, tools, "--config", config, "--init-random",
            "--device", "cpu", "--port", "{port}",
            "--port-file", "{port_file}") + tuple(extra)


class FleetController:
    """The policy loop.  One background thread ticks every
    ``ctrl_interval_s``; every shared-state mutation happens under
    ``_lock`` (the tick thread, :meth:`notify_preemption` from a
    signal path, and the HTTP stats reader all touch it).

    Injectable seams, all for fake-clock provability
    (tests/test_controller.py): ``clock`` drives dwell/cooldown/
    backoff; ``signals_fn(name, group) -> (burn, stage_shares)``
    replaces the live SLO/stats scrape; ``supervisor`` replaces real
    subprocess spawning; ``guard`` replaces the real
    :class:`PreemptionGuard` (whose SIGTERM handler would collide with
    the serving CLI's own drain handler — the controller only ever
    POLLS ``guard.should_stop``, so any object with that attribute
    works)."""

    def __init__(self, fleet, cfg=None, *, supervisor=None,
                 clock=time.monotonic, guard=None, signals_fn=None):
        cfg = cfg if cfg is not None else fleet.cfg
        self.fleet = fleet
        self.cfg = cfg
        self._clock = clock
        self.stats = CtrlStats()
        self.supervisor = supervisor
        if self.supervisor is None and cfg.ctrl_spawn_cmd:
            self.supervisor = ReplicaSupervisor(
                cfg.ctrl_spawn_cmd,
                deadline_s=cfg.ctrl_spawn_deadline_s,
                backoff_s=cfg.ctrl_backoff_s,
                backoff_max_s=cfg.ctrl_backoff_max_s, clock=clock)
        self.guard = guard
        self._own_guard = None
        self._signals_fn = signals_fn or self._live_signals
        self._lock = threading.RLock()
        # Per-group policy state (all clock-stamped: dwell/cooldown are
        # provable with an injected clock).
        self._initial: Dict[str, int] = {
            name: len(g) for name, g in fleet.groups.items()}
        self._pending: Dict[str, Tuple[str, float]] = {}
        self._cooldown_until: Dict[str, float] = {}
        self._refused_until: Dict[str, float] = {}
        # rid → (group, retire-at, supervised?)
        self._draining: Dict[str, Tuple[str, float, bool]] = {}
        self._preempted = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = get_logger()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "FleetController":
        if self._thread is not None:
            return self
        if self.guard is None and self.cfg.ctrl_spot_guard:
            from ..utils.observability import PreemptionGuard

            self._own_guard = PreemptionGuard()
            self._own_guard.__enter__()
            self.guard = self._own_guard
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-controller", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self.supervisor is not None:
            # Supervised replicas die with their controller — they are
            # scale-out capacity, not config members, and an orphaned
            # subprocess outliving the fleet is a leak.
            self.supervisor.stop(grace_s=self.cfg.ctrl_drain_grace_s)
        if self._own_guard is not None:
            self._own_guard.__exit__(None, None, None)
            if self.guard is self._own_guard:
                self.guard = None
            self._own_guard = None

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.ctrl_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                self._log.exception(
                    "controller: tick failed; retrying next interval")

    # -- external notifications ---------------------------------------

    def notify_preemption(self, rid: Optional[str] = None) -> None:
        """A spot/maintenance notice landed: drain ``rid`` (or every
        supervised replica when None) out of routing now, retire after
        the grace — and refuse scale-out while the notice stands."""
        with self._lock:
            self._preempted = True
            if rid is not None:
                self._begin_drain(rid, reason="preemption")
                return
            if self.supervisor is not None:
                for srid in self.supervisor.owned():
                    self._begin_drain(srid, reason="preemption")

    # -- booking seam --------------------------------------------------

    def _record(self, action: str, reason: str = "", *,
                model: str = "", **attrs) -> None:
        """THE controller booking seam (tools/dsodlint.py
        ``BOOKING_SEAMS``): every decision increments its counter here
        and leaves a typed flight-recorder event."""
        self.stats.inc_decision(action, reason)
        if action == "restart":
            self.stats.inc_restart(model)
        rec = self.fleet.recorder
        if rec is not None:
            kw = dict(attrs)
            if reason:
                kw["reason"] = reason
            if model:
                kw["model"] = model
            rec.event("ctrl_" + action, **kw)

    # -- sensors -------------------------------------------------------

    def _live_signals(self, name: str, group
                      ) -> Tuple[float, Dict[str, float]]:
        """(worst SLO burn over the group's objectives, mean stage
        shares over reporting members).  Remote /stats scrapes are
        bounded by PROBE_TIMEOUT_S and skipped for known-down
        replicas — a tick can cost a couple of dials, never a hang."""
        burn = 0.0
        if self.fleet.slo is not None:
            for key, v in self.fleet.slo.signals().items():
                if key.startswith("slo_burn:"):
                    burn = max(burn, float(v))
        shares: Dict[str, List[float]] = {}
        for _rid, b in group.members:
            try:
                snap = b.stats_snapshot()
            except Exception:  # noqa: BLE001 — a corpse reports nothing
                continue
            ss = (snap.get("capacity") or {}).get("stage_share") or {}
            for k, v in ss.items():
                if isinstance(v, (int, float)):
                    shares.setdefault(k, []).append(float(v))
        mean = {k: sum(v) / len(v) for k, v in shares.items() if v}
        return burn, mean

    # -- the policy tick ----------------------------------------------

    def tick(self) -> None:
        """One policy evaluation over every replica set.  Order
        matters: reap exited supervised processes first (their group
        counts must reflect reality), finish due drains, then
        heal/scale each group."""
        now = self._clock()
        if (self.guard is not None
                and getattr(self.guard, "should_stop", False)):
            with self._lock:
                already = self._preempted
            if not already:
                self._record("preemption_notice")
                self.notify_preemption()
        if self.supervisor is not None:
            for rid in self.supervisor.poll():
                self._record("replica_exit", model=self._group_of(rid),
                             replica=rid)
                self._forget_drain(rid)
                self.fleet.detach_replica(rid)
        self._finish_due_drains(now)
        for name, group in list(self.fleet.groups.items()):
            try:
                self._tick_group(name, group, now)
            except Exception:  # noqa: BLE001 — one group's fault
                self._log.exception(
                    "controller: policy failed for group %s", name)
        self._publish_supervised_gauge()

    def _tick_group(self, name: str, group, now: float) -> None:
        cfg = self.cfg
        target = cfg.ctrl_target_replicas or self._initial.get(name, 1)
        with self._lock:
            draining = {rid for rid, (g, _t, _s)
                        in self._draining.items() if g == name}
            preempted = self._preempted
        members = [(rid, b) for rid, b in group.members
                   if rid not in draining]
        healthy = sum(1 for _rid, b in members if b.healthy())
        # Heal first, dwell-free: a dead replica is not a trend to be
        # smoothed, it is a hole in the fleet.
        if healthy < target:
            self._heal(name, now, healthy=healthy, target=target,
                       preempted=preempted)
            return
        burn, shares = self._signals_fn(name, group)
        queue_share = shares.get("queue", 0.0)
        if burn >= cfg.ctrl_scale_out_burn:
            if queue_share >= cfg.ctrl_queue_share:
                if len(members) >= cfg.ctrl_max_replicas:
                    self._refuse(name, now, "at_max_replicas",
                                 burn=round(burn, 3))
                elif preempted:
                    self._refuse(name, now, "preempted",
                                 burn=round(burn, 3))
                else:
                    self._act_after_dwell(
                        name, "scale_out", now,
                        lambda: self._heal(
                            name, now, healthy=healthy, target=target,
                            preempted=preempted, reason="scale_out",
                            burn=burn))
            else:
                # Burn without queue depth: the bottleneck is wherever
                # the largest non-queue share sits — another replica
                # on the same device would not absorb it.
                host = shares.get("host", 0.0)
                device = shares.get("device", 0.0)
                why = "host_bound" if host >= device else "device_bound"
                self._refuse(name, now, why, burn=round(burn, 3),
                             queue_share=round(queue_share, 3))
            return
        self._clear_pending(name, "scale_out")
        if burn <= cfg.ctrl_scale_in_burn and len(members) > target:
            self._act_after_dwell(
                name, "scale_in", now,
                lambda: self._scale_in(name, group, now, burn))
        else:
            self._clear_pending(name, "scale_in")

    # -- actions -------------------------------------------------------

    def _heal(self, name: str, now: float, *, healthy: int,
              target: int, preempted: bool, reason: str = "heal",
              burn: float = 0.0) -> None:
        if preempted:
            self._refuse(name, now, "preempted", model=name)
            return
        if self.supervisor is None or not self.supervisor.spawn_cmd:
            self._refuse(name, now, "no_spawn_cmd", model=name)
            return
        if not self.supervisor.can_spawn(name):
            self._refuse(
                name, now, "backoff", model=name,
                retry_in_s=round(
                    self.supervisor.backoff_remaining(name), 3))
            return
        self._record("spawn", reason, model=name, healthy=healthy,
                     target=target, burn=round(burn, 3))
        with self._lock:
            self._cooldown_until[name] = now + self.cfg.ctrl_cooldown_s
        rep = self.supervisor.spawn(name)
        if rep is None:
            self._record("spawn_failed", reason, model=name)
            return
        backend = rep.backend
        if backend is None:
            backend = self._admit_remote(name, rep)
        if backend is None:
            self._kill_spawned(rep)
            self._record("spawn_failed", "never_healthy", model=name)
            return
        rid = self.fleet.attach_replica(name, backend)
        self.supervisor.adopt(rid, rep)
        self._record("restart" if reason == "heal" else "scale_out",
                     reason, model=name, replica=rid, url=rep.url)

    @staticmethod
    def _kill_spawned(rep) -> None:
        try:
            if rep.proc is not None:
                ReplicaSupervisor._kill(rep.proc)
        except Exception:  # noqa: BLE001 — cleanup best-effort
            pass

    def _admit_remote(self, name: str, rep):
        """Health-gated admission: the spawned replica enters routing
        only once its /healthz actually answers (within the spawn
        deadline's budget) — the breaker then guards it like any other
        member."""
        from .fleet import RemoteBackend

        backend = RemoteBackend(
            name, rep.url, timeout_s=self.cfg.request_timeout_s,
            health_poll_s=self.cfg.health_poll_s)
        deadline = time.monotonic() + self.cfg.ctrl_spawn_deadline_s
        while time.monotonic() < deadline and not self._stop.is_set():
            if backend.probe_now():
                backend.start()
                return backend
            time.sleep(0.25)
        return None

    def _scale_in(self, name: str, group, now: float,
                  burn: float) -> None:
        victim = None
        if self.supervisor is not None:
            owned = self.supervisor.owned()
            # Newest supervised member drains first (LIFO): config-
            # declared replicas are never the controller's to retire.
            for rid, _b in reversed(group.members):
                if rid in owned:
                    victim = rid
                    break
        if victim is None:
            self._refuse(name, now, "no_supervised_member",
                         burn=round(burn, 3))
            return
        with self._lock:
            self._cooldown_until[name] = now + self.cfg.ctrl_cooldown_s
            self._begin_drain(victim, reason="scale_in")

    def _begin_drain(self, rid: str, *, reason: str) -> None:
        """Flip ``rid`` out of routing NOW; schedule the retire for
        after the grace (``_lock`` is reentrant — callers may already
        hold it)."""
        with self._lock:
            if rid in self._draining:
                return
            name = self._group_of(rid)
            group = self.fleet.groups.get(name)
            if group is None:
                return
            supervised = (self.supervisor is not None
                          and self.supervisor.owns(rid))
            group.set_draining(rid, True)
            self._draining[rid] = (
                name, self._clock() + self.cfg.ctrl_drain_grace_s,
                supervised)
        self._record("drain", reason, model=name, replica=rid,
                     grace_s=self.cfg.ctrl_drain_grace_s)

    def _finish_due_drains(self, now: float) -> None:
        with self._lock:
            due = [(rid, g, sup) for rid, (g, t, sup)
                   in self._draining.items() if now >= t]
            for rid, _g, _sup in due:
                del self._draining[rid]
        for rid, name, supervised in due:
            if supervised and self.supervisor is not None:
                self.supervisor.retire(
                    rid, grace_s=self.cfg.ctrl_drain_grace_s)
            self.fleet.detach_replica(rid)
            self._record("retire", model=name, replica=rid,
                         supervised=supervised)

    def _forget_drain(self, rid: str) -> None:
        with self._lock:
            entry = self._draining.pop(rid, None)
        if entry is not None:
            group = self.fleet.groups.get(entry[0])
            if group is not None:
                group.set_draining(rid, False)

    # -- hysteresis helpers -------------------------------------------

    def _act_after_dwell(self, name: str, action: str, now: float,
                         act) -> None:
        with self._lock:
            if now < self._cooldown_until.get(name, 0.0):
                return
            pending = self._pending.get(name)
            if pending is None or pending[0] != action:
                self._pending[name] = (action, now)
                return
            if now - pending[1] < self.cfg.ctrl_dwell_s:
                return
            del self._pending[name]
        act()

    def _clear_pending(self, name: str, action: str) -> None:
        with self._lock:
            if self._pending.get(name, ("", 0.0))[0] == action:
                del self._pending[name]

    def _refuse(self, name: str, now: float, why: str,
                **attrs) -> None:
        """Record a refusal (refusals are decisions too — 'we saw the
        burn and did NOT scale, because X' is the half of the story
        operators page on) — debounced to once per cooldown window so
        a sustained bottleneck is one event, not one per tick."""
        with self._lock:
            if now < self._refused_until.get(name, 0.0):
                return
            self._refused_until[name] = now + self.cfg.ctrl_cooldown_s
        attrs.setdefault("model", name)
        self._record("refuse_scale_out", why, **attrs)

    # -- misc ----------------------------------------------------------

    def _group_of(self, rid: str) -> str:
        for name, g in self.fleet.groups.items():
            if any(r == rid for r, _b in g.members):
                return name
        return rid.split("#", 1)[0]

    def _publish_supervised_gauge(self) -> None:
        counts: Dict[Tuple[str, str], int] = {}
        if self.supervisor is not None:
            with self._lock:
                draining = set(self._draining)
            for rid, rep in self.supervisor.owned().items():
                state = "draining" if rid in draining else "running"
                counts[(rep.model, state)] = \
                    counts.get((rep.model, state), 0) + 1
        for name in self.fleet.groups:
            for state in ("running", "draining"):
                self.stats.set_supervised(
                    name, state, counts.get((name, state), 0))

    def snapshot(self) -> Dict:
        with self._lock:
            out = {
                "preempted": self._preempted,
                "draining": sorted(self._draining),
                "pending": {n: a for n, (a, _t)
                            in self._pending.items()},
                "targets": {
                    n: (self.cfg.ctrl_target_replicas
                        or self._initial.get(n, 1))
                    for n in self.fleet.groups},
            }
        if self.supervisor is not None:
            out["supervised"] = {
                rid: rep.url for rid, rep
                in sorted(self.supervisor.owned().items())}
        out.update(self.stats.snapshot())
        return out
