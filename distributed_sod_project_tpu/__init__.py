"""distributed_sod_project_tpu — a TPU-native salient-object-detection framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of
``lartpang/Distributed-SOD-Project`` (see ``SURVEY.md`` — the reference
mount was unreadable, so parity targets come from SURVEY.md §2's
component inventory and ``BASELINE.json``):

- Model zoo: MINet (VGG16/ResNet50), HDFNet (RGB-D two-stream), U²-Net,
  BASNet, Swin-T SOD  (``models/``)
- Losses: BCE + soft-IoU + SSIM + CEL with multi-level deep supervision
  (``losses/``, fused Pallas reductions in ``pallas/``)
- Data: DUTS / NJU2K / NLPR loaders with per-host sharding and a
  synthetic fallback — three batch-identical backends (C++/PIL host,
  tf.data, Grain) (``data/``), C++ decode/encode runtime (``native/``)
- Parallelism: SPMD data-parallel training over a ``jax.sharding.Mesh``
  via ``shard_map`` (cross-replica BatchNorm + gradient psum riding
  ICI), GSPMD tensor parallelism + ZeRO-1 weight-update sharding, and
  ring-attention sequence parallelism for the transformer path
  (``parallel/``)
- Train/eval engines, poly-LR schedules, orbax checkpointing, SOD
  metrics (MAE, max-Fβ, S-measure, E-measure)  (``train/``, ``eval/``,
  ``metrics/``)

The package directory uses underscores (``distributed_sod_project_tpu``)
because the upstream-style name ``distributed-sod-project_tpu`` is not a
valid Python identifier.
"""

__version__ = "0.1.0"
