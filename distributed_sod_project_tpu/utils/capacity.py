"""Live per-compiled-program capacity ledger
(docs/OBSERVABILITY.md "Capacity & SLO").

``tools/roofline.py`` prices the flagship step OFFLINE (closed-form
FLOPs/bytes, ``--xla-check`` against XLA's cost model).  This module
makes those numbers a LIVE surface: every AOT-compiled executable the
serve engine caches (and, opted in, the train step program) is asked
for its own ``cost_analysis()`` / ``memory_analysis()`` at warmup, and
the measured device time the stacks already track (the engine's
per-(res, batch, arm) EWMA; the trainer's StepTimer) turns static cost
into live utilization:

- ``MFU = flops / measured_s / peak_flops`` per program — the
  model-FLOPs-utilization dial, continuously, per compiled program;
- ``roofline utilization = max(flop util, bandwidth util)`` — how close
  the program runs to ITS binding roofline (the tools/roofline.py
  ``t >= max(F/peak, B/bw)`` bound, inverted);
- HBM: each program's analyzed peak working set plus the device's live
  ``memory_stats`` headroom (``bytes_limit − bytes_in_use``);
- a stage-share attribution gauge (device / queue / host fractions of
  the measured end-to-end, from the PR-9 stage splits) — the
  scale-out-vs-futile signal ROADMAP item 2 names: deep queues with a
  high device share mean the device is the bottleneck (scale out);
  deep queues with a low device share mean the host is (scaling out is
  futile).

Off by default (``serve.capacity_ledger`` / ``capacity_ledger``):
nothing records, nothing renders, /metrics is byte-identical.  The
peak numbers default to the same v5e constants as tools/roofline.py —
on other hardware override at construction (MFU is then reported
against the configured peak, like every MFU number in this repo).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .logging import get_logger

# v5e per-chip peaks — the SAME constants tools/roofline.py predicts
# against, so live MFU and the offline roofline share a denominator.
PEAK_FLOPS = 197e12  # dense bf16 MACs*2
HBM_BW = 819e9       # bytes/s
ICI_BW = 2e11        # bytes/s — v5e 1,600 Gbps aggregate ICI per chip
DCN_BW = 12.5e9      # bytes/s — ~100 Gbps per-host DCN NIC (the
                     # inter-host hop hierarchical collectives price)


def ring_wire_bytes(payload_bytes: float, axis_size: int) -> float:
    """Bytes each chip moves for a ring allreduce of ``payload_bytes``:
    ``2(n-1)/n × payload`` (reduce-scatter + all-gather halves).  For
    n=1 this is 0 — a single-replica 'collective' is free."""
    n = max(int(axis_size), 1)
    return 2.0 * (n - 1) / n * float(payload_bytes)


def collective_wire_bytes(c: Dict) -> float:
    """Per-chip wire bytes for ONE comm_plan collective dict: a full
    allreduce (``psum``/``all_reduce``) moves ``2(n-1)/n × payload``,
    a lone reduce-scatter or all-gather leg half that — the split the
    hierarchical ICI×DCN plan needs so each leg prices its own link."""
    n = max(int(c.get("axis_size", 1)), 1)
    payload = float(c.get("bytes", 0))
    kind = c.get("kind", "psum")
    if kind in ("reduce_scatter", "all_gather"):
        return (n - 1) / n * payload
    return 2.0 * (n - 1) / n * payload


def collective_link_bw(c: Dict) -> float:
    """The link bandwidth a collective's wire bytes traverse:
    ``level='dcn'`` (the inter-host hop of ``mesh.data_hosts>1``
    plans) prices against ``DCN_BW``, everything else against
    ``ICI_BW``.  Plans from before the level field default to ici."""
    return DCN_BW if c.get("level", "ici") == "dcn" else ICI_BW


def program_cost(compiled) -> Dict[str, float]:
    """``{flops, bytes, peak_hbm_bytes}`` from one compiled executable's
    own analyses.  Backends that omit a key (or the whole API) report
    0 — the ledger renders what XLA actually said, never a guess."""
    flops = bytes_ = 0.0
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            flops = float(cost.get("flops", 0.0) or 0.0)
            bytes_ = float(cost.get("bytes accessed", 0.0) or 0.0)
    except Exception:  # noqa: BLE001 — analysis is best-effort telemetry
        pass
    peak = 0.0
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            peak = float(
                getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                + getattr(mem, "temp_size_in_bytes", 0))
    except Exception:  # noqa: BLE001
        pass
    return {"flops": flops, "bytes": bytes_, "peak_hbm_bytes": peak}


def device_hbm_gauges():
    """Per-device ``(label, in_use, headroom)`` from jax
    ``memory_stats()``; one zero row when the platform reports none
    (CPU) so the family set is platform-stable."""
    rows = []
    try:
        import jax

        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — platform without the API
                ms = {}
            in_use = int(ms.get("bytes_in_use", 0))
            limit = int(ms.get("bytes_limit", 0))
            rows.append((str(d.id), in_use,
                         max(limit - in_use, 0) if limit else 0))
    except Exception:  # noqa: BLE001 — no backend at all
        rows = []
    return rows or [("0", 0, 0)]


class CapacityLedger:
    """Cost/memory analysis per compiled program + measured-time EWMA →
    live utilization gauges.  Thread-safe; renders through the standard
    ``prom_families(labels)`` provider contract."""

    def __init__(self, *, peak_flops: float = PEAK_FLOPS,
                 hbm_bw: float = HBM_BW,
                 share_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 device_memory: bool = True):
        if peak_flops <= 0 or hbm_bw <= 0:
            raise ValueError("peak_flops/hbm_bw must be > 0")
        self.peak_flops = float(peak_flops)
        self.hbm_bw = float(hbm_bw)
        self._share_fn = share_fn
        self._device_memory = device_memory
        self._lock = threading.Lock()
        # key → {flops, bytes, peak_hbm_bytes, ewma_ms (None until
        # observed)}
        self._programs: Dict[str, Dict[str, float]] = {}
        # key → comm plan (parallel/engine.comm_plan dict: collectives
        # with payload bytes + axis size, overlap estimate, ZeRO HBM
        # saving) — static shape accounting, no tracing.
        self._comm: Dict[str, Dict] = {}
        self._log = get_logger()

    # -- ingest --------------------------------------------------------

    def record(self, key: str, compiled) -> Dict[str, float]:
        """Record one AOT-compiled executable's static cost under
        ``key`` (idempotent: a re-warm keeps the measured EWMA)."""
        cost = program_cost(compiled)
        with self._lock:
            prev = self._programs.get(key)
            if prev is not None:
                cost["ewma_ms"] = prev.get("ewma_ms")
            else:
                cost["ewma_ms"] = None
            self._programs[key] = cost
        return cost

    def record_jit(self, key: str, fn, *args) -> bool:
        """Train-side convenience: AOT lower+compile ``fn(*args)`` just
        for its analyses (one extra compile, paid only with the ledger
        opted in) and record it.  False (logged) when the callable has
        no AOT path."""
        lower = getattr(fn, "lower", None)
        if lower is None:
            self._log.warning(
                "capacity: %s has no .lower() — ledger stays empty for "
                "this program", key)
            return False
        try:
            self.record(key, lower(*args).compile())
            return True
        except Exception:  # noqa: BLE001 — telemetry must not kill a run
            self._log.exception("capacity: cost analysis failed for %s",
                                key)
            return False

    def record_comm(self, key: str, plan: Dict) -> None:
        """Record one program's communication plan under ``key`` —
        ``parallel/engine.comm_plan``'s dict (per-collective payload
        bytes + axis size, bucket count, structural overlap fraction,
        ZeRO HBM saving).  Rendered as the ``dsod_capacity_comm_*``
        families (DCN-level legs as ``dsod_capacity_comm_dcn_*``);
        wire bytes and estimated milliseconds are derived here against
        ``ICI_BW``/``DCN_BW`` so the constants live in ONE place."""
        if not isinstance(plan, dict) or "collectives" not in plan:
            raise ValueError("record_comm wants a comm_plan dict "
                             "(missing 'collectives')")
        with self._lock:
            self._comm[key] = plan

    def observe(self, key: str, device_ms: float, alpha: float = 0.2
                ) -> None:
        """Fold one measured device time (ms) into ``key``'s EWMA —
        the same 0.8/0.2 blend as the engine's SLO-expiry estimate."""
        with self._lock:
            p = self._programs.get(key)
            if p is None:
                return
            old = p.get("ewma_ms")
            p["ewma_ms"] = (float(device_ms) if old is None
                            else (1.0 - alpha) * old
                            + alpha * float(device_ms))

    # -- derived -------------------------------------------------------

    @staticmethod
    def _util(p: Dict[str, float], peak_flops: float, hbm_bw: float
              ) -> Dict[str, float]:
        ms = p.get("ewma_ms")
        if not ms:
            return {"mfu": 0.0, "roofline": 0.0}
        s = ms / 1000.0
        mfu = p["flops"] / s / peak_flops if p["flops"] else 0.0
        bwu = p["bytes"] / s / hbm_bw if p["bytes"] else 0.0
        return {"mfu": mfu, "roofline": max(mfu, bwu)}

    def mfu(self, key: str) -> float:
        with self._lock:
            p = self._programs.get(key)
            return self._util(p, self.peak_flops, self.hbm_bw)["mfu"] \
                if p else 0.0

    def snapshot(self) -> Dict:
        """The /stats capacity block."""
        with self._lock:
            programs = {k: dict(p) for k, p in
                        sorted(self._programs.items())}
        out = {}
        for k, p in programs.items():
            u = self._util(p, self.peak_flops, self.hbm_bw)
            out[k] = {
                "flops": p["flops"],
                "bytes": p["bytes"],
                "peak_hbm_bytes": p["peak_hbm_bytes"],
                "device_ms_ewma": (round(p["ewma_ms"], 3)
                                   if p["ewma_ms"] else None),
                "mfu": round(u["mfu"], 6),
                "roofline_util": round(u["roofline"], 6),
            }
        snap = {"programs": out,
                "peak_flops": self.peak_flops, "hbm_bw": self.hbm_bw}
        with self._lock:
            comm = {k: dict(p) for k, p in sorted(self._comm.items())}
        if comm:
            for plan in comm.values():
                for c in plan.get("collectives", ()):
                    wire = collective_wire_bytes(c)
                    c["wire_bytes"] = int(wire)
                    c["est_ms"] = round(
                        wire / collective_link_bw(c) * 1e3, 6)
            snap["comm"] = comm
            snap["ici_bw"] = ICI_BW
            snap["dcn_bw"] = DCN_BW
        if self._share_fn is not None:
            try:
                snap["stage_share"] = {
                    k: round(v, 6)
                    for k, v in (self._share_fn() or {}).items()}
            except Exception:  # noqa: BLE001 — telemetry must not throw
                pass
        return snap

    # -- exposition ----------------------------------------------------

    def prom_families(self, labels: str = ""):
        """The ``dsod_capacity_*`` families: per-program static cost +
        live utilization (one ``program=`` sample each), the stage-share
        attribution, and per-device HBM headroom.  Core families render
        unconditionally while the ledger exists (inventory-stable); the
        ledger itself only exists when the knob is on."""
        with self._lock:
            rows = [(k, dict(p)) for k, p in
                    sorted(self._programs.items())]
        pre = f"{labels}," if labels else ""

        def plbl(k):
            return f'{pre}program="{k}"'

        flops, bts, peak, ms, mfu, roof = [], [], [], [], [], []
        for k, p in rows:
            u = self._util(p, self.peak_flops, self.hbm_bw)
            flops.append('dsod_capacity_program_flops{%s} %g'
                         % (plbl(k), p["flops"]))
            bts.append('dsod_capacity_program_hbm_bytes{%s} %g'
                       % (plbl(k), p["bytes"]))
            peak.append('dsod_capacity_program_peak_hbm_bytes{%s} %g'
                        % (plbl(k), p["peak_hbm_bytes"]))
            ms.append('dsod_capacity_device_ms{%s} %g'
                      % (plbl(k), p["ewma_ms"] or 0.0))
            mfu.append('dsod_capacity_mfu{%s} %g' % (plbl(k), u["mfu"]))
            roof.append('dsod_capacity_roofline_util{%s} %g'
                        % (plbl(k), u["roofline"]))
        fams = []
        for name, samples in (
                ("dsod_capacity_program_flops", flops),
                ("dsod_capacity_program_hbm_bytes", bts),
                ("dsod_capacity_program_peak_hbm_bytes", peak),
                ("dsod_capacity_device_ms", ms),
                ("dsod_capacity_mfu", mfu),
                ("dsod_capacity_roofline_util", roof)):
            if samples:
                fams.append((name, "gauge", samples))
        # Comm ledger (ROADMAP item 4): per-collective payload/wire
        # bytes and the ICI-bandwidth time estimate, plus per-program
        # overlap + ZeRO-saving gauges.  Rendered only once a plan is
        # recorded — like the per-program families, `if samples`.
        with self._lock:
            comm_rows = [(k, p) for k, p in sorted(self._comm.items())]
        cb, cw, cms, cov, czs = [], [], [], [], []
        db, dw, dms = [], [], []
        for k, plan in comm_rows:
            for c in plan.get("collectives", ()):
                cl = (f'{pre}program="{k}",collective="{c["name"]}",'
                      f'axis="{c.get("axis", "")}"')
                payload = float(c.get("bytes", 0))
                wire = collective_wire_bytes(c)
                est = wire / collective_link_bw(c) * 1e3
                if c.get("level", "ici") == "dcn":
                    # The slow hop gets its own families so a dashboard
                    # can alarm on DCN pressure without parsing labels.
                    db.append('dsod_capacity_comm_dcn_bytes{%s} %g'
                              % (cl, payload))
                    dw.append('dsod_capacity_comm_dcn_wire_bytes{%s} %g'
                              % (cl, wire))
                    dms.append('dsod_capacity_comm_dcn_est_ms{%s} %g'
                               % (cl, est))
                    continue
                cb.append('dsod_capacity_comm_bytes{%s} %g'
                          % (cl, payload))
                cw.append('dsod_capacity_comm_wire_bytes{%s} %g'
                          % (cl, wire))
                cms.append('dsod_capacity_comm_est_ms{%s} %g'
                           % (cl, est))
            cov.append('dsod_capacity_comm_overlap_frac{%s} %g'
                       % (plbl(k), plan.get("overlap_frac", 0.0)))
            czs.append('dsod_capacity_comm_zero_hbm_saved_bytes{%s} %g'
                       % (plbl(k), plan.get("zero_hbm_saved_bytes", 0)))
        for name, samples in (
                ("dsod_capacity_comm_bytes", cb),
                ("dsod_capacity_comm_wire_bytes", cw),
                ("dsod_capacity_comm_est_ms", cms),
                ("dsod_capacity_comm_dcn_bytes", db),
                ("dsod_capacity_comm_dcn_wire_bytes", dw),
                ("dsod_capacity_comm_dcn_est_ms", dms),
                ("dsod_capacity_comm_overlap_frac", cov),
                ("dsod_capacity_comm_zero_hbm_saved_bytes", czs)):
            if samples:
                fams.append((name, "gauge", samples))
        # Stage-share attribution (device/queue/host fractions of the
        # measured e2e): rendered whenever a share source exists, 0
        # before traffic.
        if self._share_fn is not None:
            try:
                shares = self._share_fn() or {}
            except Exception:  # noqa: BLE001
                shares = {}
            fams.append(("dsod_capacity_stage_share", "gauge", [
                'dsod_capacity_stage_share{%sstage="%s"} %g'
                % (pre, s, shares.get(s, 0.0))
                for s in ("device", "queue", "host")]))
        if self._device_memory:
            in_use, headroom = [], []
            for dev, used, head in device_hbm_gauges():
                dl = f'{pre}device="{dev}"'
                in_use.append('dsod_capacity_hbm_bytes_in_use{%s} %d'
                              % (dl, used))
                headroom.append('dsod_capacity_hbm_headroom_bytes{%s} %d'
                                % (dl, head))
            fams.append(("dsod_capacity_hbm_bytes_in_use", "gauge",
                         in_use))
            fams.append(("dsod_capacity_hbm_headroom_bytes", "gauge",
                         headroom))
        return fams
