"""End-to-end tracing: the span layer under docs/OBSERVABILITY.md.

Dependency-free on purpose (stdlib only, no jax/numpy): the load
generator imports this next to a TPU-bound server, and the trainer
sidecar renders it while a fit() is mid-dispatch.  One schema serves
BOTH stacks — a serving request's queue/coalesce/device/fetch/
resize-back stages and a training chunk's data-wait/dispatch/flush/
ckpt/eval stages are the same shape:

    span = {trace, span, parent, name, t0, dur_ms, attrs}

- **Trace ids propagate, span ids don't.**  A trace id is minted once
  at the outermost door (the fleet router's ``X-Request-ID``, a chunk
  boundary in the train loop) and rides headers across processes;
  every attempt, retry, and hedge of one request shares it.  Span ids
  are local and only exist to parent children.
- **Sampling is deterministic in the trace id** (:func:`trace_sampled`)
  so a router and its remote replicas agree on which requests to trace
  without coordination, and a retried request is traced either
  everywhere or nowhere.
- **Bounded by construction.**  Completed traces live in a ring of
  ``capacity`` entries; the worst-``worst_n`` traces per exemplar key
  (e.g. ``(model, res_bucket)``) are pinned so a latency outlier
  survives the ring even under full-rate traffic.  An abandoned trace
  (root span never ended) is evicted like any other entry.
- **Export is JSON/JSONL.**  ``snapshot()`` backs the ``/debug/traces``
  endpoints; ``to_jsonl()`` writes one trace per line for offline
  timeline tooling.

The ``X-Timing`` response header (:func:`format_timing` /
:func:`parse_timing`) is the zero-overhead sibling: a per-request
stage summary computed from numbers the engine already tracks, echoed
on EVERY 200 regardless of sampling, so a client (tools/loadgen.py
``--slowest``) can always break its tail down by stage and quote the
trace id when the request was sampled.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Span", "Tracer", "format_timing", "mint_trace_id", "parse_timing",
    "trace_sampled",
]

_SAMPLE_MOD = 1 << 24
# Per-trace span bound: the ring caps the number of TRACES, this caps
# each trace's span list — a client free to reuse one sampled
# X-Request-ID forever must not be free to grow one ring entry forever.
MAX_SPANS_PER_TRACE = 256


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (also the ``X-Request-ID`` value)."""
    return os.urandom(8).hex()


def trace_sampled(trace_id: str, sample: float) -> bool:
    """Deterministic per-trace sampling verdict.

    Hash-based, not random: the same (trace_id, rate) pair answers the
    same everywhere, so a router at 1% and its replicas at 1% trace the
    SAME 1% of requests end-to-end, and all attempts of one request
    (retries, hedges) are all-or-nothing.
    """
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    h = zlib.crc32(trace_id.encode("utf-8", "replace")) & (_SAMPLE_MOD - 1)
    return h < int(sample * _SAMPLE_MOD)


class Span:
    """A live span handle.  ``end()`` records it into the tracer; a
    span that is never ended simply never appears (its trace can still
    complete — gaps are the caller's bug, visible in the export)."""

    __slots__ = ("_tracer", "trace_id", "span_id", "parent_id", "name",
                 "t0", "_root", "attrs", "_done")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, t0: float,
                 root: bool, attrs: Optional[Dict]):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self._root = root
        self.attrs = dict(attrs) if attrs else {}
        self._done = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, t1: Optional[float] = None, key=None, **attrs) -> None:
        """Record the span.  ``key`` (root spans only) names the
        worst-N exemplar bucket this trace competes in, e.g.
        ``(model, res_bucket)``.  Idempotent: a double end is a no-op
        (failure paths may race the happy path's end)."""
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self._tracer._record(self.trace_id, self.span_id, self.parent_id,
                             self.name, self.t0,
                             t1 if t1 is not None else self._tracer._clock(),
                             self.attrs, root=self._root, key=key)


class Tracer:
    """Thread-safe span store: sampling gate, bounded ring of completed
    traces, pinned worst-N exemplars per key.

    ``begin()`` returns None when the trace is not sampled — callers
    guard every further touch on that None, so an unsampled request
    costs exactly one crc32 and one compare.
    """

    def __init__(self, sample: float = 0.0, capacity: int = 256,
                 worst_n: int = 4, clock=time.monotonic):
        if not 0.0 <= float(sample) <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if worst_n < 0:
            raise ValueError(f"worst_n must be >= 0, got {worst_n}")
        self.sample = float(sample)
        self.capacity = int(capacity)
        self.worst_n = int(worst_n)
        self._clock = clock
        # monotonic → wall anchor, taken once: exported t0s are epoch
        # seconds so cross-process timelines line up approximately.
        self._wall0 = time.time() - clock()
        self._lock = threading.Lock()
        # trace_id → {"spans": [...], "done", "dur_ms", "key", "pinned"}
        self._traces: "OrderedDict[str, Dict]" = OrderedDict()
        # exemplar key → [(dur_ms, trace_id)] sorted ascending, len<=N
        self._worst: Dict[str, List[Tuple[float, str]]] = {}
        self._completed = 0
        self._dropped = 0
        self._span_drops = 0

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0

    def sampled(self, trace_id: str) -> bool:
        return trace_sampled(trace_id, self.sample)

    # -- recording -----------------------------------------------------

    def begin(self, name: str, trace_id: Optional[str], *,
              parent_id: Optional[str] = None, t0: Optional[float] = None,
              root: bool = False, attrs: Optional[Dict] = None
              ) -> Optional[Span]:
        """Open a span in ``trace_id``, or None when the trace is not
        sampled (or ``trace_id`` is None).  ``root=True`` marks the
        span whose ``end()`` completes the trace IN THIS PROCESS — the
        engine's request span is a root even when it carries a
        cross-process parent (the router's attempt span id)."""
        if trace_id is None or not self.sampled(trace_id):
            return None
        return Span(self, trace_id, os.urandom(4).hex(), parent_id, name,
                    t0 if t0 is not None else self._clock(), root, attrs)

    def record(self, trace_id: Optional[str], name: str, t0: float,
               t1: float, *, parent_id: Optional[str] = None,
               attrs: Optional[Dict] = None) -> Optional[str]:
        """Record a retroactive (already-finished) span from two
        timestamps; returns its span id.  Sampling-gated like
        :meth:`begin`."""
        if trace_id is None or not self.sampled(trace_id):
            return None
        sid = os.urandom(4).hex()
        self._record(trace_id, sid, parent_id, name, t0, t1,
                     dict(attrs) if attrs else {}, root=False, key=None)
        return sid

    def _record(self, trace_id, span_id, parent_id, name, t0, t1, attrs,
                *, root: bool, key) -> None:
        span = {
            "span": span_id,
            "parent": parent_id,
            "name": name,
            "t0": t0,
            "dur_ms": round(max(t1 - t0, 0.0) * 1000.0, 3),
        }
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                tr = self._traces[trace_id] = {
                    "spans": [], "done": False, "dur_ms": None,
                    "key": None, "pinned": False}
            if (len(tr["spans"]) >= MAX_SPANS_PER_TRACE
                    and (not root or tr["done"])):
                # Past the cap only a COMPLETING root still lands (so
                # the trace closes); everything else — including repeat
                # roots on a done trace — is dropped, not stored.
                self._span_drops += 1
                return
            tr["spans"].append(span)
            if root and not tr["done"]:
                tr["done"] = True
                tr["dur_ms"] = span["dur_ms"]
                self._completed += 1
                if key is not None and self.worst_n > 0:
                    tr["key"] = self._key_str(key)
                    self._consider_worst(tr["key"], span["dur_ms"],
                                         trace_id)
            self._evict_locked()

    @staticmethod
    def _key_str(key) -> str:
        if isinstance(key, (tuple, list)):
            return ",".join(str(k) for k in key)
        return str(key)

    def _consider_worst(self, key: str, dur_ms: float, trace_id: str
                        ) -> None:
        lst = self._worst.setdefault(key, [])
        lst.append((dur_ms, trace_id))
        lst.sort(key=lambda e: e[0])
        tr = self._traces.get(trace_id)
        if tr is not None:
            tr["pinned"] = True
        while len(lst) > self.worst_n:
            _d, evicted = lst.pop(0)
            ev = self._traces.get(evicted)
            if ev is not None and not any(
                    tid == evicted for ws in self._worst.values()
                    for _dd, tid in ws):
                ev["pinned"] = False

    def _evict_locked(self) -> None:
        while len(self._traces) > self.capacity:
            victim = None
            for tid, tr in self._traces.items():
                if not tr["pinned"]:
                    victim = tid
                    break
            if victim is None:  # everything pinned: drop the oldest
                victim = next(iter(self._traces))
                for ws in self._worst.values():
                    ws[:] = [e for e in ws if e[1] != victim]
            self._traces.pop(victim, None)
            self._dropped += 1

    # -- export --------------------------------------------------------

    def _trace_dict(self, tid: str, tr: Dict) -> Dict:
        spans = sorted(tr["spans"], key=lambda s: s["t0"])
        tmin = spans[0]["t0"] if spans else 0.0
        out_spans = []
        for s in spans:
            d = {k: v for k, v in s.items() if k != "t0"}
            d["rel_ms"] = round((s["t0"] - tmin) * 1000.0, 3)
            d["t0_unix"] = round(s["t0"] + self._wall0, 6)
            out_spans.append(d)
        return {"trace_id": tid, "done": tr["done"], "dur_ms": tr["dur_ms"],
                "key": tr["key"], "spans": out_spans}

    def snapshot(self, n: int = 50) -> Dict:
        """The ``/debug/traces`` payload: the newest ``n`` completed
        traces plus the pinned worst-N exemplars per key."""
        with self._lock:
            done = [(tid, tr) for tid, tr in self._traces.items()
                    if tr["done"]]
            # done[-n:] at n<=0 would be the WHOLE list — a client
            # n=0 must mean none, not everything.
            recent = [self._trace_dict(tid, tr)
                      for tid, tr in (done[-n:] if n > 0 else [])]
            worst = {key: [self._trace_dict(tid, self._traces[tid])
                           for _d, tid in reversed(lst)
                           if tid in self._traces]
                     for key, lst in sorted(self._worst.items())}
            stats = {"sample": self.sample, "capacity": self.capacity,
                     "completed_total": self._completed,
                     "dropped_total": self._dropped,
                     "span_drops_total": self._span_drops,
                     "held": len(self._traces)}
        return {**stats, "traces": recent, "worst": worst}

    def to_jsonl(self, n: Optional[int] = None) -> str:
        """Completed traces as JSONL, one trace per line (offline
        timeline tooling; newest last)."""
        with self._lock:
            done = [(tid, tr) for tid, tr in self._traces.items()
                    if tr["done"]]
            if n is not None:
                done = done[-n:] if n > 0 else []
            lines = [json.dumps(self._trace_dict(tid, tr))
                     for tid, tr in done]
        return "\n".join(lines) + ("\n" if lines else "")

    def get_trace(self, trace_id: str) -> Optional[Dict]:
        with self._lock:
            tr = self._traces.get(trace_id)
            return self._trace_dict(trace_id, tr) if tr else None

    @property
    def completed_total(self) -> int:
        with self._lock:
            return self._completed


# -- X-Timing header ---------------------------------------------------
#
# Format: ``trace=<id>;queue=1.234;device=5.678;e2e=7.001`` — the
# stage values are milliseconds with 3 decimals, the exact numbers the
# engine's latency histograms observed for this request, so a client
# can reconcile its own e2e against the server's split without a
# /debug/traces round trip.  ``trace=-`` means the request was not
# sampled (stages still ride).

def format_timing(trace_id: Optional[str], stages: Dict[str, float]) -> str:
    parts = [f"trace={trace_id if trace_id else '-'}"]
    parts += [f"{k}={float(v):.3f}" for k, v in stages.items()]
    return ";".join(parts)


def parse_timing(header: Optional[str]
                 ) -> Tuple[Optional[str], Dict[str, float]]:
    """``X-Timing`` value → ``(trace_id | None, {stage: ms})``.
    Tolerant: unparseable fragments are skipped, never raised on."""
    if not header:
        return None, {}
    trace_id = None
    stages: Dict[str, float] = {}
    for part in header.split(";"):
        k, sep, v = part.strip().partition("=")
        if not sep:
            continue
        if k == "trace":
            trace_id = v if v and v != "-" else None
            continue
        try:
            stages[k] = float(v)
        except ValueError:
            continue
    return trace_id, stages
