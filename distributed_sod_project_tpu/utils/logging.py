"""Rank-0-gated logging (SURVEY.md §2 C12).

The reference gates its console/file logger and TensorBoard writer on
rank 0; here the gate is ``jax.process_index() == 0``.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional


def is_primary_process() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


class _LiveStderrHandler(logging.StreamHandler):
    """StreamHandler that resolves ``sys.stderr`` at EMIT time.

    A handler constructed with the construction-time ``sys.stderr``
    object keeps writing to it forever — under pytest that object is
    one test's capture stream, closed when that test ends, and any
    later emit (an engine warming inside a different test, a
    background thread) raises into ``--- Logging error ---`` noise on
    whatever stream is current.  Resolving the CURRENT stderr per
    record follows redirections instead of outliving them."""

    def __init__(self):
        logging.StreamHandler.__init__(self)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):  # StreamHandler.__init__/setStream write it
        pass


def get_logger(name: str = "dsod", log_file: Optional[str] = None) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(logging.INFO)
    logger.propagate = False
    if not is_primary_process():
        if not logger.handlers:
            logger.addHandler(logging.NullHandler())
        return logger
    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s] %(message)s", "%H:%M:%S"
    )
    if not any(isinstance(h, logging.StreamHandler) and not isinstance(h, logging.FileHandler)
               for h in logger.handlers):
        sh = _LiveStderrHandler()
        sh.setFormatter(fmt)
        logger.addHandler(sh)
    if log_file:
        # Attach the file handler even when the logger already exists —
        # later calls may be the first to name a log file.
        existing = {
            getattr(h, "baseFilename", None)
            for h in logger.handlers
            if isinstance(h, logging.FileHandler)
        }
        import os

        if os.path.abspath(log_file) not in existing:
            fh = logging.FileHandler(log_file)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
    return logger
