"""Central registry of every ``DSOD_*`` environment knob.

Thirteen PRs accreted ~16 env knobs, read wherever they were born —
and twice (PR 3) a program-affecting one was forgotten from
``bench.py::_PROGRAM_ENV_VARS``, silently contaminating A/B baseline
keys.  This module is the single source of truth:

- every knob is declared ONCE here (name, default, whether it selects
  a different *compiled program*, one-line doc, where it is read);
- every read goes through :func:`read` — the only place in the
  codebase allowed to touch ``os.environ`` for a ``DSOD_`` name
  (``tools/dsodlint.py`` check ``env-coherence`` enforces both
  directions: an unregistered read fails lint, and the
  ``program_affecting`` rows must equal ``bench.py::_PROGRAM_ENV_VARS``
  exactly);
- the generated table in docs/PERFORMANCE.md ("Environment knobs") is
  rendered from this registry (:func:`markdown_table`), so the docs
  cannot drift from the code.

``program_affecting=True`` means: two runs with different values of
this var compile DIFFERENT XLA programs, so bench baselines must key
on it (the PR-3 contamination lesson).  Host-side knobs (paths,
process-pool method, fault injection) are False.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple, Optional


class EnvVar(NamedTuple):
    name: str
    default: Optional[str]    # value when unset (None = genuinely unset)
    program_affecting: bool   # selects a different compiled program
    doc: str                  # one line, rendered into PERFORMANCE.md
    read_at: str              # where the value is consumed


_ENTRIES = (
    EnvVar("DSOD_RESIZE_IMPL", None, True,
           "Decoder resample execution strategy A/B override "
           "(fast / convt / xla / pallas / pallas_dma); explicit "
           "model.resample_impl wins.",
           "models/layers.py"),
    EnvVar("DSOD_RESIZE_INTERLEAVE", None, True,
           "'stack' selects the historical stack+reshape upsample "
           "interleave (relayout-copy A/B arm; tools/hlo_guard.py).",
           "models/layers.py"),
    EnvVar("DSOD_STEM_IMPL", None, True,
           "'s2d' computes the ResNet stem as space-to-depth + 4x4 "
           "conv (same arithmetic, TPU-friendlier tiling).",
           "models/backbones/resnet.py"),
    EnvVar("DSOD_FLASH_BLOCK_Q", None, True,
           "Flash-attention Q block rows (on-hardware tuning; "
           "tools/bench_flash.py sweeps it).",
           "pallas/flash_attention.py"),
    EnvVar("DSOD_FLASH_BLOCK_KV", None, True,
           "Flash-attention KV block rows (paired with "
           "DSOD_FLASH_BLOCK_Q).",
           "pallas/flash_attention.py"),
    EnvVar("DSOD_DLF_VMEM_MB", None, True,
           "Scoped-VMEM ceiling override for the dynamic-filter "
           "kernel (MB; <=0 = compiler default).",
           "pallas/dynamic_filter.py"),
    EnvVar("DSOD_RESAMPLE_VMEM_MB", None, True,
           "Scoped-VMEM ceiling override for the fused-resample "
           "kernel (MB; <=0 = compiler default).",
           "pallas/fused_resample.py"),
    EnvVar("DSOD_CONV_VMEM_MB", None, True,
           "Scoped-VMEM ceiling override for the fused conv-stage "
           "kernels (MB; <=0 = compiler default).",
           "pallas/fused_conv.py"),
    EnvVar("DSOD_FAULTS", "", False,
           "Deterministic fault-injection plan for the chaos suites "
           "(resilience/inject.py spec syntax); empty = no faults.",
           "resilience/inject.py"),
    EnvVar("DSOD_NATIVE_LIB", None, False,
           "Path override for the native host-decode shared library "
           "(default: native/build/libdsod_host.so).",
           "data/native.py"),
    EnvVar("DSOD_DECODE_MP", "spawn", False,
           "multiprocessing start method for the decode process pool "
           "(spawn default: fork inherits held locks from a "
           "jax-initialized process).",
           "data/pipeline.py"),
    EnvVar("DSOD_NO_COMPILE_CACHE", None, False,
           "Any non-empty value disables the persistent XLA "
           "compilation cache setup.",
           "utils/platform.py"),
    EnvVar("DSOD_BENCH_BASELINE", None, False,
           "Path override for bench.py's baseline file (default: "
           "bench_baseline.json next to bench.py).",
           "bench.py"),
    EnvVar("DSOD_BENCH_HISTORY", None, False,
           "Path override for the append-only bench history JSONL "
           "(empty string disables; default: "
           "tools/bench_history.jsonl).",
           "bench.py"),
    EnvVar("DSOD_BISECT_EXPORT", None, False,
           "'1' makes tools/bisect_swin_eval.py stage scripts "
           "jax.export for TPU instead of executing (read inside the "
           "generated stage script).",
           "tools/bisect_swin_eval.py (generated stage)"),
    EnvVar("DSOD_T1_FAST", None, False,
           "Any non-empty value makes tools/t1.sh skip the non-gating "
           "smokes (read by the shell script, not Python).",
           "tools/t1.sh"),
)

REGISTRY: Dict[str, EnvVar] = {e.name: e for e in _ENTRIES}

# The rows bench.py::_PROGRAM_ENV_VARS must mirror exactly (dsodlint
# check env-coherence compares the two literals both ways).
PROGRAM_AFFECTING = tuple(e.name for e in _ENTRIES if e.program_affecting)


def spec(name: str) -> EnvVar:
    """The registry row for ``name``; loud KeyError for unregistered
    names — an unregistered knob is a bug, not a feature request."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a registered DSOD env var — add it to "
            "utils/envvars.py (and to bench.py::_PROGRAM_ENV_VARS if "
            "it selects a different compiled program)") from None


def read(name: str, env: Optional[dict] = None) -> Optional[str]:
    """THE one sanctioned ``os.environ`` read for ``DSOD_*`` knobs
    (every other read site fails ``tools/dsodlint.py`` env-coherence).
    Returns the raw string, or the registry default when unset.
    ``env`` overrides the source mapping (injectable for tests)."""
    e = spec(name)
    v = (os.environ if env is None else env).get(name)
    return e.default if v is None else v


def read_int(name: str, fallback: int, env: Optional[dict] = None) -> int:
    """Integer knob: ``fallback`` when unset or empty."""
    v = read(name, env=env)
    return int(v) if v else fallback


def markdown_table() -> str:
    """The docs/PERFORMANCE.md "Environment knobs" table body —
    regenerate with ``python -m distributed_sod_project_tpu.utils.envvars``."""
    lines = ["| Knob | Default | Program-affecting | Read at | What it does |",
             "|---|---|---|---|---|"]
    for e in _ENTRIES:
        default = "*(unset)*" if e.default is None else f"`{e.default!r}`"
        lines.append(
            f"| `{e.name}` | {default} | "
            f"{'yes' if e.program_affecting else 'no'} | "
            f"`{e.read_at}` | {e.doc} |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(markdown_table())
