"""Alert engine: declarative rules over the telemetry surface
(docs/OBSERVABILITY.md "Model health").

The metric families (utils/observability.py) make model health
*visible*; this module makes it *actionable* without a Prometheus
deployment in the loop: a small set of declarative rules evaluated
in-process over the same signals the /metrics endpoints render, with a
hysteretic firing state machine and a uniform surface (``/alerts``
JSON + ``dsod_alert_*`` families) on every front end — the trainer
sidecar, the single-engine server, and the fleet router.

Design constraints, in order:

- **Fake-clock deterministic.**  Every transition is a pure function of
  (observed value, injected clock) — the same discipline as the
  degraded-mode ladder (serve/admission.py), so the fire → hold →
  clear sequences are provable in tests without sleeps.
- **Hysteretic by construction.**  A rule must BREACH for ``for_s``
  before it fires and must stay CLEAR for ``clear_s`` before it
  resolves; in between it holds.  A monitor that flaps per scrape is
  worse than no monitor (every alert consumer debounces it again,
  differently).
- **Stable surface.**  ``prom_families`` renders one sample per rule
  UNCONDITIONALLY (0 when quiet) so the family inventory
  (tools/metrics_lint.py) cannot drift with alert activity.

Rule kinds:

- ``gt`` / ``lt`` — plain threshold on the signal's current value.
- ``z``  — EWMA z-score: the rule tracks an exponentially-weighted
  mean/variance of the signal and breaches when the standardized
  residual exceeds ``value`` (one-sided, high).  Warmup-gated: no
  breach before ``min_n`` observations, so the first samples cannot
  alarm against an unseeded baseline.

Rules are declared either programmatically (:class:`Rule`) or as a
compact colon DSL that survives ``--set`` tuple coercion (no commas):

    name:signal:kind:value[:for_s[:clear_s]]
    e.g.  drift_psi:quality_psi_max:gt:0.25:5:10
          grad_spike:grad_norm:z:6:0:60
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_KINDS = ("gt", "lt", "z")

# States of the per-rule machine.  "pending" = breached, serving its
# for_s dwell; "clearing" = stopped breaching, serving its clear_s
# dwell (still ACTIVE — the hold half of the hysteresis).
OK, PENDING, FIRING, CLEARING = "ok", "pending", "firing", "clearing"
ACTIVE_STATES = (FIRING, CLEARING)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative alert rule over a named scalar signal.

    ``hint`` tags what a firing rule should *mean* to an opt-in
    consumer — the train loop hands rules with ``hint="rollback"`` to
    the PR-1 resilience supervisor as a divergence (rollback-and-retry)
    when ``health_rollback_hint`` is on.
    """

    name: str
    signal: str
    kind: str = "gt"          # gt | lt | z
    value: float = 0.0        # threshold, or z-score bound for kind=z
    for_s: float = 0.0        # breach dwell before firing
    clear_s: float = 0.0      # clear dwell before resolving
    hint: str = ""            # e.g. "rollback" (opt-in consumer tag)
    ewma_alpha: float = 0.1   # kind=z: mean/var smoothing
    min_n: int = 8            # kind=z: observations before arming

    def __post_init__(self):
        if not self.name or not self.signal:
            raise ValueError(f"alert rule needs name and signal: {self!r}")
        if self.kind not in _KINDS:
            raise ValueError(
                f"alert rule {self.name!r}: kind must be one of "
                f"{_KINDS}, got {self.kind!r}")
        if self.for_s < 0 or self.clear_s < 0:
            raise ValueError(
                f"alert rule {self.name!r}: for_s/clear_s must be >= 0")
        if self.kind == "z" and self.value <= 0:
            raise ValueError(
                f"alert rule {self.name!r}: z rules need value > 0")

    @classmethod
    def parse(cls, spec: str, **kw) -> "Rule":
        """``name:signal:kind:value[:for_s[:clear_s]]`` → Rule.  Colon
        DSL on purpose: it survives the config system's comma-splitting
        tuple coercion, so custom rules ride ``--set`` cleanly."""
        parts = [p.strip() for p in str(spec).split(":")]
        if len(parts) < 4:
            raise ValueError(
                f"alert rule spec {spec!r} needs at least "
                "name:signal:kind:value")
        try:
            value = float(parts[3])
            for_s = float(parts[4]) if len(parts) > 4 else 0.0
            clear_s = float(parts[5]) if len(parts) > 5 else 0.0
        except ValueError as e:
            raise ValueError(
                f"alert rule spec {spec!r}: non-numeric field ({e})")
        if len(parts) > 6:
            raise ValueError(f"alert rule spec {spec!r}: too many fields")
        return cls(name=parts[0], signal=parts[1], kind=parts[2],
                   value=value, for_s=for_s, clear_s=clear_s, **kw)


def parse_rules(specs: Sequence[str]) -> List[Rule]:
    return [Rule.parse(s) for s in specs or ()]


class _RuleState:
    __slots__ = ("state", "since", "last_value", "last_z", "fired_total",
                 "detail", "ewma_mean", "ewma_var", "n")

    def __init__(self):
        self.state = OK
        self.since: Optional[float] = None
        self.last_value: Optional[float] = None
        self.last_z: Optional[float] = None
        self.fired_total = 0
        self.detail = ""
        self.ewma_mean = 0.0
        self.ewma_var = 0.0
        self.n = 0


class AlertEngine:
    """Evaluate a rule set against pushed signal values.

    Feed values with :meth:`feed` (one signal) or :meth:`evaluate`
    (a dict — the cadence point both stacks use: the train loop at its
    metric boundaries, the serve engine at its dispatch-loop observe
    point, throttled).  All clock reads go through the injected
    ``clock`` so the full fire → hold → clear ladder is provable with
    a fake clock.  ``on_fire(rule, state_dict)`` is invoked (outside
    the lock) on each ok/pending → firing transition;
    ``on_transition(rule, old_state, new_state, state_dict)`` on EVERY
    state change — the flight recorder (utils/flightrecorder.py) hangs
    its alert-transition event stream here so an incident timeline
    shows pending/clearing edges, not just firings.
    """

    def __init__(self, rules: Sequence[Rule], *, clock=time.monotonic,
                 on_fire: Optional[Callable] = None,
                 on_transition: Optional[Callable] = None):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.rules: Tuple[Rule, ...] = tuple(rules)
        self._clock = clock
        self._on_fire = on_fire
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._st: Dict[str, _RuleState] = {r.name: _RuleState()
                                           for r in rules}

    # -- evaluation ----------------------------------------------------

    def _breach(self, rule: Rule, st: _RuleState, value: float) -> bool:
        if rule.kind == "gt":
            return value > rule.value
        if rule.kind == "lt":
            return value < rule.value
        # kind == "z": score against the PRE-update EWMA baseline (the
        # value must not dilute the mean it is judged against), then
        # fold it in.  Warmup-gated on min_n.
        breach = False
        if st.n >= rule.min_n:
            sd = math.sqrt(max(st.ewma_var, 1e-12))
            st.last_z = (value - st.ewma_mean) / sd
            breach = st.last_z > rule.value
        a = rule.ewma_alpha
        if st.n == 0:
            st.ewma_mean = value
        else:
            delta = value - st.ewma_mean
            st.ewma_mean += a * delta
            st.ewma_var = (1.0 - a) * (st.ewma_var + a * delta * delta)
        st.n += 1
        return breach

    def feed(self, signal: str, value: float,
             now: Optional[float] = None, detail: str = "") -> None:
        """Advance every rule watching ``signal`` with one observation.
        ``detail`` (e.g. the nonfinite parameter group) is stored on
        breach and surfaced in /alerts and healthz reasons."""
        if value is None or not math.isfinite(float(value)):
            return  # a broken signal must not wedge or fire the machine
        value = float(value)
        now = self._clock() if now is None else now
        fired = []
        transitions = []
        with self._lock:
            for rule in self.rules:
                if rule.signal != signal:
                    continue
                st = self._st[rule.name]
                st.last_value = value
                breach = self._breach(rule, st, value)
                if breach and detail:
                    st.detail = detail
                old_state = st.state
                if self._advance(rule, st, breach, now):
                    fired.append((rule, self._state_dict(rule, st)))
                if st.state != old_state and \
                        self._on_transition is not None:
                    transitions.append((rule, old_state, st.state,
                                        self._state_dict(rule, st)))
        for rule, old, new, snap in transitions:
            self._on_transition(rule, old, new, snap)
        for rule, snap in fired:
            if self._on_fire is not None:
                self._on_fire(rule, snap)

    def evaluate(self, signals: Dict[str, float],
                 now: Optional[float] = None,
                 details: Optional[Dict[str, str]] = None) -> None:
        now = self._clock() if now is None else now
        details = details or {}
        for k, v in signals.items():
            self.feed(k, v, now=now, detail=details.get(k, ""))

    def _advance(self, rule: Rule, st: _RuleState, breach: bool,
                 now: float) -> bool:
        """One state-machine step; returns True on a fresh firing."""
        if st.state == OK:
            if breach:
                st.state, st.since = PENDING, now
                if rule.for_s <= 0:
                    return self._fire(st, now)
            return False
        if st.state == PENDING:
            if not breach:
                st.state, st.since = OK, None
                return False
            if now - st.since >= rule.for_s:
                return self._fire(st, now)
            return False
        if st.state == FIRING:
            if not breach:
                st.state, st.since = CLEARING, now
                if rule.clear_s <= 0:
                    st.state, st.since, st.detail = OK, None, ""
            return False
        # CLEARING: a re-breach returns to firing WITHOUT a fresh
        # fired_total tick (the alert never resolved); a full clear
        # dwell resolves it.
        if breach:
            st.state, st.since = FIRING, now
        elif now - st.since >= rule.clear_s:
            st.state, st.since, st.detail = OK, None, ""
        return False

    @staticmethod
    def _fire(st: _RuleState, now: float) -> bool:
        st.state, st.since = FIRING, now
        st.fired_total += 1
        return True

    # -- surfaces ------------------------------------------------------

    def active(self) -> List[str]:
        """Names of rules currently ACTIVE (firing or in their clear
        dwell) — what /healthz names in its degraded reasons."""
        with self._lock:
            return [r.name for r in self.rules
                    if self._st[r.name].state in ACTIVE_STATES]

    def active_reasons(self) -> List[str]:
        """``name(detail)`` strings for health surfaces."""
        with self._lock:
            out = []
            for r in self.rules:
                st = self._st[r.name]
                if st.state in ACTIVE_STATES:
                    out.append(f"{r.name}({st.detail})" if st.detail
                               else r.name)
            return out

    def _state_dict(self, rule: Rule, st: _RuleState) -> Dict:
        d = {
            "rule": rule.name,
            "signal": rule.signal,
            "kind": rule.kind,
            "value": rule.value,
            "for_s": rule.for_s,
            "clear_s": rule.clear_s,
            "state": st.state,
            "active": st.state in ACTIVE_STATES,
            "fired_total": st.fired_total,
            "last_value": st.last_value,
        }
        if rule.hint:
            d["hint"] = rule.hint
        if st.detail:
            d["detail"] = st.detail
        if rule.kind == "z":
            d["ewma_mean"] = round(st.ewma_mean, 6)
            d["last_z"] = (round(st.last_z, 3)
                           if st.last_z is not None else None)
        return d

    def snapshot(self) -> Dict:
        """The /alerts payload."""
        with self._lock:
            rules = [self._state_dict(r, self._st[r.name])
                     for r in self.rules]
        return {"active": [r["rule"] for r in rules if r["active"]],
                "rules": rules}

    def firing(self, hint: Optional[str] = None) -> List[Rule]:
        """Rules currently FIRING (not merely holding through their
        clear dwell), optionally filtered by hint tag — the rollback
        consumer reads this."""
        with self._lock:
            return [r for r in self.rules
                    if self._st[r.name].state == FIRING
                    and (hint is None or r.hint == hint)]

    def prom_families(self, labels: str = ""):
        """``dsod_alert_active`` / ``dsod_alert_fired_total`` /
        ``dsod_alert_value`` with one ``rule=`` sample per rule,
        rendered unconditionally so the family inventory is stable."""
        with self._lock:
            rows = [(r.name, self._st[r.name].state in ACTIVE_STATES,
                     self._st[r.name].fired_total,
                     self._st[r.name].last_value)
                    for r in self.rules]
        pre = f"{labels}," if labels else ""
        active, fired, value = [], [], []
        for name, act, n, v in rows:
            lbl = f'{pre}rule="{name}"'
            active.append('dsod_alert_active{%s} %d' % (lbl, 1 if act else 0))
            fired.append('dsod_alert_fired_total{%s} %d' % (lbl, n))
            value.append('dsod_alert_value{%s} %g'
                         % (lbl, v if v is not None else 0.0))
        return [("dsod_alert_active", "gauge", active),
                ("dsod_alert_fired_total", "counter", fired),
                ("dsod_alert_value", "gauge", value)]


def values_from_families(families, signals: Sequence[str]
                         ) -> Dict[str, float]:
    """Extract scalar signal values from a prom family list — the
    bridge that lets a rule watch ANY registered family.

    A signal spec is a family name (first sample wins) or
    ``family{k="v",...}`` (first sample whose label set CONTAINS every
    given pair).  Histogram families resolve through their ``_count``
    sample.  Missing signals are simply absent from the result (the
    engine skips them)."""
    out: Dict[str, float] = {}
    wanted = []
    for spec in signals:
        fam, _, label_part = spec.partition("{")
        labels = []
        if label_part:
            for frag in label_part.rstrip("}").split(","):
                frag = frag.strip()
                if frag:
                    labels.append(frag)
        wanted.append((spec, fam, labels))
    for name, _typ, samples in families:
        for spec, fam, labels in wanted:
            if spec in out or name != fam:
                continue
            for line in samples:
                head, _, rest = line.partition(" ")
                bare = head.partition("{")[0]
                # Plain families: the sample named exactly ``fam``.
                # Histograms: resolve through the ``_count`` sample.
                if bare not in (fam, fam + "_count"):
                    continue
                if labels:
                    lhead = head.partition("{")[2].rstrip("}")
                    if not all(lbl in lhead for lbl in labels):
                        continue
                try:
                    out[spec] = float(rest.split()[0])
                except (ValueError, IndexError):
                    continue
                break
    return out
