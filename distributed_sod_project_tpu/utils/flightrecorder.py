"""Black-box flight recorder: durable telemetry history + crash-safe
incident bundles (docs/OBSERVABILITY.md "Flight recorder & incidents").

PRs 9-11 built a live telemetry surface — spans, health signals,
alerts, SLO burn rates, the capacity ledger — but all of it lives in
process memory: a SIGKILLed replica, a wedged trainer, or a
watchdog-114 exit takes its evidence with it.  This module is the
missing durable layer, three pieces:

- :class:`SegmentRing` — a bounded on-disk ring of append-only JSONL
  segments.  Appends are one ``write()`` + ``flush()`` per record (a
  SIGKILLed process loses at most the record being written — the OS
  page cache survives process death), rotation is by segment size,
  retention by segment count, and :func:`read_records` is the
  torn-tail-tolerant reader: a record half-written at kill time is
  skipped, every COMPLETE record replays.  A restarted process always
  opens a FRESH segment — it never appends to a file whose tail may be
  torn.
- :class:`FlightRecorder` — a background thread sampling the process's
  :class:`~.observability.TelemetryRegistry` families (the same
  ``prom_families`` machinery /metrics renders) into compact
  ``{series: value}`` sample records, plus typed ``event`` records
  (alert transitions, SLO burn crossings, hot reloads, degraded-ladder
  moves, supervisor rollbacks, watchdog trips) pushed by the host
  stack.  Off by default; when off nothing is constructed and the
  /metrics surface is byte-identical.
- **Incident bundles** — on a trigger (alert firing, watchdog trip,
  SIGTERM, dispatch crash) the recorder snapshots the last
  ``bundle_window_s`` of the ring together with caller-registered live
  sections (/debug/traces worst-N, /alerts, /slo, the capacity
  snapshot, the resolved config) into ONE gzip-compressed JSON file
  under ``<dir>/incidents/``.  Triggers are debounced: a flapping
  alert cannot bundle-storm (suppressed triggers are counted and noted
  in the next bundle's meta).

``tools/incident.py`` is the offline consumer: it renders an incident
timeline (events overlaid on metric deltas around the trigger) and
diffs two time windows of any recorded family.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from .logging import get_logger

# A record larger than this cannot be appended (one poisoned section
# must not blow a segment ring sized in KB into GB).
MAX_RECORD_BYTES = 4 * 1024 * 1024

_SEGMENT_RE = re.compile(r"^seg-(\d{8})\.jsonl$")
_SLUG_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")


def flatten_families(families) -> Dict[str, float]:
    """Prometheus family list → compact ``{series: value}`` dict — the
    flight recorder's sample payload.

    Scalar families keep every sample under its full ``name{labels}``
    key; histogram families keep only their ``_count``/``_sum`` series
    (per-bucket lines would multiply the record size ~14x for data the
    offline diff never needs — counts and sums are what rates and
    means derive from)."""
    out: Dict[str, float] = {}
    for _name, typ, samples in families:
        for line in samples:
            head, _, rest = line.rpartition(" ")
            if not head:
                continue
            if typ == "histogram" and "_bucket{" in head:
                continue
            try:
                out[head] = float(rest)
            except ValueError:
                continue
    return out


def series_family(series: str) -> str:
    """A sample-record series key → its metric FAMILY name (labels
    stripped, histogram ``_count``/``_sum`` suffixes folded back) —
    what tools/metrics_lint.py checks against the inventory."""
    name = series.partition("{")[0]
    for suffix in ("_count", "_sum"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


class SegmentRing:
    """Bounded on-disk ring of append-only JSONL segments.

    One record per line; one ``write()`` + ``flush()`` per record so a
    SIGKILL can tear at most the line in flight (the reader skips it).
    Rotation: a segment past ``segment_bytes`` closes and a new one
    opens; retention: at most ``keep_segments`` segments survive,
    oldest deleted first.  Opening an existing directory CONTINUES the
    sequence with a fresh segment — the previous process's possibly-
    torn tail is never appended to.
    """

    def __init__(self, dir_: str, *, segment_bytes: int = 256 * 1024,
                 keep_segments: int = 16):
        if segment_bytes < 1024:
            raise ValueError(
                f"segment_bytes must be >= 1024, got {segment_bytes}")
        if keep_segments < 2:
            raise ValueError(
                f"keep_segments must be >= 2 (one rotating, one "
                f"history), got {keep_segments}")
        self.dir = str(dir_)
        self.segment_bytes = int(segment_bytes)
        self.keep_segments = int(keep_segments)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        existing = self._segment_seqs(self.dir)
        self._seq = (existing[-1] + 1) if existing else 0
        # Retention on OPEN too, not only on rotation: a crash-looping
        # writer that dies before filling one segment (and every
        # one-shot append_event) opens a fresh segment per run — prune
        # to keep-1 here so the bound holds across restarts, not just
        # within one process's rotations.
        for old in existing[: max(0, len(existing)
                                  - (self.keep_segments - 1))]:
            try:
                os.unlink(self._segment_path(self.dir, old))
            except OSError:
                pass
        self._f = None
        self._written = 0
        self.records_total = 0
        self.dropped_oversize = 0

    @staticmethod
    def _segment_seqs(dir_: str) -> List[int]:
        try:
            names = os.listdir(dir_)
        except OSError:
            return []
        seqs = []
        for n in names:
            m = _SEGMENT_RE.match(n)
            if m:
                seqs.append(int(m.group(1)))
        return sorted(seqs)

    @staticmethod
    def _segment_path(dir_: str, seq: int) -> str:
        return os.path.join(dir_, f"seg-{seq:08d}.jsonl")

    def _open_locked(self) -> None:
        self._f = open(self._segment_path(self.dir, self._seq), "a",
                       buffering=1)
        self._written = 0

    def _rotate_locked(self) -> None:
        if self._f is not None:
            self._f.close()
        # Retention BEFORE opening the successor: prune to keep-1 so
        # the count lands exactly at keep_segments after the open — a
        # SIGKILL between the two steps leaves keep-1, never keep+1
        # (the on-disk bound must hold at EVERY instant, not just
        # between rotations; the chaos test kills mid-rotation).
        seqs = self._segment_seqs(self.dir)
        for old in seqs[: max(0, len(seqs) - (self.keep_segments - 1))]:
            try:
                os.unlink(self._segment_path(self.dir, old))
            except OSError:
                pass
        self._seq += 1
        self._open_locked()

    def append(self, record: Dict) -> bool:
        """Append one record; returns False when it was dropped for
        size.  Crash-safe by construction: the line lands in the OS
        page cache in one write before this returns."""
        line = json.dumps(record, separators=(",", ":"),
                          default=str) + "\n"
        data = line.encode()
        if len(data) > MAX_RECORD_BYTES:
            with self._lock:
                self.dropped_oversize += 1
            return False
        with self._lock:
            if self._f is None:
                self._open_locked()
            elif self._written >= self.segment_bytes:
                self._rotate_locked()
            self._f.write(line)
            self._f.flush()
            self._written += len(data)
            self.records_total += 1
        return True

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def segments(self) -> List[str]:
        with self._lock:
            return [self._segment_path(self.dir, s)
                    for s in self._segment_seqs(self.dir)]


def read_records(dir_: str, since: Optional[float] = None,
                 until: Optional[float] = None) -> List[Dict]:
    """Replay a segment ring from disk, tolerating a torn tail.

    Reads every segment in sequence order; a line that is not complete
    JSON (the record a SIGKILL interrupted mid-write, or a truncated
    disk) is SKIPPED, never raised on — every complete record replays.
    ``since``/``until`` filter on the record's wall-clock ``t``."""
    out: List[Dict] = []
    for seq in SegmentRing._segment_seqs(dir_):
        path = SegmentRing._segment_path(dir_, seq)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail / corrupt line: skip, keep going
            if not isinstance(rec, dict):
                continue
            t = rec.get("t")
            if since is not None and (t is None or t < since):
                continue
            if until is not None and (t is None or t > until):
                continue
            out.append(rec)
    return out


def append_event(dir_: str, kind: str, keep_segments: int = 16,
                 **attrs) -> None:
    """One-shot event append into a ring directory WITHOUT a live
    recorder — the resilience supervisor notes rollbacks between
    fit() attempts this way (each attempt owns its own recorder; the
    rollback happens in the gap).  ``keep_segments`` must match the
    ring owner's retention (the open path prunes to it).  Never
    raises: a telemetry append must not turn a recovery into a
    crash."""
    try:
        ring = SegmentRing(dir_, keep_segments=keep_segments)
        ring.append(dict({"t": time.time(), "kind": "event",
                          "event": kind, "pid": os.getpid()}, **attrs))
        ring.close()
    except Exception:  # noqa: BLE001 — telemetry must not throw
        get_logger().exception("flightrecorder: append_event failed")


class FlightRecorder:
    """The black-box recorder: background sampler + event sink +
    debounced incident bundling over one :class:`SegmentRing`.

    ``families_fn()`` returns the prom family list to sample
    (``TelemetryRegistry.prom_families`` for the engine/trainer, the
    router-book families for the fleet).  ``sections`` maps a bundle
    section name to a zero-arg callable evaluated AT BUNDLE TIME
    (traces, alerts, slo, capacity, stats, resolved config); a section
    that raises is captured as its error string — one broken provider
    must not cost the bundle.  ``clock`` is injectable so the debounce
    ladder is fake-clock provable; record timestamps are WALL time
    (``time.time()``) so offline timelines line up across processes.
    """

    def __init__(self, dir_: str, families_fn: Optional[Callable] = None,
                 *, sample_s: float = 1.0,
                 segment_bytes: int = 256 * 1024, keep_segments: int = 16,
                 bundle_window_s: float = 300.0, debounce_s: float = 30.0,
                 sections: Optional[Dict[str, Callable]] = None,
                 meta: Optional[Dict] = None, clock=time.monotonic):
        if not dir_:
            raise ValueError(
                "flight recorder needs a directory (recorder_dir)")
        if sample_s <= 0:
            raise ValueError(
                f"recorder sample_s must be > 0, got {sample_s}")
        if bundle_window_s <= 0:
            raise ValueError(
                f"recorder bundle_window_s must be > 0, got "
                f"{bundle_window_s}")
        if debounce_s < 0:
            raise ValueError(
                f"recorder debounce_s must be >= 0, got {debounce_s}")
        self.dir = str(dir_)
        self.ring = SegmentRing(self.dir, segment_bytes=segment_bytes,
                                keep_segments=keep_segments)
        self.families_fn = families_fn
        self.sample_s = float(sample_s)
        self.bundle_window_s = float(bundle_window_s)
        self.debounce_s = float(debounce_s)
        self.sections = dict(sections or {})
        self.meta = dict(meta or {})
        self._clock = clock
        self._log = get_logger()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_bundle: Optional[float] = None
        self.samples_total = 0
        self.events_total = 0
        self.bundles_total = 0
        self.suppressed_total = 0
        self._suppressed_since_bundle = 0
        os.makedirs(self.incidents_dir, exist_ok=True)

    @property
    def incidents_dir(self) -> str:
        return os.path.join(self.dir, "incidents")

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "FlightRecorder":
        if self._thread is not None:
            return self
        self._stop.clear()
        self.event("recorder_start", **self.meta)
        if self.families_fn is not None:
            self._thread = threading.Thread(
                target=self._sample_loop, name="flight-recorder",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.event("recorder_stop")
        self.ring.close()

    def _sample_loop(self) -> None:
        # First sample immediately: a replica killed within one
        # interval of starting should still leave evidence.
        while True:
            self.sample()
            if self._stop.wait(self.sample_s):
                return

    # -- recording -----------------------------------------------------

    def sample(self) -> Optional[Dict]:
        """Take one telemetry sample now (the loop's body; also called
        synchronously right before a bundle so the incident is
        bracketed by fresh numbers)."""
        if self.families_fn is None:
            return None
        try:
            values = flatten_families(self.families_fn())
        except Exception:  # noqa: BLE001 — telemetry must not throw
            self._log.exception("flightrecorder: sample failed")
            return None
        rec = {"t": time.time(), "kind": "sample", "v": values}
        if self.ring.append(rec):
            with self._lock:
                self.samples_total += 1
        return rec

    def event(self, kind: str, **attrs) -> None:
        """Record one typed event (hot reload, degraded move, alert
        transition, ...).  Never raises."""
        try:
            rec = dict({"t": time.time(), "kind": "event",
                        "event": str(kind)}, **attrs)
            if self.ring.append(rec):
                with self._lock:
                    self.events_total += 1
        except Exception:  # noqa: BLE001 — telemetry must not throw
            self._log.exception("flightrecorder: event failed")

    def alert_transition(self, rule, old: str, new: str, state: Dict
                         ) -> None:
        """The AlertEngine ``on_transition`` hook: every transition is
        an event; a fresh FIRING additionally triggers an incident
        (debounced — a flapping rule cannot bundle-storm)."""
        self.event("alert_transition", rule=rule.name, old=old, new=new,
                   value=state.get("last_value"),
                   detail=state.get("detail", ""))
        if new == "firing":
            # Background: transitions fire from ingest/observe points
            # (the engine dispatch loop, the router's booking seam) —
            # the capture must not stall them.
            self.trigger(f"alert:{rule.name}",
                         detail=state.get("detail", ""),
                         background=True)

    # -- incident bundling ---------------------------------------------

    def trigger(self, reason: str, detail: str = "",
                background: bool = False) -> Optional[str]:
        """Snapshot an incident bundle; returns its path, or None when
        the trigger was debounced (or handed to the background
        writer).  Never raises — an incident capture failing must not
        worsen the incident.

        ``background=True`` is for callers ON A SERVING HOT PATH (the
        router's request-handler thread, the engine's dispatch loop):
        the debounce claim stays synchronous — a storm is still one
        bundle — but the expensive part (section evaluation may scrape
        replicas with 2 s timeouts; the ring read + gzip write are
        file I/O) moves to a daemon thread so a failing replica's
        incident capture never delays the very failover that handles
        it.  Exit paths (SIGTERM, watchdog, train crash) keep the
        default synchronous write — the process is about to die and
        must not race its own capture."""
        now = self._clock()
        with self._lock:
            if (self._last_bundle is not None
                    and now - self._last_bundle < self.debounce_s):
                self.suppressed_total += 1
                self._suppressed_since_bundle += 1
                suppressed = True
            else:
                self._last_bundle = now
                suppressed = False
        if suppressed:
            self.event("incident_suppressed", reason=reason)
            return None

        def write():
            try:
                return self._write_bundle(reason, detail)
            except Exception:  # noqa: BLE001 — capture must not throw
                self._log.exception("flightrecorder: bundle failed (%s)",
                                    reason)
                return None

        if background:
            threading.Thread(target=write, name="flight-bundle",
                             daemon=True).start()
            return None
        return write()

    def _write_bundle(self, reason: str, detail: str) -> str:
        # The incident event lands in the RING first (so a later
        # bundle, or the ring alone, still shows it), then a fresh
        # sample brackets the trigger.
        self.event("incident", reason=reason, detail=detail)
        self.sample()
        t_wall = time.time()
        sections = {}
        for name, fn in self.sections.items():
            try:
                sections[name] = fn()
            except Exception as e:  # noqa: BLE001 — capture all it can
                sections[name] = {"error": f"{type(e).__name__}: {e}"}
        with self._lock:
            suppressed = self._suppressed_since_bundle
            self._suppressed_since_bundle = 0
        bundle = {
            "meta": dict(self.meta, reason=reason, detail=detail,
                         t=t_wall, pid=os.getpid(),
                         host=socket.gethostname(),
                         window_s=self.bundle_window_s,
                         suppressed_since_last=suppressed),
            "records": read_records(self.dir,
                                    since=t_wall - self.bundle_window_s),
            "sections": sections,
        }
        slug = _SLUG_SAFE.sub("-", reason)[:48] or "incident"
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(t_wall))
        path = os.path.join(
            self.incidents_dir,
            f"incident-{stamp}-{int((t_wall % 1) * 1000):03d}-{slug}"
            ".json.gz")
        tmp = path + ".tmp"
        with gzip.open(tmp, "wt") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)  # atomic: a reader never sees half a bundle
        with self._lock:
            self.bundles_total += 1
        self._log.warning("flightrecorder: incident bundle %s (%s)",
                          path, reason)
        return path

    # -- surfaces ------------------------------------------------------

    def list_bundles(self) -> List[Dict]:
        out = []
        try:
            names = sorted(os.listdir(self.incidents_dir))
        except OSError:
            return out
        for n in names:
            if not n.endswith(".json.gz"):
                continue
            p = os.path.join(self.incidents_dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append({"file": n, "path": p, "bytes": st.st_size,
                        "mtime": st.st_mtime})
        return out

    def snapshot(self) -> Dict:
        """The /incidents payload for one process."""
        with self._lock:
            counts = {
                "samples_total": self.samples_total,
                "events_total": self.events_total,
                "bundles_total": self.bundles_total,
                "suppressed_total": self.suppressed_total,
            }
        return {
            "enabled": True,
            "dir": self.dir,
            "sample_s": self.sample_s,
            "segments": [os.path.basename(s)
                         for s in self.ring.segments()],
            "bundles": self.list_bundles(),
            **counts,
        }


def recorder_from_knobs(knobs, *, dir_default: str = "",
                        families_fn=None, sections=None, meta=None,
                        clock=time.monotonic) -> Optional[FlightRecorder]:
    """Config-knob bring-up shared by all three stacks (ServeConfig /
    FleetConfig / ExperimentConfig carry the same ``flight_recorder`` +
    ``recorder_*`` fields).  Returns None when the knob is off — the
    defaults-off byte-identity contract; raises the loud ValueError
    when it is on without a resolvable directory."""
    if not getattr(knobs, "flight_recorder", False):
        return None
    dir_ = getattr(knobs, "recorder_dir", "") or dir_default
    if not dir_:
        raise ValueError(
            "flight_recorder=true needs recorder_dir (no default "
            "directory in this context) — set recorder_dir to the "
            "on-disk ring location")
    return FlightRecorder(
        dir_, families_fn,
        sample_s=knobs.recorder_sample_s,
        segment_bytes=knobs.recorder_segment_kb * 1024,
        keep_segments=knobs.recorder_keep_segments,
        bundle_window_s=knobs.recorder_bundle_window_s,
        debounce_s=knobs.recorder_debounce_s,
        sections=sections, meta=meta, clock=clock)
