"""JAX platform selection shared by every entrypoint ([B:5] --device)."""

from __future__ import annotations


def select_platform(device: str | None) -> None:
    """Apply a ``--device {tpu,cpu}`` choice.  Call before the first
    backend touch.

    Uses ``jax.config.update`` only — never the ``JAX_PLATFORMS`` env
    var: with a PJRT plugin registered at interpreter startup (e.g. a
    remote-TPU tunnel), the env path forces an eager plugin dial that
    can hang the process, while the config path initializes only the
    requested backend.  ``tpu`` (and None) trust default discovery so
    the same flag works with libtpu, tunnel plugins, and bare CPU.
    """
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif device not in (None, "tpu"):
        raise ValueError(f"unknown --device {device!r}")


def maybe_enable_compilation_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache: the zoo's 320×320 programs take
    minutes to compile for TPU, and every CLI invocation is a fresh
    process — cache compiled executables on disk so only the first run
    of a (program, shape) pays.  Opt out with DSOD_NO_COMPILE_CACHE=1.

    Call AFTER the first backend touch (``jax.devices()``/``make_mesh``):
    gating is on the RESOLVED backend, not the ``--device`` flag, because
    ``--device`` unset can still land on CPU (tunnel down → fallback) and
    XLA:CPU's AOT cache entries pin host machine features, which can
    SIGILL when feature detection disagrees across processes (observed
    in-sandbox).  jax re-reads the config at each compile, so enabling
    post-init still covers every program the process compiles."""
    import os

    from . import envvars

    if envvars.read("DSOD_NO_COMPILE_CACHE"):
        return
    import jax

    if jax.default_backend() == "cpu":
        return
    cache = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
             or os.path.expanduser("~/.cache/dsod_xla"))
    try:
        os.makedirs(cache, exist_ok=True)
        # Thresholds first, the cache dir LAST: the dir update is the
        # switch that turns the cache on, so any failure before it
        # leaves the cache fully off and the warning below accurate.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", cache)
    except (OSError, AttributeError, ValueError) as e:
        # Unwritable cache dir, or an older jaxlib without these config
        # keys.  Best-effort, but never silent: cache-off must be
        # distinguishable from cache-on when debugging slow compiles.
        import logging

        logging.getLogger(__name__).warning(
            "persistent compilation cache disabled (%s: %s)",
            type(e).__name__, e)


def pin_cpu() -> None:
    """Pin jax to the CPU backend (config path, NOT the JAX_PLATFORMS
    env var — with the remote-TPU PJRT plugin registered by
    sitecustomize, the env path eagerly dials the tunnel and hangs when
    it is down).  Shared by the offline tools (eval_preds,
    inspect_ckpt, export_model); a no-op when a backend is already up."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already initialized
        pass
