"""JAX platform selection shared by every entrypoint ([B:5] --device)."""

from __future__ import annotations


def select_platform(device: str | None) -> None:
    """Apply a ``--device {tpu,cpu}`` choice.  Call before the first
    backend touch.

    Uses ``jax.config.update`` only — never the ``JAX_PLATFORMS`` env
    var: with a PJRT plugin registered at interpreter startup (e.g. a
    remote-TPU tunnel), the env path forces an eager plugin dial that
    can hang the process, while the config path initializes only the
    requested backend.  ``tpu`` (and None) trust default discovery so
    the same flag works with libtpu, tunnel plugins, and bare CPU.
    """
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif device not in (None, "tpu"):
        raise ValueError(f"unknown --device {device!r}")
