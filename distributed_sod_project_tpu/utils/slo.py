"""SLO objectives, error-budget accounting, and burn-rate alerting
(docs/OBSERVABILITY.md "Capacity & SLO").

The serving stack's accounting identity (served + shed + expired +
errors == submitted) says what HAPPENED; this module says whether that
is *acceptable* — the SRE error-budget formulation, in-process, with no
Prometheus deployment in the loop:

- **Objectives** are declarative: availability ("goal of requests
  terminate ok") or latency-threshold ("goal of requests terminate ok
  within latency_ms"), scoped ``all`` / ``model=X`` / ``tenant=Y``, over
  a sliding ``window_s``.  Colon DSL (comma-free, so ``--set`` tuple
  coercion passes specs through — the alert-rule discipline):

      name:scope:kind:goal:window_s[:latency_ms]
      e.g.  avail:model=minet:availability:0.999:3600
            fast:tenant=pro:latency:0.95:3600:250

- **Events come from the terminal book.**  The router feeds one event
  per counted submission at the same points it books the terminal
  outcome (serve/router.py), the single-engine server feeds at its
  ``run_predict`` return, the trainer feeds one event per completed
  step (goodput: kind=latency over step time).  Client-fault terminals
  (``rejected`` / ``bad_request`` — malformed input that no replica
  count could have served) are EXCLUDED, the SRE 4xx convention; every
  other terminal is good or bad exactly once, so ``good + bad``
  reconciles against the book.

- **Multi-window burn rate.**  ``burn(w) = (bad_w / total_w) / (1 -
  goal)`` — 1.0 burns the budget exactly at the window's end.  The
  alert signal is ``min(burn(fast), burn(slow))`` with ``fast =
  window_s / 12`` (the 1h→5m SRE convention): the fast window detects
  quickly, the slow window confirms, and taking the min IS the
  two-window AND.  Budget remaining over the slow window is
  ``1 - bad / (total * (1 - goal))`` (negative = over budget).

- **Alerting is the alert engine.**  Each objective contributes a
  burn-rate rule (``slo_<name>_burn``) and a budget-exhaustion rule
  (``slo_<name>_budget``) to a private :class:`AlertEngine`
  (utils/alerts.py) — hysteretic, fake-clock provable — whose active
  rules degrade /healthz exactly like the quality/numerics alerts.

Everything is clock-injectable and bucket-quantized (``window_s /
n_buckets`` resolution), so the full fire → hold → clear ladder is
provable in tests without sleeps.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .alerts import AlertEngine, Rule

_KINDS = ("availability", "latency")
_SCOPES = ("all", "model", "tenant")

# Fast window = slow window / 12 (1h → 5m): quick detection, confirmed
# by the full window before the min-of-windows signal breaches.
FAST_FRACTION = 1.0 / 12.0

# Terminal outcomes excluded from SLO events: the client's fault, not
# the service's (the SRE 4xx convention) — a flood of malformed uploads
# must not burn the availability budget.
EXCLUDED_OUTCOMES = frozenset(("rejected", "bad_request"))

# Terminal outcomes that count GOOD: a served response, whether a
# backend forward ("ok") or the router cache answering for one
# ("cache_hit" — serve/cache.py).  Everything else counted is bad.
GOOD_OUTCOMES = frozenset(("ok", "cache_hit"))


@dataclasses.dataclass(frozen=True)
class SLObjective:
    """One declarative SLO: ``goal`` of matching events must be good
    over any sliding ``window_s``."""

    name: str
    scope_kind: str = "all"       # all | model | tenant
    scope_value: str = ""
    kind: str = "availability"    # availability | latency
    goal: float = 0.999
    window_s: float = 3600.0
    latency_ms: float = 0.0       # kind=latency: the good/bad line

    def __post_init__(self):
        if not self.name:
            raise ValueError(f"SLO objective needs a name: {self!r}")
        if self.scope_kind not in _SCOPES:
            raise ValueError(
                f"SLO {self.name!r}: scope must be all|model=X|tenant=X, "
                f"got {self.scope_kind!r}")
        if self.scope_kind != "all" and not self.scope_value:
            raise ValueError(
                f"SLO {self.name!r}: scope {self.scope_kind}= needs a "
                "value")
        if self.kind not in _KINDS:
            raise ValueError(
                f"SLO {self.name!r}: kind must be one of {_KINDS}, got "
                f"{self.kind!r}")
        if not 0.0 < self.goal < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: goal must be in (0, 1), got "
                f"{self.goal}")
        if self.window_s <= 0:
            raise ValueError(
                f"SLO {self.name!r}: window_s must be > 0, got "
                f"{self.window_s}")
        if self.kind == "latency" and self.latency_ms <= 0:
            raise ValueError(
                f"SLO {self.name!r}: kind=latency needs latency_ms > 0")

    @classmethod
    def parse(cls, spec: str) -> "SLObjective":
        """``name:scope:kind:goal:window_s[:latency_ms]`` → objective.
        ``scope`` is ``all`` or ``model=X`` / ``tenant=X``."""
        parts = [p.strip() for p in str(spec).split(":")]
        if len(parts) < 5:
            raise ValueError(
                f"SLO spec {spec!r} needs at least "
                "name:scope:kind:goal:window_s")
        if len(parts) > 6:
            raise ValueError(f"SLO spec {spec!r}: too many fields")
        scope = parts[1]
        if scope == "all":
            skind, sval = "all", ""
        else:
            skind, sep, sval = scope.partition("=")
            if not sep:
                raise ValueError(
                    f"SLO spec {spec!r}: scope must be all, model=X, or "
                    f"tenant=X, got {scope!r}")
        try:
            goal = float(parts[3])
            window_s = float(parts[4])
            latency_ms = float(parts[5]) if len(parts) > 5 else 0.0
        except ValueError as e:
            raise ValueError(f"SLO spec {spec!r}: non-numeric field ({e})")
        return cls(name=parts[0], scope_kind=skind, scope_value=sval,
                   kind=parts[2], goal=goal, window_s=window_s,
                   latency_ms=latency_ms)

    def matches(self, model: Optional[str], tenant: Optional[str]) -> bool:
        if self.scope_kind == "all":
            return True
        if self.scope_kind == "model":
            return model == self.scope_value
        return tenant == self.scope_value

    @property
    def scope(self) -> str:
        return ("all" if self.scope_kind == "all"
                else f"{self.scope_kind}={self.scope_value}")


def parse_slos(specs: Sequence[str]) -> List[SLObjective]:
    objs = [SLObjective.parse(s) for s in specs or ()]
    names = [o.name for o in objs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate SLO objective names in {names}")
    return objs


class _WindowCounts:
    """Good/bad counts over a sliding window, quantized into
    ``n_buckets`` time buckets (sum-over-suffix gives any horizon up to
    the window).  Not thread-safe — the tracker's lock covers it."""

    def __init__(self, window_s: float, n_buckets: int = 60):
        self._width = float(window_s) / int(n_buckets)
        self._n = int(n_buckets)
        self._buckets: Dict[int, List[float]] = {}  # idx → [good, bad]

    def add(self, good: float, bad: float, now: float) -> None:
        idx = int(now / self._width)
        b = self._buckets.get(idx)
        if b is None:
            b = self._buckets[idx] = [0.0, 0.0]
            # Prune anything older than the full window (bounded size).
            floor = idx - self._n
            for k in [k for k in self._buckets if k <= floor]:
                del self._buckets[k]
        b[0] += good
        b[1] += bad

    def totals(self, horizon_s: float, now: float) -> Tuple[float, float]:
        """(good, bad) over the trailing ``horizon_s``.  Bucket
        quantization: a bucket counts while ANY of it overlaps the
        horizon."""
        lo = int((now - horizon_s) / self._width)
        hi = int(now / self._width)
        good = bad = 0.0
        for idx, (g, b) in self._buckets.items():
            if lo <= idx <= hi:
                good += g
                bad += b
        return good, bad


class _ObjState:
    __slots__ = ("window", "good_total", "bad_total")

    def __init__(self, window: _WindowCounts):
        self.window = window
        self.good_total = 0.0
        self.bad_total = 0.0


class SLOTracker:
    """Error-budget accounting over a set of objectives, plus the
    burn-rate/budget alert rules.  One per process front end (router,
    single-engine server, trainer sidecar); thread-safe."""

    def __init__(self, objectives: Sequence[SLObjective], *,
                 burn_threshold: float = 10.0,
                 alert_for_s: float = 5.0, alert_clear_s: float = 60.0,
                 clock=time.monotonic, n_buckets: int = 60,
                 on_transition=None):
        objectives = list(objectives)
        if not objectives:
            raise ValueError("SLOTracker needs at least one objective")
        if burn_threshold <= 0:
            raise ValueError(
                f"slo_burn_threshold must be > 0, got {burn_threshold}")
        self.objectives: Tuple[SLObjective, ...] = tuple(objectives)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        self._st: Dict[str, _ObjState] = {
            o.name: _ObjState(_WindowCounts(o.window_s, n_buckets))
            for o in objectives}
        rules = []
        for o in objectives:
            rules.append(Rule(
                f"slo_{o.name}_burn", f"slo_burn:{o.name}", "gt",
                self.burn_threshold, for_s=alert_for_s,
                clear_s=alert_clear_s, hint="slo"))
            rules.append(Rule(
                f"slo_{o.name}_budget", f"slo_budget:{o.name}", "lt",
                0.0, for_s=alert_for_s, clear_s=alert_clear_s,
                hint="slo"))
        self.alerts = AlertEngine(rules, clock=clock,
                                  on_transition=on_transition)
        self._next_eval = 0.0

    # -- ingest --------------------------------------------------------

    def observe(self, ok: bool, latency_ms: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None, n: int = 1,
                now: Optional[float] = None) -> None:
        """One terminal event (``n`` identical events — the trainer
        feeds a k-step chunk as one call).  The caller has already
        excluded client-fault terminals (:func:`observe_outcome` does
        both)."""
        now = self._clock() if now is None else now
        with self._lock:
            for o in self.objectives:
                if not o.matches(model, tenant):
                    continue
                good = bool(ok)
                if good and o.kind == "latency":
                    good = (latency_ms is not None
                            and latency_ms <= o.latency_ms)
                st = self._st[o.name]
                if good:
                    st.good_total += n
                    st.window.add(n, 0.0, now)
                else:
                    st.bad_total += n
                    st.window.add(0.0, n, now)
        self._maybe_evaluate(now)

    def observe_outcome(self, outcome: str, latency_ms: float,
                        model: Optional[str] = None,
                        tenant: Optional[str] = None,
                        now: Optional[float] = None) -> None:
        """Feed one terminal-book outcome string (router/server form):
        client-fault terminals are excluded, served terminals (``ok``,
        ``cache_hit``) are good, everything else is bad."""
        if outcome in EXCLUDED_OUTCOMES:
            return
        self.observe(outcome in GOOD_OUTCOMES, latency_ms=latency_ms,
                     model=model, tenant=tenant, now=now)

    # -- evaluation ----------------------------------------------------

    def _burns(self, o: SLObjective, st: _ObjState, now: float
               ) -> Dict[str, float]:
        out = {}
        for win, horizon in (("fast", o.window_s * FAST_FRACTION),
                             ("slow", o.window_s)):
            good, bad = st.window.totals(horizon, now)
            total = good + bad
            out[win] = ((bad / total) / (1.0 - o.goal)) if total else 0.0
        return out

    def _budget_remaining(self, o: SLObjective, st: _ObjState,
                          now: float) -> float:
        good, bad = st.window.totals(o.window_s, now)
        total = good + bad
        if not total:
            return 1.0
        allowed = total * (1.0 - o.goal)
        return 1.0 - bad / allowed if allowed > 0 else 1.0

    def signals(self, now: Optional[float] = None) -> Dict[str, float]:
        """The alert-engine inputs: per objective, the min-of-windows
        burn rate and the slow-window budget remaining."""
        now = self._clock() if now is None else now
        with self._lock:
            out = {}
            for o in self.objectives:
                st = self._st[o.name]
                burns = self._burns(o, st, now)
                out[f"slo_burn:{o.name}"] = min(burns["fast"],
                                                burns["slow"])
                out[f"slo_budget:{o.name}"] = \
                    self._budget_remaining(o, st, now)
            return out

    def evaluate(self, now: Optional[float] = None) -> None:
        """Advance every rule with the current window state.  Called on
        ingest (throttled ~1 Hz), and by the periodic observe points /
        scrape paths so burn decay CLEARS alerts even with no traffic."""
        now = self._clock() if now is None else now
        self.alerts.evaluate(self.signals(now), now=now)

    def _maybe_evaluate(self, now: float) -> None:
        with self._lock:
            due = now >= self._next_eval
            if due:
                self._next_eval = now + 1.0
        if due:
            self.evaluate(now)

    def active_reasons(self) -> List[str]:
        """Active SLO alerts for the /healthz degraded verdict (the
        scrape itself advances the machine so exhausted-then-recovered
        budgets clear without traffic)."""
        self._maybe_evaluate(self._clock())
        return self.alerts.active_reasons()

    # -- surfaces ------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict:
        """The /slo payload."""
        now = self._clock() if now is None else now
        self._maybe_evaluate(now)
        with self._lock:
            objs = []
            for o in self.objectives:
                st = self._st[o.name]
                good, bad = st.window.totals(o.window_s, now)
                burns = self._burns(o, st, now)
                entry = {
                    "name": o.name,
                    "scope": o.scope,
                    "kind": o.kind,
                    "goal": o.goal,
                    "window_s": o.window_s,
                    "good": good,
                    "bad": bad,
                    "good_total": st.good_total,
                    "bad_total": st.bad_total,
                    "budget_remaining": round(
                        self._budget_remaining(o, st, now), 6),
                    "burn_rate": {k: round(v, 4)
                                  for k, v in burns.items()},
                }
                if o.kind == "latency":
                    entry["latency_ms"] = o.latency_ms
                objs.append(entry)
        active = self.alerts.active()
        return {"objectives": objs, "active": active,
                "burn_threshold": self.burn_threshold}

    def prom_families(self, labels: str = ""):
        """``dsod_slo_*`` families, one ``slo=``-labeled sample per
        objective (scope rides as its own label), rendered
        unconditionally so the inventory is stable while the tracker
        exists.  The alert engine renders its own ``dsod_alert_*``
        families — register both providers."""
        now = self._clock()
        self._maybe_evaluate(now)
        pre = f"{labels}," if labels else ""
        with self._lock:
            rows = []
            for o in self.objectives:
                st = self._st[o.name]
                rows.append((o, st.good_total, st.bad_total,
                             self._budget_remaining(o, st, now),
                             self._burns(o, st, now)))

        def lbl(o):
            return f'{pre}slo="{o.name}",scope="{o.scope}"'

        target, good, bad, budget, burn = [], [], [], [], []
        for o, g, b, rem, burns in rows:
            target.append('dsod_slo_target{%s} %g' % (lbl(o), o.goal))
            good.append('dsod_slo_good_total{%s} %g' % (lbl(o), g))
            bad.append('dsod_slo_bad_total{%s} %g' % (lbl(o), b))
            budget.append('dsod_slo_budget_remaining{%s} %g'
                          % (lbl(o), rem))
            for win in ("fast", "slow"):
                burn.append('dsod_slo_burn_rate{%s,window="%s"} %g'
                            % (lbl(o), win, burns[win]))
        return [("dsod_slo_target", "gauge", target),
                ("dsod_slo_good_total", "counter", good),
                ("dsod_slo_bad_total", "counter", bad),
                ("dsod_slo_budget_remaining", "gauge", budget),
                ("dsod_slo_burn_rate", "gauge", burn)]


def build_tracker(specs: Sequence[str], *, burn_threshold: float,
                  alert_for_s: float, alert_clear_s: float,
                  clock=time.monotonic,
                  on_transition=None) -> Optional[SLOTracker]:
    """Config-knob bring-up: None when ``specs`` is empty (the
    defaults-off byte-identity contract).  ``on_transition`` rides
    through to the private AlertEngine — the flight recorder's SLO
    burn-crossing event stream."""
    objs = parse_slos(specs)
    if not objs:
        return None
    return SLOTracker(objs, burn_threshold=burn_threshold,
                      alert_for_s=alert_for_s,
                      alert_clear_s=alert_clear_s, clock=clock,
                      on_transition=on_transition)
