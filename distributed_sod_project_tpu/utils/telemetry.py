"""Opt-in trainer telemetry sidecar (docs/OBSERVABILITY.md).

Until now a running fit() exposed nothing at runtime except log lines
and a fixed-step ``profile_window`` — debugging "why is step time
noisy on host 3" meant killing the run.  The sidecar is the serving
stack's introspection surface, grafted onto training:

- ``GET /metrics``  — Prometheus text: PipelineStats (host data
  plane), StepTimer (windowed step time / throughput), device memory,
  the MetricWriter backend, all rendered through the SAME
  ``TelemetryRegistry`` + ``prom_families`` machinery the serve
  endpoints use (one exposition code path for both stacks).
- ``GET /healthz``  — fed by the PR-1 step watchdog's OWN heartbeat
  (``seconds_since_beat``): 200 while chunks complete, 503 once the
  watchdog fired (on its default policy the process exits 114 anyway;
  tests run with an ``on_stall`` observer).
- ``GET /debug/traces`` — the train loop's sampled chunk span
  timelines (utils/tracing.py).
- ``GET /debug/profile?seconds=N`` — arm ``jax.profiler`` ON DEMAND
  for an N-second window instead of only at a pre-configured step:
  the handler blocks for the window (the HTTP server is threaded;
  /metrics stays live) and answers with the dump directory.

Opt-in and additive: ``telemetry_port=-1`` (the default) starts no
thread and binds no socket; the train loop's behavior is untouched
either way (the sidecar only ever READS the objects the loop already
maintains).  Stdlib HTTP only — the training image gains no
dependency, and the port file publish reuses the serving stack's
atomic ``publish_port``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from .logging import get_logger
from .observability import TelemetryRegistry
from .tracing import Tracer

# Profile windows any longer would mostly measure the requester's
# patience; jax.profiler dumps also grow linearly with the window.
MAX_PROFILE_SECONDS = 120.0


def trainer_prom_families(*, data_stats, timer, batch_size: int,
                          writer_backend: str = "noop",
                          step_fn: Optional[Callable[[], int]] = None,
                          tracer: Optional[Tracer] = None,
                          device_memory: bool = True):
    """The trainer's /metrics families.  ONE function builds them — the
    sidecar renders it live, tools/metrics_lint.py renders it
    synthetically — so the inventory a lint checks and the surface a
    run exposes cannot drift.

    Families are emitted UNCONDITIONALLY (zero-valued when idle / on
    platforms without ``memory_stats``) so the family inventory is
    stable across runs and platforms.
    """
    fams = list(data_stats.prom_families())
    snap = timer.snapshot()
    step = int(step_fn()) if step_fn is not None else 0
    mean_ms = snap["mean_step_ms"]
    imgs = (batch_size / (mean_ms / 1000.0)) if mean_ms > 0 else 0.0
    gauges = [
        ("dsod_train_step", step),
        ("dsod_train_step_time_ms", mean_ms),
        ("dsod_train_imgs_per_sec", round(imgs, 3)),
    ]
    counters = [("dsod_train_chunks_total", snap["ticks"])]
    if tracer is not None:
        counters.append(("dsod_train_traces_total",
                         tracer.completed_total))
    for name, v in gauges:
        fams.append((name, "gauge", [f"{name} {v:g}"]))
    for name, v in counters:
        fams.append((name, "counter", [f"{name} {v:g}"]))
    # Device memory: the two stable keys every jax memory_stats()
    # implementation reports (TPU/GPU); 0 where the platform has none
    # (CPU) so the family set does not depend on the platform.
    in_use, peak = [], []
    devices = []
    if device_memory:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — no backend: render zeros
            devices = []
    for d in devices:
        try:
            ms = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — platform without the API
            ms = {}
        lbl = f'device="{d.id}"'
        in_use.append('dsod_train_device_bytes_in_use{%s} %d'
                      % (lbl, int(ms.get("bytes_in_use", 0))))
        peak.append('dsod_train_device_peak_bytes_in_use{%s} %d'
                    % (lbl, int(ms.get("peak_bytes_in_use", 0))))
    if not devices:
        in_use = ['dsod_train_device_bytes_in_use{device="0"} 0']
        peak = ['dsod_train_device_peak_bytes_in_use{device="0"} 0']
    fams.append(("dsod_train_device_bytes_in_use", "gauge", in_use))
    fams.append(("dsod_train_device_peak_bytes_in_use", "gauge", peak))
    # Which scalar backend is actually writing (the MetricWriter
    # clu-missing fallback is visible here, not just in one log line).
    fams.append(("dsod_train_metric_writer_info", "gauge", [
        'dsod_train_metric_writer_info{backend="%s"} 1' % writer_backend]))
    return fams


class TrainerTelemetry:
    """The sidecar server.  Construct with live references, ``start()``
    after the watchdog exists, ``stop()`` in the train loop's finally.

    ``registry`` is the :class:`TelemetryRegistry` to render at
    /metrics; ``watchdog`` (may be None = not armed) feeds /healthz;
    ``tracer`` backs /debug/traces; ``profile_dir`` roots the
    on-demand profiler dumps.
    """

    def __init__(self, registry: TelemetryRegistry, *,
                 host: str = "127.0.0.1", port: int = 0,
                 port_file: Optional[str] = None, watchdog=None,
                 tracer: Optional[Tracer] = None,
                 profile_dir: Optional[str] = None, alerts=None,
                 slo=None, recorder=None):
        self.registry = registry
        self.watchdog = watchdog
        self.tracer = tracer
        self.alerts = alerts  # utils/alerts.AlertEngine | None
        self.slo = slo        # utils/slo.SLOTracker | None
        self.recorder = recorder  # utils/flightrecorder.FlightRecorder
        self.profile_dir = profile_dir or "."
        self._host = host
        self._port = int(port)
        self._port_file = port_file
        self._srv = None
        self._thread: Optional[threading.Thread] = None
        self._profile_lock = threading.Lock()
        self._log = get_logger()

    # -- lifecycle -----------------------------------------------------

    @property
    def bound_port(self) -> Optional[int]:
        return self._srv.server_address[1] if self._srv else None

    def start(self) -> "TrainerTelemetry":
        if self._srv is not None:
            return self
        # Imported here, not at module top: the handler plumbing and
        # the atomic port-file publish are the serving stack's — one
        # implementation of each — but a fit() without telemetry must
        # not pay the serve imports.
        from ..serve.server import (JsonHTTPHandler, ThreadingHTTPServer,
                                    publish_port)

        telemetry = self

        class _Handler(JsonHTTPHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                telemetry._handle_get(self)

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = _Server((self._host, self._port), _Handler)
        publish_port(self._port_file, self._srv.server_address[1])
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="trainer-telemetry",
            daemon=True)
        self._thread.start()
        self._log.info(
            "telemetry: sidecar listening on http://%s:%d "
            "(/metrics /healthz /debug/traces /debug/profile)",
            self._host, self._srv.server_address[1])
        return self

    def stop(self) -> None:
        if self._srv is None:
            return
        self._srv.shutdown()
        self._srv.server_close()
        self._srv = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- request handling ----------------------------------------------

    def _handle_get(self, handler) -> None:
        import urllib.parse

        split = urllib.parse.urlsplit(handler.path)
        path = split.path
        if path == "/metrics":
            handler._send(200, self.registry.render().encode(),
                          "text/plain; version=0.0.4")
        elif path == "/healthz":
            code, body = self._health()
            handler._send_json(code, body)
        elif path == "/debug/traces":
            from ..serve.server import _query_int

            n = _query_int(split.query, "n", 50)
            if self.tracer is None:
                handler._send_json(200, {"sample": 0.0, "traces": [],
                                         "worst": {}})
            else:
                handler._send_json(200, self.tracer.snapshot(n))
        elif path == "/alerts":
            # Numerics + SLO rule states merged (disjoint names).
            snap = {"active": [], "rules": []}
            for eng in (self.alerts,
                        self.slo.alerts if self.slo is not None
                        else None):
                if eng is not None:
                    s = eng.snapshot()
                    snap["active"] += s["active"]
                    snap["rules"] += s["rules"]
            handler._send_json(200, snap)
        elif path == "/slo":
            # Goodput error-budget accounting (utils/slo.py; the
            # trainer's events are completed steps).
            handler._send_json(200, self.slo.snapshot()
                               if self.slo is not None
                               else {"objectives": [], "active": []})
        elif path == "/incidents":
            # Flight-recorder state (utils/flightrecorder.py): segment
            # ring + incident bundles on disk.
            handler._send_json(200, self.recorder.snapshot()
                               if self.recorder is not None
                               else {"enabled": False})
        elif path == "/debug/profile":
            self._handle_profile(handler, split.query)
        else:
            handler._send_json(404, {"error": f"no route {path}"})

    def _health(self):
        # Active model-health alerts DEGRADE the verdict (200 with the
        # rules named — the run lives, the model may not) and never
        # mask the watchdog's 503 (a wedged dispatch outranks a
        # quality worry).  SLO goodput alerts join the same list.
        active = self.alerts.active_reasons() if self.alerts else []
        if self.slo is not None:
            active = active + self.slo.active_reasons()
        wd = self.watchdog
        if wd is None:
            # No watchdog armed: the sidecar answering at all proves
            # the process lives; say so honestly instead of inventing
            # a liveness signal the loop is not feeding.
            body = {"status": "ok", "watchdog": "off"}
            if active:
                body.update(status="degraded", alerts=active)
            return 200, body
        if wd.fired:
            return 503, {"status": "stalled", "watchdog": "fired",
                         "last_step": wd.last_step}
        age = wd.seconds_since_beat()
        body = {"status": "ok",
                "last_beat_s": round(age, 3) if age is not None else None,
                "last_step": wd.last_step}
        if active:
            body.update(status="degraded", alerts=active)
        return 200, body

    def _handle_profile(self, handler, query: str) -> None:
        import urllib.parse

        q = urllib.parse.parse_qs(query)
        try:
            seconds = float((q.get("seconds") or ["2"])[0])
        except ValueError:
            handler._send_json(400, {"error": "seconds must be a number"})
            return
        if not 0 < seconds <= MAX_PROFILE_SECONDS:
            handler._send_json(400, {
                "error": f"seconds must be in (0, {MAX_PROFILE_SECONDS:g}]"
            })
            return
        if not self._profile_lock.acquire(blocking=False):
            handler._send_json(409, {"error": "a profile window is "
                                              "already armed"})
            return
        try:
            import jax

            logdir = os.path.join(
                self.profile_dir, f"profile_ondemand_{int(time.time())}")
            try:
                jax.profiler.start_trace(logdir)
            except Exception as e:  # noqa: BLE001 — e.g. profiler busy
                handler._send_json(409, {
                    "error": f"profiler unavailable: {e}"})
                return
            # Block THIS handler thread for the window (the server is
            # threaded — /metrics and /healthz stay live meanwhile),
            # then answer with the dump path: the caller knows the
            # trace is complete the moment the response lands.
            stop_err = None
            try:
                time.sleep(seconds)
            finally:
                # stop_trace ALWAYS runs (and its own failure must not
                # escape): a started-but-never-stopped trace wedges
                # jax's profiler for the life of the process — every
                # later window (on-demand or profile_window) would 409.
                try:
                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    stop_err = e
            if stop_err is not None:
                self._log.warning("telemetry: profiler stop failed: %s",
                                  stop_err)
                handler._send_json(500, {
                    "error": f"profiler stop failed: {stop_err}"})
                return
            self._log.info("telemetry: on-demand profile (%.1fs) "
                           "written to %s", seconds, logdir)
            handler._send_json(200, {"logdir": logdir,
                                     "seconds": seconds})
        finally:
            self._profile_lock.release()


def build_trainer_registry(cfg, *, data_stats, timer, writer,
                           step_fn=None, tracer=None, health=None,
                           alerts=None, capacity=None,
                           slo=None) -> TelemetryRegistry:
    """The trainer's full :class:`TelemetryRegistry` — one construction
    shared by the sidecar (which serves it at /metrics) and the flight
    recorder (which samples it onto disk), so a fit() with only the
    recorder armed records exactly the families a sidecar would have
    exposed."""
    registry = TelemetryRegistry().register(
        "trainer", lambda labels="": trainer_prom_families(
            data_stats=data_stats, timer=timer,
            batch_size=cfg.global_batch_size,
            writer_backend=writer.backend, step_fn=step_fn,
            tracer=tracer))
    if health is not None:
        registry.register("health", health.prom_families)
    if alerts is not None:
        registry.register("alerts", alerts.prom_families)
    if capacity is not None:
        registry.register("capacity", capacity.prom_families)
    if slo is not None:
        registry.register("slo", slo.prom_families)
        registry.register("slo_alerts", slo.alerts.prom_families)
    return registry


def build_trainer_telemetry(cfg, *, data_stats, timer, writer,
                            watchdog=None, tracer=None, workdir=None,
                            step_fn=None, port: Optional[int] = None,
                            port_file: Optional[str] = None,
                            health=None, alerts=None, capacity=None,
                            slo=None, registry=None,
                            recorder=None) -> Optional[TrainerTelemetry]:
    """fit()'s one-call bring-up: None when telemetry is off
    (``cfg.telemetry_port < 0`` and no explicit ``port``).

    ``health`` (utils/modelhealth.HealthMonitor) and ``alerts``
    (utils/alerts.AlertEngine) — both optional — add the
    ``dsod_health_*`` / ``dsod_alert_*`` families to /metrics and back
    the /alerts endpoint + the degraded /healthz verdict.  ``capacity``
    (utils/capacity.CapacityLedger) adds the ``dsod_capacity_*``
    families; ``slo`` (utils/slo.SLOTracker) adds ``dsod_slo_*``, the
    /slo endpoint, and its burn/budget alerts to the degraded verdict
    (docs/OBSERVABILITY.md "Capacity & SLO").  ``registry`` (a
    pre-built :func:`build_trainer_registry`) lets the flight recorder
    and the sidecar share one instance; ``recorder`` backs
    /incidents."""
    eff_port = cfg.telemetry_port if port is None else port
    if eff_port is None or eff_port < 0:
        return None
    if registry is None:
        registry = build_trainer_registry(
            cfg, data_stats=data_stats, timer=timer, writer=writer,
            step_fn=step_fn, tracer=tracer, health=health,
            alerts=alerts, capacity=capacity, slo=slo)
    return TrainerTelemetry(
        registry, host="127.0.0.1", port=eff_port, port_file=port_file,
        watchdog=watchdog, tracer=tracer, profile_dir=workdir,
        alerts=alerts, slo=slo, recorder=recorder).start()
