"""Input sanitation — host-side failure detection (SURVEY.md §5).

The functional training step cannot race, but it CAN be fed garbage:
wrong dataset layout, masks that aren't binary, NaNs from a corrupt
decode, images that skipped normalization.  ``validate_batch`` runs
once on the first batch of a training run (cheap, host-side) and fails
loudly with the actual problem instead of letting a silent bad input
become an unexplained divergence thousands of steps later.

``periodic_validate`` extends the net past the first batch: a
non-finite-only re-check every ``cfg.data.validate_every`` batches on
the host side of the prefetch queue (before the H2D copy, so it costs
no device sync).  Default off — the once-only behavior stands.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator

import numpy as np


def validate_batch(batch: Dict, image_size, use_depth: bool = False) -> None:
    """Raise ValueError describing the first problem found."""
    def arr(k):
        v = batch.get(k)
        if v is None:
            raise ValueError(f"batch is missing {k!r}")
        return np.asarray(v)

    img = arr("image")
    mask = arr("mask")
    h, w = int(image_size[0]), int(image_size[1])
    if img.ndim != 4 or img.shape[1:] != (h, w, 3):
        raise ValueError(
            f"image shape {img.shape} != [B,{h},{w},3] — dataset layout "
            "or image_size mismatch")
    if mask.shape != img.shape[:3] + (1,):
        raise ValueError(f"mask shape {mask.shape} does not pair with "
                         f"image {img.shape}")
    if not np.all(np.isfinite(img)):
        raise ValueError("non-finite pixels in image batch (corrupt "
                         "decode or broken normalization)")
    mmin, mmax = float(mask.min()), float(mask.max())
    if mmin < 0.0 or mmax > 1.0:
        raise ValueError(f"mask range [{mmin}, {mmax}] outside [0,1] — "
                         "masks must be binarized probabilities")
    uniq = np.unique(mask)
    if np.any((uniq > 0.0) & (uniq < 1.0)):
        # Bilinear-resized masks must have been re-binarized upstream.
        raise ValueError("mask is not binary (found values strictly "
                         "between 0 and 1) — check the mask transform")
    if float(mask.mean()) in (0.0, 1.0):
        import warnings

        warnings.warn("every mask pixel in the first batch is "
                      f"{int(mask.mean())} — wrong mask directory?",
                      stacklevel=2)
    if use_depth:
        depth = arr("depth")
        if depth.shape != img.shape[:3] + (1,):
            raise ValueError(f"depth shape {depth.shape} does not pair "
                             f"with image {img.shape}")
        if not np.all(np.isfinite(depth)):
            raise ValueError("non-finite values in depth batch")


def check_finite_batch(batch: Dict, batch_index: int = -1) -> None:
    """The cheap subset of ``validate_batch``: raise on non-finite
    values in the float arrays (corrupt decode / poisoned cache).
    Shape/range invariants can't drift mid-run; finiteness can."""
    for k in ("image", "mask", "depth"):
        v = batch.get(k)
        if v is not None and not np.all(np.isfinite(np.asarray(v))):
            raise ValueError(
                f"non-finite values in {k!r} at batch {batch_index} — "
                "mid-run data corruption (decoder bug, bitrot, or a "
                "poisoned cache); see docs/RESILIENCE.md")


def periodic_validate(batches: Iterable[Dict], every: int,
                      start_index: int = 0) -> Iterator[Dict]:
    """Yield ``batches``, re-running :func:`check_finite_batch` on every
    ``every``-th one (host-side, pre-transfer).  ``every<=0`` passes
    the iterator through untouched."""
    if every <= 0:
        yield from batches
        return
    for i, batch in enumerate(batches, start=start_index):
        if i % every == 0:
            check_finite_batch(batch, batch_index=i)
        yield batch
