"""Throughput accounting — the imgs/sec counter the governing metric
(BASELINE.json:2, images/sec/chip) is computed from."""

from __future__ import annotations

import time
from collections import deque


class StepTimer:
    """Sliding-window step timer; excludes the first ``warmup`` ticks so
    XLA compilation time never pollutes throughput numbers.

    **What a tick means (honesty contract).**  ``tick(steps=n)`` marks
    an observation that ``n`` more train steps COMPLETED on device, and
    the window stores per-step time = interval / n.  Under device-side
    step chunking (``train.steps_per_dispatch=k``) the loop calls
    ``tick(steps=k)`` immediately after the per-chunk metric readback —
    a ``jax.device_get`` that cannot return before the chunk's
    dependency chain executed — so the clock advances with completed
    device work, never with host dispatches, and ``imgs_per_sec`` stays
    honest under async run-ahead.  The historical k=1 path keeps its
    per-dispatch tick: there the log-cadence metric fetch bounds host
    run-ahead, so the window mean still converges to the completion
    rate (documented dispatch-rate semantics, preserved so recorded
    baselines replay identically).

    ``on_tick`` (optional) is invoked once per ``tick()`` — the train
    loop feeds the step watchdog's heartbeat through it
    (resilience/watchdog.py), so "a chunk completed" and "the
    throughput clock advanced" are, by construction, the same event.
    """

    def __init__(self, window: int = 50, warmup: int = 2, on_tick=None):
        self.window = window
        self.warmup = warmup
        self.on_tick = on_tick
        self._times: deque = deque(maxlen=window)
        self._last = None
        self._count = 0

    def tick(self, steps: int = 1) -> None:
        """Record that ``steps`` more train steps completed since the
        previous tick (1 = the per-step path; k = one scanned chunk)."""
        now = time.perf_counter()
        self._count += 1
        if self._last is not None and self._count > self.warmup:
            self._times.append((now - self._last) / max(int(steps), 1))
        self._last = now
        if self.on_tick is not None:
            self.on_tick()

    @property
    def mean_step_time(self) -> float:
        """Mean PER-STEP time over the window (chunk intervals are
        divided by their step count before entering the window)."""
        if not self._times:
            return float("nan")
        return sum(self._times) / len(self._times)

    def images_per_sec(self, batch_size: int) -> float:
        """Throughput from the windowed per-step mean; ``batch_size``
        is the per-STEP global batch (not the chunk total)."""
        st = self.mean_step_time
        return batch_size / st if st == st and st > 0 else float("nan")

    @property
    def ticks(self) -> int:
        """Completed-work observations so far (chunks, not steps)."""
        return self._count

    def snapshot(self) -> dict:
        """Telemetry-sidecar view: windowed per-step time and tick
        count (NaN-free — 0.0 before the window fills, so Prometheus
        samples stay parseable)."""
        st = self.mean_step_time
        return {"ticks": self._count,
                "mean_step_ms": round(st * 1000.0, 3) if st == st else 0.0}
