"""Throughput accounting — the imgs/sec counter the governing metric
(BASELINE.json:2, images/sec/chip) is computed from."""

from __future__ import annotations

import time
from collections import deque


class StepTimer:
    """Sliding-window step timer; excludes the first ``warmup`` steps so
    XLA compilation time never pollutes throughput numbers.

    ``on_tick`` (optional) is invoked once per ``tick()`` — the train
    loop feeds the step watchdog's heartbeat through it
    (resilience/watchdog.py), so "a step completed" and "the throughput
    clock advanced" are, by construction, the same event.
    """

    def __init__(self, window: int = 50, warmup: int = 2, on_tick=None):
        self.window = window
        self.warmup = warmup
        self.on_tick = on_tick
        self._times: deque = deque(maxlen=window)
        self._last = None
        self._count = 0

    def tick(self) -> None:
        now = time.perf_counter()
        self._count += 1
        if self._last is not None and self._count > self.warmup:
            self._times.append(now - self._last)
        self._last = now
        if self.on_tick is not None:
            self.on_tick()

    @property
    def mean_step_time(self) -> float:
        if not self._times:
            return float("nan")
        return sum(self._times) / len(self._times)

    def images_per_sec(self, batch_size: int) -> float:
        st = self.mean_step_time
        return batch_size / st if st == st and st > 0 else float("nan")
